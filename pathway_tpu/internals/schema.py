"""Class-based schemas (parity: reference ``python/pathway/internals/schema.py``)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Mapping, Optional, Type

from pathway_tpu.internals import dtype as dt


@dataclass(frozen=True)
class ColumnDefinition:
    primary_key: bool = False
    default_value: Any = ...  # ... means no default
    dtype: Optional[dt.DType] = None
    name: Optional[str] = None

    @property
    def has_default(self) -> bool:
        return self.default_value is not ...


def column_definition(
    *,
    primary_key: bool = False,
    default_value: Any = ...,
    dtype: Any = None,
    name: str | None = None,
) -> Any:
    """Declare per-column properties inside a Schema class body."""
    return ColumnDefinition(
        primary_key=primary_key,
        default_value=default_value,
        dtype=dt.wrap(dtype) if dtype is not None else None,
        name=name,
    )


@dataclass(frozen=True)
class ColumnSchema:
    name: str
    dtype: dt.DType
    primary_key: bool = False
    default_value: Any = ...

    @property
    def has_default(self) -> bool:
        return self.default_value is not ...


class SchemaMetaclass(type):
    _columns: Dict[str, ColumnSchema]

    def __init__(cls, name: str, bases: tuple, namespace: dict, **kwargs: Any) -> None:
        super().__init__(name, bases, namespace)
        columns: Dict[str, ColumnSchema] = {}
        for base in bases:
            columns.update(getattr(base, "_columns", {}))
        annotations = namespace.get("__annotations__", {})
        if any(isinstance(h, str) for h in annotations.values()):
            # postponed evaluation (`from __future__ import annotations`) leaves string
            # hints; resolve them with the stdlib resolver
            import typing

            try:
                hints = typing.get_type_hints(cls)
                annotations = {k: hints.get(k, v) for k, v in annotations.items()}
            except Exception:
                pass  # unresolvable forward refs fall through as raw strings
        for col_name, hint in annotations.items():
            if col_name.startswith("_"):
                continue
            definition = namespace.get(col_name)
            if isinstance(definition, ColumnDefinition):
                out_name = definition.name or col_name
                columns[out_name] = ColumnSchema(
                    name=out_name,
                    dtype=definition.dtype or dt.wrap(hint),
                    primary_key=definition.primary_key,
                    default_value=definition.default_value,
                )
            else:
                columns[col_name] = ColumnSchema(name=col_name, dtype=dt.wrap(hint))
        cls._columns = columns

    def columns(cls) -> Dict[str, ColumnSchema]:
        return dict(cls._columns)

    def column_names(cls) -> list[str]:
        return list(cls._columns)

    def primary_key_columns(cls) -> list[str] | None:
        pkeys = [c.name for c in cls._columns.values() if c.primary_key]
        return pkeys or None

    def typehints(cls) -> Dict[str, Any]:
        return {n: c.dtype.typehint for n, c in cls._columns.items()}

    def dtypes(cls) -> Dict[str, dt.DType]:
        return {n: c.dtype for n, c in cls._columns.items()}

    def default_values(cls) -> Dict[str, Any]:
        return {n: c.default_value for n, c in cls._columns.items() if c.has_default}

    def __or__(cls, other: "SchemaMetaclass") -> "SchemaMetaclass":
        columns = dict(cls._columns)
        for name, col in other._columns.items():
            if name in columns and columns[name].dtype != col.dtype:
                raise TypeError(f"column {name!r} has conflicting dtypes in schema union")
            columns[name] = col
        return schema_from_columns(columns, name=f"{cls.__name__}|{other.__name__}")

    def with_types(cls, **kwargs: Any) -> "SchemaMetaclass":
        columns = dict(cls._columns)
        for name, hint in kwargs.items():
            if name not in columns:
                raise ValueError(f"unknown column {name!r}")
            old = columns[name]
            columns[name] = ColumnSchema(name, dt.wrap(hint), old.primary_key, old.default_value)
        return schema_from_columns(columns, name=cls.__name__)

    def without(cls, *names: str) -> "SchemaMetaclass":
        columns = {n: c for n, c in cls._columns.items() if n not in names}
        return schema_from_columns(columns, name=cls.__name__)

    def update_types(cls, **kwargs: Any) -> "SchemaMetaclass":
        return cls.with_types(**kwargs)

    def __repr__(cls) -> str:
        cols = ", ".join(f"{n}: {c.dtype!r}" for n, c in cls._columns.items())
        return f"<Schema {cls.__name__}({cols})>"


class Schema(metaclass=SchemaMetaclass):
    """Subclass with annotations to declare a table schema::

        class InputSchema(pw.Schema):
            name: str
            age: int
    """


def schema_from_columns(
    columns: Mapping[str, ColumnSchema], name: str = "Schema"
) -> SchemaMetaclass:
    cls = SchemaMetaclass(name, (Schema,), {})
    cls._columns = dict(columns)
    return cls


def schema_from_types(_name: str = "Schema", **kwargs: Any) -> SchemaMetaclass:
    """Build a schema from ``column=type`` kwargs (reference ``schema_from_types``)."""
    columns = {n: ColumnSchema(n, dt.wrap(t)) for n, t in kwargs.items()}
    return schema_from_columns(columns, name=_name)


def schema_from_dict(
    columns: Mapping[str, Any], *, name: str = "Schema"
) -> SchemaMetaclass:
    out: Dict[str, ColumnSchema] = {}
    for col_name, spec in columns.items():
        if isinstance(spec, dict):
            out[col_name] = ColumnSchema(
                name=col_name,
                dtype=dt.wrap(spec.get("dtype", Any)),
                primary_key=spec.get("primary_key", False),
                default_value=spec.get("default_value", ...),
            )
        else:
            out[col_name] = ColumnSchema(name=col_name, dtype=dt.wrap(spec))
    return schema_from_columns(out, name=name)


def schema_builder(
    columns: Mapping[str, ColumnDefinition | Any],
    *,
    name: str = "Schema",
    properties: Any = None,
) -> SchemaMetaclass:
    out: Dict[str, ColumnSchema] = {}
    for col_name, definition in columns.items():
        if isinstance(definition, ColumnDefinition):
            out_name = definition.name or col_name
            out[out_name] = ColumnSchema(
                name=out_name,
                dtype=definition.dtype or dt.ANY,
                primary_key=definition.primary_key,
                default_value=definition.default_value,
            )
        else:
            out[col_name] = ColumnSchema(name=col_name, dtype=dt.wrap(definition))
    return schema_from_columns(out, name=name)


def schema_from_pandas(df: Any, *, id_from: list[str] | None = None, name: str = "Schema") -> SchemaMetaclass:
    import numpy as np

    columns: Dict[str, ColumnSchema] = {}
    for col in df.columns:
        np_dtype = df[col].dtype
        if np_dtype == np.int64:
            hint: Any = int
        elif np_dtype == np.float64:
            hint = float
        elif np_dtype == np.bool_:
            hint = bool
        elif str(np_dtype).startswith("datetime64"):
            hint = dt.DATE_TIME_NAIVE
        else:
            sample = df[col].dropna()
            hint = type(sample.iloc[0]) if len(sample) else Any
        columns[str(col)] = ColumnSchema(
            name=str(col), dtype=dt.wrap(hint), primary_key=bool(id_from and col in id_from)
        )
    return schema_from_columns(columns, name=name)


def schema_from_csv(
    path: str,
    *,
    name: str = "Schema",
    properties: Any = None,
    delimiter: str = ",",
    comment_character: str | None = None,
    quote: str = '"',
    double_quote_escapes: bool = True,
    num_parsed_rows: int | None = None,
) -> SchemaMetaclass:
    """Infer a schema from a CSV file's header + sampled rows (reference ``schema_from_csv``)."""
    import csv as _csv

    from pathway_tpu.internals import dtype as dt

    with open(path, newline="") as f:
        reader = _csv.reader(f, delimiter=delimiter, quotechar=quote)
        rows = []
        header: list[str] | None = None
        for i, rec in enumerate(reader):
            if comment_character and rec and rec[0].startswith(comment_character):
                continue
            if header is None:
                header = rec
                continue
            rows.append(rec)
            if num_parsed_rows is not None and len(rows) >= num_parsed_rows:
                break
    assert header is not None, "empty csv"

    def infer(values: list[str]) -> dt.DType:
        non_empty = [v for v in values if v != ""]
        if not non_empty:
            return dt.STR

        def all_parse(cast: Any) -> bool:
            for v in non_empty:
                try:
                    cast(v)
                except ValueError:
                    return False
            return True

        if all_parse(int):
            return dt.INT
        if all_parse(float):
            return dt.FLOAT
        if all(v in ("True", "False", "true", "false") for v in non_empty):
            return dt.BOOL
        return dt.STR

    columns = {
        h: ColumnSchema(h, infer([r[i] if i < len(r) else "" for r in rows]))
        for i, h in enumerate(header)
    }
    return schema_from_columns(columns, name=name)


def is_subschema(sub: SchemaMetaclass, sup: SchemaMetaclass) -> bool:
    sup_cols = sup.columns()
    for name, col in sub.columns().items():
        if name not in sup_cols:
            return False
        if not dt.dtype_issubclass(col.dtype, sup_cols[name].dtype):
            return False
    return True
