"""Incremental aggregation reducers.

Parity: reference ``src/engine/reduce.rs`` (``enum Reducer``, semigroup vs full-recompute
impls) + ``python/pathway/internals/reducers.py``. Semigroup reducers (count/sum) update in
O(1) on insert AND retract; non-subtractable reducers (min/max/unique/tuple/...) keep a
per-group multiset and recompute on change. Dense sum aggregations over large batches use
jax segment-sum kernels (see ``pathway_tpu.ops.segment``).
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Callable, Iterable

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr


class Reducer:
    """Descriptor of an aggregation; the engine keeps ONE columnar state per reducer
    leaf (``make_state``), holding every group's accumulation in slot-indexed arrays —
    the reference's per-group reducer impls (``reduce.rs:41,56``) flattened into
    struct-of-arrays so a whole commit updates in vectorized segment kernels."""

    name = "reducer"
    semigroup = False  # True when retract is O(1) (subtractable)
    n_args = 1

    def make(self) -> "Accumulator":
        raise NotImplementedError

    def make_state(self) -> "ColumnarState":
        return _ObjectState(self)

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return dt.ANY

    def __call__(self, *args: Any, **kwargs: Any) -> expr.ReducerExpression:
        return expr.ReducerExpression(self, *args, **kwargs)


class ColumnarState:
    """Slot-indexed accumulator storage for one reducer leaf across ALL groups.

    ``update`` applies one commit's rows: ``slots[i]`` is row i's group slot,
    ``uniq_slots``/``inverse`` the batch's dense segmentation (``inverse[i]`` indexes
    ``uniq_slots``), ``diffs`` the +1/-1 multiplicities. ``key_lo`` carries the group
    keys' low bits so float segment sums can ride the mesh exchange
    (``ops/segment.py``)."""

    def ensure(self, capacity: int) -> None:
        raise NotImplementedError

    def reset(self, slots: np.ndarray) -> None:
        """Recycled slots start fresh (a new group reused a dead group's slot)."""
        raise NotImplementedError

    # -- elastic membership handoff (parallel/membership.py) -----------------
    #
    # Every ColumnarState is slot-parallel arrays plus plain scalars, so the
    # per-group handoff is generic: gather the moved groups' slots on the
    # donor, scatter them into freshly upserted slots on the new owner.

    def reshard_take(self, slots: np.ndarray) -> dict:
        """Gather the given group slots' accumulator state (donor side)."""
        arrays: dict = {}
        scalars: dict = {}
        for name, value in vars(self).items():
            if isinstance(value, np.ndarray):
                arrays[name] = value[slots] if len(value) else value[:0]
            elif isinstance(value, (bool, int, float, str, type(None))):
                scalars[name] = value
            # anything else (e.g. _ObjectState.reducer) is graph config,
            # reconstructed identically on the importing rank
        return {"arrays": arrays, "scalars": scalars}

    def reshard_put(self, slots: np.ndarray, blob: dict) -> None:
        """Scatter taken accumulator state into this state's slots (importer
        side; the slots were freshly upserted for the moved group keys)."""
        if len(slots):
            self.ensure(int(slots.max()) + 1)
        for name, vals in blob["arrays"].items():
            cur = getattr(self, name, None)
            if cur is None or not len(slots) or len(vals) != len(slots):
                continue
            if cur.dtype != vals.dtype:
                # adopt the donor's dtype (a fresh _SumState starts int64
                # until its first insert locks the real dtype)
                if cur.dtype == object or vals.dtype == object:
                    cur = cur.astype(object)
                else:
                    cur = cur.astype(np.promote_types(cur.dtype, vals.dtype))
                setattr(self, name, cur)
            cur[slots] = vals
        for name, v in blob["scalars"].items():
            # scalar flags merge sticky (dtype_locked: locked on either side
            # stays locked); config scalars are equal on both sides anyway
            setattr(self, name, getattr(self, name, None) or v)

    def update(
        self,
        slots: np.ndarray,
        uniq_slots: np.ndarray,
        inverse: np.ndarray,
        arrays: list[np.ndarray],
        diffs: np.ndarray,
        cnt_delta: np.ndarray,
        counts_after: np.ndarray,
        key_lo: np.ndarray | None = None,
    ) -> None:
        raise NotImplementedError

    def values(self, slots: np.ndarray) -> np.ndarray:
        """Current aggregate per requested slot (vectorized gather)."""
        raise NotImplementedError


def _grow(arr: np.ndarray, capacity: int, fill: Any = 0) -> np.ndarray:
    if len(arr) >= capacity:
        return arr
    out = np.empty(max(capacity, 2 * len(arr), 16), dtype=arr.dtype)
    out[: len(arr)] = arr
    out[len(arr) :] = fill
    return out


class _CountState(ColumnarState):
    def __init__(self) -> None:
        self.vals = np.zeros(0, dtype=np.int64)

    def ensure(self, capacity: int) -> None:
        self.vals = _grow(self.vals, capacity)

    def reset(self, slots: np.ndarray) -> None:
        self.vals[slots] = 0

    def update(self, slots, uniq_slots, inverse, arrays, diffs, cnt_delta, counts_after, key_lo=None) -> None:
        self.vals[uniq_slots] += cnt_delta

    def values(self, slots: np.ndarray) -> np.ndarray:
        return self.vals[slots]


class _SumState(ColumnarState):
    """Typed segment-summed totals; object/exotic dtypes fall back to a Python pass.

    ``zero_on_empty``: emptied groups snap back to exact 0 (float drift guard), the
    _SumAcc semantics."""

    def __init__(self, zero_on_empty: bool) -> None:
        self.vals: np.ndarray = np.zeros(0, dtype=np.int64)
        self.dtype_locked = False
        self.zero_on_empty = zero_on_empty

    def ensure(self, capacity: int) -> None:
        self.vals = _grow(self.vals, capacity)

    def reset(self, slots: np.ndarray) -> None:
        self.vals[slots] = None if self.vals.dtype == object else 0

    def _lock_dtype(self, incoming: np.ndarray) -> None:
        if self.dtype_locked:
            if incoming.dtype != self.vals.dtype and incoming.dtype != object:
                promoted = np.promote_types(self.vals.dtype, incoming.dtype)
                if promoted != self.vals.dtype:
                    self.vals = self.vals.astype(promoted)
            return
        self.dtype_locked = True
        if incoming.dtype == object or incoming.dtype.kind not in "bif":
            self.vals = self.vals.astype(object)
            self.vals[:] = None  # None = untouched; first insert assigns directly
        elif incoming.dtype.kind == "f":
            self.vals = self.vals.astype(incoming.dtype)

    def update(self, slots, uniq_slots, inverse, arrays, diffs, cnt_delta, counts_after, key_lo=None) -> None:
        vals = np.asarray(arrays[0])
        self._lock_dtype(vals)
        from pathway_tpu.ops.segment import segment_sum

        if self.vals.dtype == object or vals.dtype == object or vals.dtype.kind not in "bif":
            if self.vals.dtype != object:
                self.vals = self.vals.astype(object)
            for i in range(len(vals)):
                s = slots[i]
                contrib = vals[i]
                cur = self.vals[s]
                if diffs[i] > 0:
                    self.vals[s] = contrib if cur is None else cur + contrib
                else:
                    self.vals[s] = cur - contrib
        else:
            weights = diffs if vals.dtype.kind != "f" else diffs.astype(vals.dtype)
            sums = segment_sum(vals * weights, inverse, len(uniq_slots), key_lo=key_lo)
            self.vals[uniq_slots] += sums.astype(self.vals.dtype, copy=False)
        if self.zero_on_empty:
            emptied = uniq_slots[counts_after == 0]
            if len(emptied):
                # emptied groups snap to the pristine state (float-drift guard)
                self.vals[emptied] = None if self.vals.dtype == object else 0

    def values(self, slots: np.ndarray) -> np.ndarray:
        return self.vals[slots]


class _AvgState(_SumState):
    """sum/count; counts mirror the group's signed row count."""

    def __init__(self) -> None:
        super().__init__(zero_on_empty=False)
        self.counts = np.zeros(0, dtype=np.int64)

    def ensure(self, capacity: int) -> None:
        super().ensure(capacity)
        self.counts = _grow(self.counts, capacity)

    def reset(self, slots: np.ndarray) -> None:
        super().reset(slots)
        self.counts[slots] = 0

    def update(self, slots, uniq_slots, inverse, arrays, diffs, cnt_delta, counts_after, key_lo=None) -> None:
        super().update(slots, uniq_slots, inverse, arrays, diffs, cnt_delta, counts_after, key_lo)
        self.counts[uniq_slots] += cnt_delta

    def values(self, slots: np.ndarray) -> np.ndarray:
        sums = self.vals[slots]
        counts = self.counts[slots]
        if sums.dtype == object:
            out = np.empty(len(slots), dtype=object)
            for i in range(len(slots)):
                out[i] = sums[i] / counts[i] if counts[i] else None
            return out
        safe = np.where(counts == 0, 1, counts)
        out = sums / safe
        if (counts == 0).any():
            out = out.astype(object)
            out[counts == 0] = None
        return out


class _ObjectState(ColumnarState):
    """Generic fallback: one Accumulator object per group slot (the recompute-style
    reducers: min/max/unique/tuple/...)."""

    def __init__(self, reducer: "Reducer") -> None:
        self.reducer = reducer
        self.accs = np.empty(0, dtype=object)

    def ensure(self, capacity: int) -> None:
        if len(self.accs) >= capacity:
            return
        old = self.accs
        self.accs = np.empty(max(capacity, 2 * len(old), 16), dtype=object)
        self.accs[: len(old)] = old

    def reset(self, slots: np.ndarray) -> None:
        for s in slots.tolist():
            self.accs[s] = None

    def update(self, slots, uniq_slots, inverse, arrays, diffs, cnt_delta, counts_after, key_lo=None) -> None:
        from pathway_tpu.ops.segment import segment_slices

        order, starts, ends = segment_slices(inverse, len(uniq_slots))
        any_retract = bool(np.any(diffs < 0))
        for j, s in enumerate(uniq_slots.tolist()):
            rows = order[starts[j] : ends[j]]
            if len(rows) == 0:
                continue
            acc = self.accs[s]
            if acc is None:
                acc = self.accs[s] = self.reducer.make()
            if not any_retract:
                acc.insert_many(zip(*(arr[rows] for arr in arrays)))
            else:
                # mixed commit: preserve original row order (retract/insert interleave)
                for i in rows:
                    vals = tuple(arr[i] for arr in arrays)
                    if diffs[i] > 0:
                        acc.insert(vals)
                    else:
                        acc.retract(vals)

    def values(self, slots: np.ndarray) -> np.ndarray:
        out = np.empty(len(slots), dtype=object)
        for i, s in enumerate(slots.tolist()):
            acc = self.accs[s]
            out[i] = acc.value() if acc is not None else None
        return out


class Accumulator:
    def insert(self, values: tuple) -> None:
        raise NotImplementedError

    def retract(self, values: tuple) -> None:
        raise NotImplementedError

    def value(self) -> Any:
        raise NotImplementedError

    def insert_many(self, rows: Iterable[tuple]) -> None:
        for r in rows:
            self.insert(r)

    def retract_many(self, rows: Iterable[tuple]) -> None:
        for r in rows:
            self.retract(r)


class _CountAcc(Accumulator):
    __slots__ = ("n",)

    def __init__(self) -> None:
        self.n = 0

    def insert(self, values: tuple) -> None:
        self.n += 1

    def retract(self, values: tuple) -> None:
        self.n -= 1

    def value(self) -> int:
        return self.n


class CountReducer(Reducer):
    name = "count"
    semigroup = True
    n_args = 0

    def make(self) -> Accumulator:
        return _CountAcc()

    def make_state(self) -> ColumnarState:
        return _CountState()

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return dt.INT


class _SumAcc(Accumulator):
    __slots__ = ("total", "n")

    def __init__(self) -> None:
        self.total: Any = 0
        self.n = 0

    def insert(self, values: tuple) -> None:
        self.total = values[0] if self.n == 0 else self.total + values[0]
        self.n += 1

    def retract(self, values: tuple) -> None:
        self.n -= 1
        if self.n == 0:
            self.total = 0
        else:
            self.total = self.total - values[0]

    def value(self) -> Any:
        return self.total


class SumReducer(Reducer):
    name = "sum"
    semigroup = True

    def make(self) -> Accumulator:
        return _SumAcc()

    def make_state(self) -> ColumnarState:
        return _SumState(zero_on_empty=True)

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        base = arg_dtypes[0].strip_optional()
        if base in (dt.INT, dt.FLOAT, dt.DURATION) or isinstance(base, dt.Array):
            return base
        return dt.ANY


class _MultisetAcc(Accumulator):
    """Base for non-subtractable reducers: keeps every contribution."""

    __slots__ = ("items",)

    def __init__(self) -> None:
        self.items: Counter = Counter()

    def _key(self, values: tuple) -> Any:
        return values if len(values) != 1 else values[0]

    def insert(self, values: tuple) -> None:
        self.items[_hashable(self._key(values))] += 1

    def retract(self, values: tuple) -> None:
        k = _hashable(self._key(values))
        self.items[k] -= 1
        if self.items[k] == 0:
            del self.items[k]

    def insert_many(self, rows: Iterable[tuple]) -> None:
        # Counter.update over a generator runs at C speed
        self.items.update(_hashable(self._key(r)) for r in rows)

    def retract_many(self, rows: Iterable[tuple]) -> None:
        self.items.subtract(_hashable(self._key(r)) for r in rows)
        for k in [k for k, c in self.items.items() if c == 0]:
            del self.items[k]


def _hashable(v: Any) -> Any:
    if isinstance(v, np.ndarray):
        return _NdarrayWrap(v)
    if isinstance(v, tuple):
        return tuple(_hashable(x) for x in v)
    return v


def _unhash(v: Any) -> Any:
    if isinstance(v, _NdarrayWrap):
        return v.arr
    if isinstance(v, tuple):
        return tuple(_unhash(x) for x in v)
    return v


class _NdarrayWrap:
    __slots__ = ("arr", "_h")

    def __init__(self, arr: np.ndarray):
        self.arr = arr
        self._h = hash((arr.tobytes(), arr.shape))

    def __hash__(self) -> int:
        return self._h

    def __eq__(self, other: object) -> bool:
        return isinstance(other, _NdarrayWrap) and np.array_equal(self.arr, other.arr)

    def _key(self) -> tuple:
        return (self.arr.shape, self.arr.tobytes())

    def __lt__(self, other: "_NdarrayWrap") -> bool:
        return self._key() < other._key()

    def __le__(self, other: "_NdarrayWrap") -> bool:
        return self._key() <= other._key()

    def __gt__(self, other: "_NdarrayWrap") -> bool:
        return self._key() > other._key()

    def __ge__(self, other: "_NdarrayWrap") -> bool:
        return self._key() >= other._key()


class _MinAcc(_MultisetAcc):
    def value(self) -> Any:
        present = [k for k in self.items if k is not None]
        return _unhash(min(present)) if present else None


class _MaxAcc(_MultisetAcc):
    def value(self) -> Any:
        present = [k for k in self.items if k is not None]
        return _unhash(max(present)) if present else None


class MinReducer(Reducer):
    name = "min"

    def make(self) -> Accumulator:
        return _MinAcc()

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return arg_dtypes[0]


class MaxReducer(Reducer):
    name = "max"

    def make(self) -> Accumulator:
        return _MaxAcc()

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return arg_dtypes[0]


class _ArgExtremeAcc(_MultisetAcc):
    """values = (cmp_value, pointer)."""

    def __init__(self, take_min: bool):
        super().__init__()
        self.take_min = take_min

    def _key(self, values: tuple) -> Any:
        return values

    def value(self) -> Any:
        pick = min(self.items) if self.take_min else max(self.items)
        return _unhash(pick)[1]


class ArgMinReducer(Reducer):
    name = "argmin"
    n_args = 2

    def make(self) -> Accumulator:
        return _ArgExtremeAcc(True)

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return dt.POINTER


class ArgMaxReducer(Reducer):
    name = "argmax"
    n_args = 2

    def make(self) -> Accumulator:
        return _ArgExtremeAcc(False)

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return dt.POINTER


class _UniqueAcc(_MultisetAcc):
    def value(self) -> Any:
        if len(self.items) != 1:
            from pathway_tpu.engine.columnar import ERROR
            from pathway_tpu.engine.expression_evaluator import get_runtime

            if get_runtime()["terminate_on_error"]:
                # reference semantics: a unique() violation fails the run unless
                # error poisoning was opted into (terminate_on_error=False)
                raise ValueError(
                    "unique reducer: group holds more than one distinct value"
                )
            return ERROR
        return _unhash(next(iter(self.items)))


class UniqueReducer(Reducer):
    name = "unique"

    def make(self) -> Accumulator:
        return _UniqueAcc()

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return arg_dtypes[0]


class _AnyAcc(_MultisetAcc):
    def value(self) -> Any:
        return _unhash(min(self.items, key=lambda v: repr(v)))


class AnyReducer(Reducer):
    name = "any"

    def make(self) -> Accumulator:
        return _AnyAcc()

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return arg_dtypes[0]


class _TupleAcc(Accumulator):
    """values = (value, sort_key_or_None); collects a tuple ordered by insertion/key."""

    __slots__ = ("items", "counter", "skip_nones")

    def __init__(self, skip_nones: bool = False):
        self.items: Counter = Counter()
        self.counter = 0
        self.skip_nones = skip_nones

    def insert(self, values: tuple) -> None:
        value, sort_key = values
        if self.skip_nones and value is None:
            return
        self.counter += 1
        self.items[_hashable((sort_key, self.counter, value))] += 1

    def retract(self, values: tuple) -> None:
        value, sort_key = values
        if self.skip_nones and value is None:
            return
        hv, hs = _hashable(value), _hashable(sort_key)
        for k in list(self.items):
            uk_sort, _counter, uk_value = k
            if uk_sort == hs and uk_value == hv:
                self.items[k] -= 1
                if self.items[k] == 0:
                    del self.items[k]
                return

    def value(self) -> tuple:
        out = []
        for k in sorted(self.items, key=lambda x: (_unhash(x)[0] is not None, _sortable(_unhash(x)[0]), _unhash(x)[1])):
            uk = _unhash(k)
            out.extend([uk[2]] * self.items[k])
        return tuple(out)


def _sortable(v: Any) -> Any:
    if v is None:
        return 0
    return v


class TupleReducer(Reducer):
    name = "tuple"
    n_args = 2

    def __init__(self, skip_nones: bool = False):
        self.skip_nones = skip_nones

    def make(self) -> Accumulator:
        return _TupleAcc(self.skip_nones)

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return dt.List_(arg_dtypes[0]) if arg_dtypes else dt.ANY_TUPLE


class _SortedTupleAcc(_MultisetAcc):
    def __init__(self, skip_nones: bool = False):
        super().__init__()
        self.skip_nones = skip_nones

    def insert(self, values: tuple) -> None:
        if self.skip_nones and values[0] is None:
            return
        super().insert(values)

    def retract(self, values: tuple) -> None:
        if self.skip_nones and values[0] is None:
            return
        super().retract(values)

    def insert_many(self, rows: Iterable[tuple]) -> None:
        super().insert_many(
            r for r in rows if not (self.skip_nones and r[0] is None)
        )

    def retract_many(self, rows: Iterable[tuple]) -> None:
        super().retract_many(
            r for r in rows if not (self.skip_nones and r[0] is None)
        )

    def value(self) -> tuple:
        out = []
        for k in sorted(self.items):
            out.extend([_unhash(k)] * self.items[k])
        return tuple(out)


class SortedTupleReducer(Reducer):
    name = "sorted_tuple"

    def __init__(self, skip_nones: bool = False):
        self.skip_nones = skip_nones

    def make(self) -> Accumulator:
        return _SortedTupleAcc(self.skip_nones)

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return dt.List_(arg_dtypes[0]) if arg_dtypes else dt.ANY_TUPLE


class _NdarrayAcc(_TupleAcc):
    def value(self) -> np.ndarray:
        return np.array(super().value())


class NdarrayReducer(Reducer):
    name = "ndarray"
    n_args = 2

    def __init__(self, skip_nones: bool = False):
        self.skip_nones = skip_nones

    def make(self) -> Accumulator:
        return _NdarrayAcc(self.skip_nones)

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return dt.Array(1, arg_dtypes[0] if arg_dtypes else dt.ANY)


class _AvgAcc(Accumulator):
    __slots__ = ("total", "n")

    def __init__(self) -> None:
        self.total = 0.0
        self.n = 0

    def insert(self, values: tuple) -> None:
        self.total = values[0] if self.n == 0 else self.total + values[0]
        self.n += 1

    def retract(self, values: tuple) -> None:
        self.total = self.total - values[0]
        self.n -= 1

    def value(self) -> Any:
        return self.total / self.n if self.n else None


class AvgReducer(Reducer):
    name = "avg"
    semigroup = True

    def make(self) -> Accumulator:
        return _AvgAcc()

    def make_state(self) -> ColumnarState:
        return _AvgState()

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return dt.FLOAT


class _EarliestAcc(Accumulator):
    """values = (value, seq) — engine passes a monotone sequence number at insert.

    Retractions carry a NEW seq (the engine cannot know the original), so removal matches by
    value only, dropping the oldest/newest occurrence of that value.
    """

    __slots__ = ("items",)

    def __init__(self) -> None:
        self.items: list[tuple[int, Any]] = []

    def insert(self, values: tuple) -> None:
        self.items.append((values[1], values[0]))

    def retract(self, values: tuple) -> None:
        target = _hashable(values[0])
        for i, (seq, v) in enumerate(self.items):
            if _hashable(v) == target:
                del self.items[i]
                return
        raise KeyError(f"retraction of absent value {values[0]!r}")

    def value(self) -> Any:
        return min(self.items, key=lambda sv: sv[0])[1] if self.items else None


class _LatestAcc(_EarliestAcc):
    def value(self) -> Any:
        return max(self.items, key=lambda sv: sv[0])[1] if self.items else None


class EarliestReducer(Reducer):
    name = "earliest"
    n_args = 2

    def make(self) -> Accumulator:
        return _EarliestAcc()

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return arg_dtypes[0]


class LatestReducer(Reducer):
    name = "latest"
    n_args = 2

    def make(self) -> Accumulator:
        return _LatestAcc()

    def return_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return arg_dtypes[0]


class _UdfAcc(Accumulator):
    def __init__(self, combine: Callable[[list[tuple]], Any]):
        self.combine = combine
        self.rows: Counter = Counter()

    def insert(self, values: tuple) -> None:
        self.rows[_hashable(values)] += 1

    def retract(self, values: tuple) -> None:
        k = _hashable(values)
        self.rows[k] -= 1
        if self.rows[k] == 0:
            del self.rows[k]

    def insert_many(self, rows: Iterable[tuple]) -> None:
        self.rows.update(_hashable(r) for r in rows)

    def retract_many(self, rows: Iterable[tuple]) -> None:
        self.rows.subtract(_hashable(r) for r in rows)
        for k in [k for k, c in self.rows.items() if c == 0]:
            del self.rows[k]

    def value(self) -> Any:
        expanded: list[tuple] = []
        for k, c in self.rows.items():
            expanded.extend([_unhash(k)] * c)
        cols = tuple(np.array(col) for col in zip(*expanded)) if expanded else ()
        return self.combine(*cols)


class UdfReducer(Reducer):
    name = "udf_reducer"

    def __init__(self, fun: Callable, n_args: int = 1):
        self.fun = fun
        self.n_args = n_args

    def make(self) -> Accumulator:
        return _UdfAcc(self.fun)


def udf_reducer(reducer_cls: Any) -> Callable:
    """Wrap a BaseCustomAccumulator subclass into a reducer (reference custom_reducers)."""
    from pathway_tpu.internals.custom_reducers import make_custom_reducer

    return make_custom_reducer(reducer_cls)


def stateful_many(combine_many: Callable) -> Callable:
    from pathway_tpu.internals.custom_reducers import stateful_many as _sm

    return _sm(combine_many)


def stateful_single(combine_single: Callable) -> Callable:
    from pathway_tpu.internals.custom_reducers import stateful_single as _ss

    return _ss(combine_single)


# -- public namespace (pw.reducers.*) --------------------------------------


class _ReducerNamespace:
    def count(self, *args: Any) -> expr.ReducerExpression:
        return expr.ReducerExpression(CountReducer(), *args)

    def sum(self, arg: Any) -> expr.ReducerExpression:
        return expr.ReducerExpression(SumReducer(), arg)

    def min(self, arg: Any) -> expr.ReducerExpression:
        return expr.ReducerExpression(MinReducer(), arg)

    def max(self, arg: Any) -> expr.ReducerExpression:
        return expr.ReducerExpression(MaxReducer(), arg)

    def argmin(self, arg: Any) -> expr.ReducerExpression:
        return expr.ReducerExpression(ArgMinReducer(), arg, _IdMarker())

    def argmax(self, arg: Any) -> expr.ReducerExpression:
        return expr.ReducerExpression(ArgMaxReducer(), arg, _IdMarker())

    def unique(self, arg: Any) -> expr.ReducerExpression:
        return expr.ReducerExpression(UniqueReducer(), arg)

    def any(self, arg: Any) -> expr.ReducerExpression:
        return expr.ReducerExpression(AnyReducer(), arg)

    def avg(self, arg: Any) -> expr.ReducerExpression:
        return expr.ReducerExpression(AvgReducer(), arg)

    def tuple(self, arg: Any, *, skip_nones: bool = False, sort_by: Any = None) -> expr.ReducerExpression:
        return expr.ReducerExpression(
            TupleReducer(skip_nones), arg, sort_by if sort_by is not None else None
        )

    def sorted_tuple(self, arg: Any, *, skip_nones: bool = False) -> expr.ReducerExpression:
        return expr.ReducerExpression(SortedTupleReducer(skip_nones), arg)

    def ndarray(self, arg: Any, *, skip_nones: bool = False, sort_by: Any = None) -> expr.ReducerExpression:
        return expr.ReducerExpression(
            NdarrayReducer(skip_nones), arg, sort_by if sort_by is not None else None
        )

    def earliest(self, arg: Any) -> expr.ReducerExpression:
        return expr.ReducerExpression(EarliestReducer(), arg, _SeqMarker())

    def latest(self, arg: Any) -> expr.ReducerExpression:
        return expr.ReducerExpression(LatestReducer(), arg, _SeqMarker())

    def udf_reducer(self, reducer_cls: Any) -> Callable:
        return udf_reducer(reducer_cls)

    def stateful_many(self, combine: Callable) -> Callable:
        return stateful_many(combine)

    def stateful_single(self, combine: Callable) -> Callable:
        return stateful_single(combine)


class _IdMarker(expr.ColumnExpression):
    """Placeholder resolved by the engine to the row's id (pointer)."""


class _SeqMarker(expr.ColumnExpression):
    """Placeholder resolved by the engine to a monotone per-row sequence number."""


reducers = _ReducerNamespace()
