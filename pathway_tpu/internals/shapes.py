"""Shape-bucketing helpers shared by every jit'd kernel path.

One home for the power-of-two bucketing rule (previously duplicated across
``models/encoder.py``, ``ops/knn.py`` and ``ops/segment.py``): padding batch
shapes to pow2 buckets keys each kernel's jit cache by O(log) distinct shapes
instead of one compile per raw size. Pure python, import-free — safe to use
from modules that must not pull in jax.
"""

from __future__ import annotations


def next_pow2(n: int, floor: int = 1) -> int:
    """Smallest power of two >= max(n, floor).

    ``floor`` is the minimum bucket (device paths use 8: tiny batches still
    produce MXU/lane-aligned shapes, and the sub-8 sizes would each cost a
    compile for no throughput gain). ``floor`` must itself be a power of two.
    """
    p = floor
    while p < n:
        p *= 2
    return p
