"""Interactive (notebook) mode — ``pw.enable_interactive_mode`` + ``LiveTable``.

Parity: reference ``internals/interactive.py`` — a live-updating table view backed by a
background run thread; printing a ``LiveTable`` shows the current snapshot.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

_interactive_enabled = False


def is_interactive_mode_enabled() -> bool:
    return _interactive_enabled


def enable_interactive_mode() -> None:
    """Switch the session into interactive mode: ``Table.live()`` becomes available and
    runs the dataflow on a background thread, keeping live snapshots updated."""
    global _interactive_enabled
    _interactive_enabled = True
    from pathway_tpu.internals.table import Table

    if not hasattr(Table, "live"):
        Table.live = _table_live  # type: ignore[attr-defined]


class LiveTable:
    """A self-updating snapshot of a table (reference ``LiveTable``)."""

    def __init__(self, table: Any):
        self._table = table
        self._rows: Dict[Any, dict] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._failed: Optional[BaseException] = None
        self._start()

    def _start(self) -> None:
        from pathway_tpu.engine.runner import GraphRunner
        from pathway_tpu.internals.parse_graph import G
        from pathway_tpu.io import subscribe

        def on_change(key: Any, row: dict, time: int, is_addition: bool) -> None:
            with self._lock:
                if is_addition:
                    self._rows[key] = row
                else:
                    self._rows.pop(key, None)

        subscribe(self._table, on_change)
        graph = G._current

        def run() -> None:
            try:
                GraphRunner(graph).run()
            except BaseException as exc:  # surfaced via .failed
                self._failed = exc

        self._thread = threading.Thread(target=run, daemon=True, name="pathway:live-table")
        self._thread.start()

    @property
    def failed(self) -> bool:
        return self._failed is not None

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(row) for row in self._rows.values()]

    def to_pandas(self) -> Any:
        import pandas as pd

        return pd.DataFrame(self.snapshot())

    def __str__(self) -> str:
        rows = self.snapshot()
        if not rows:
            return "<LiveTable: empty>"
        names = list(rows[0])
        header = " | ".join(names)
        body = "\n".join(" | ".join(str(r[n]) for n in names) for r in rows)
        return f"{header}\n{body}"

    def _repr_pretty_(self, p: Any, cycle: bool) -> None:
        p.text(str(self))


def _table_live(self: Any) -> LiveTable:
    if not _interactive_enabled:
        raise RuntimeError("call pw.enable_interactive_mode() first")
    return LiveTable(self)
