"""Custom / stateful reducers (parity: reference ``internals/custom_reducers.py``)."""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.reducers import Accumulator, Reducer


class BaseCustomAccumulator:
    """User-defined accumulator: implement from_row, update, compute_result, optionally
    retract/neutral (reference ``BaseCustomAccumulator``)."""

    @classmethod
    def from_row(cls, row: list) -> "BaseCustomAccumulator":
        raise NotImplementedError

    def update(self, other: "BaseCustomAccumulator") -> None:
        raise NotImplementedError

    def retract(self, other: "BaseCustomAccumulator") -> None:
        raise NotImplementedError("this accumulator does not support retractions")

    def compute_result(self) -> Any:
        raise NotImplementedError


class _CustomAcc(Accumulator):
    def __init__(self, acc_cls: type[BaseCustomAccumulator]):
        self.acc_cls = acc_cls
        self.state: BaseCustomAccumulator | None = None
        self.rows: list[tuple] = []  # fallback for non-retractable accumulators

    def insert(self, values: tuple) -> None:
        incoming = self.acc_cls.from_row(list(values))
        self.rows.append(values)
        if self.state is None:
            self.state = incoming
        else:
            self.state.update(incoming)

    def retract(self, values: tuple) -> None:
        self.rows.remove(values)
        incoming = self.acc_cls.from_row(list(values))
        try:
            assert self.state is not None
            self.state.retract(incoming)
        except NotImplementedError:
            # rebuild from scratch
            self.state = None
            for row in self.rows:
                incoming = self.acc_cls.from_row(list(row))
                if self.state is None:
                    self.state = incoming
                else:
                    self.state.update(incoming)

    def value(self) -> Any:
        return self.state.compute_result() if self.state is not None else None


class CustomReducer(Reducer):
    def __init__(self, acc_cls: type[BaseCustomAccumulator], n_args: int = 1):
        self.acc_cls = acc_cls
        self.name = f"custom:{acc_cls.__name__}"
        self.n_args = n_args

    def make(self) -> Accumulator:
        return _CustomAcc(self.acc_cls)


def make_custom_reducer(acc_cls: type[BaseCustomAccumulator]) -> Callable:
    def reducer_call(*args: Any) -> expr.ReducerExpression:
        return expr.ReducerExpression(CustomReducer(acc_cls, n_args=len(args)), *args)

    return reducer_call


class _StatefulManyAcc(Accumulator):
    """reference ``stateful_many``: state = combine(state, rows_batch)."""

    def __init__(self, combine: Callable):
        self.combine = combine
        self.rows: list[tuple] = []

    def insert(self, values: tuple) -> None:
        self.rows.append(values)

    def retract(self, values: tuple) -> None:
        self.rows.remove(values)

    def value(self) -> Any:
        state = None
        state = self.combine(state, [(row, 1) for row in self.rows])
        return state


def stateful_many(combine_many: Callable) -> Callable:
    def reducer_call(*args: Any) -> expr.ReducerExpression:
        class _R(Reducer):
            name = f"stateful_many:{getattr(combine_many, '__name__', 'fn')}"
            n_args = len(args)

            def make(self) -> Accumulator:
                return _StatefulManyAcc(combine_many)

        return expr.ReducerExpression(_R(), *args)

    return reducer_call


def stateful_single(combine_single: Callable) -> Callable:
    def combine_many(state: Any, rows: list) -> Any:
        for row, diff in rows:
            if diff < 0:
                raise ValueError("stateful_single does not support retractions")
            state = combine_single(state, *row)
        return state

    return stateful_many(combine_many)
