"""Fixed-point iteration — ``pw.iterate``.

Parity: reference ``internals/common.py:39`` (``pw.iterate``) over the engine's nested timely
scope with DD ``Variable`` feedback (``dataflow/variable.rs``, ``graph.rs:939``). Here the
engine runs the iteration body as a nested dataflow graph, semi-naively: each outer commit
re-derives the fixed point by feeding deltas around the feedback edge until quiescence (or
``iteration_limit``). Used by ``pw.stdlib.graphs`` (pagerank, bellman-ford, louvain).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from pathway_tpu.engine.columnar import Delta, StateTable
from pathway_tpu.engine.datasource import DataSource
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


class _ManualSource(DataSource):
    """Nested-graph input fed explicitly by the iterate evaluator."""

    def __init__(self) -> None:
        self.queue: List[Delta] = []
        self._finished = False

    def feed(self, delta: Delta) -> None:
        self.queue.append(delta)

    def next_batch(self, column_names: List[str]) -> Delta:
        if self.queue:
            return self.queue.pop(0)
        return Delta.empty(column_names)

    def is_finished(self) -> bool:
        return self._finished


class _UniverseMarker:
    pass


def iteration_limit(table: Table, limit: int) -> Table:
    table._iteration_limit = limit  # type: ignore[attr-defined]
    return table


def iterate(
    func: Callable,
    iteration_limit: int | None = None,
    **kwargs: Any,
) -> Any:
    """Iterate ``func`` to a fixed point over the tables passed as kwargs.

    ``func`` receives proxy tables and returns a dict-like / namespace of tables; returned
    names matching argument names are fed back. Returns an object with the final tables.
    """
    if iteration_limit is not None and iteration_limit < 1:
        raise ValueError("iteration_limit must be a positive integer")
    table_args = {k: v for k, v in kwargs.items() if isinstance(v, Table)}
    const_args = {k: v for k, v in kwargs.items() if not isinstance(v, Table)}

    # build the nested graph in the global graph's node list? No: a private ParseGraph.
    inner_graph = pg.ParseGraph()
    saved = G._current
    proxies: Dict[str, Table] = {}
    try:
        _set_global_graph(inner_graph)
        sources: Dict[str, _ManualSource] = {}
        for name, t in table_args.items():
            src = _ManualSource()
            sources[name] = src
            node = inner_graph.add_node(pg.InputNode(source=src, name=f"iterate:{name}"))
            proxies[name] = Table(node, t._schema, name=f"iterate:{name}")
        result = func(**proxies, **const_args)
        if isinstance(result, Table):
            result_map = {"result": result}
            single = True
        elif isinstance(result, dict):
            result_map = dict(result)
            single = False
        else:  # namespace / namedtuple
            if hasattr(result, "_asdict"):
                result_map = dict(result._asdict())
            else:
                result_map = {
                    k: v for k, v in vars(result).items() if isinstance(v, Table)
                }
            single = False
    finally:
        _set_global_graph(saved)

    node = G.add_node(
        pg.IterateNode(
            inputs=list(table_args.values()),
            input_names=list(table_args.keys()),
            inner_graph=inner_graph,
            sources=sources,
            result_map=result_map,
            iteration_limit=iteration_limit,
        )
    )
    # IterateNode itself emits the FIRST result; extra results get reader nodes
    first_name = next(iter(result_map))
    out_tables: Dict[str, Table] = {}
    primary = Table(node, result_map[first_name]._schema, name=f"iterate_out:{first_name}")
    out_tables[first_name] = primary
    for name in list(result_map)[1:]:
        reader = G.add_node(
            pg.IterateResultNode(inputs=[primary], parent=node, result_name=name)
        )
        out_tables[name] = Table(reader, result_map[name]._schema, name=f"iterate_out:{name}")

    if single:
        return out_tables[first_name]

    class _Result:
        pass

    r = _Result()
    for name, t in out_tables.items():
        setattr(r, name, t)
    return r


def _set_global_graph(graph: pg.ParseGraph) -> None:
    G._current = graph


class IterateEvaluator:
    """Runs the nested graph to fixpoint each commit (recomputed from full input state)."""

    def __init__(self, node: pg.Node, runner: Any):
        self.node = node
        self.runner = runner
        self.input_states = [
            StateTable(t.column_names()) for t in node.inputs
        ]
        self.emitted: Dict[str, StateTable] = {
            name: StateTable(t.column_names()) for name, t in node.config["result_map"].items()
        }
        self.pending_outputs: Dict[str, Delta] = {}
        self.output_columns = node.output.column_names() if node.output else []

    # operator-snapshot protocol (same contract as engine.evaluators.Evaluator)
    _NON_STATE_ATTRS = ("node", "runner", "output_columns")
    state_dict = None  # assigned below to share the engine implementation
    load_state_dict = None

    def process(self, input_deltas: List[Delta]) -> Delta:
        from pathway_tpu.engine.runner import GraphRunner

        for state, delta in zip(self.input_states, input_deltas):
            state.apply(delta)
        if all(len(d) == 0 for d in input_deltas):
            first = next(iter(self.node.config["result_map"]))
            return Delta.empty(self.output_columns)

        inner_graph: pg.ParseGraph = self.node.config["inner_graph"]
        sources: Dict[str, Any] = self.node.config["sources"]
        result_map: Dict[str, Table] = self.node.config["result_map"]
        input_names: List[str] = self.node.config["input_names"]
        limit = self.node.config.get("iteration_limit")

        nested = GraphRunner(inner_graph)
        nested._materialize_all = True  # iterate reads nested states directly
        nested.setup()
        # feed full current state as iteration 0
        for name, state in zip(input_names, self.input_states):
            sources[name].feed(state.snapshot())

        iteration = 0
        while True:
            nested.step()
            iteration += 1
            if limit is not None and iteration >= limit:
                # the limit counts APPLICATIONS of func (reference
                # ``test_iterate_with_limit``: limit N -> f^N(x)); stop before
                # feeding the next round back
                break
            changed = False
            for name in input_names:
                if name not in result_map:
                    continue
                out_node = result_map[name]._node
                out_state = nested.state_of(out_node)
                # feedback edge: diff the proxy input's state against the iterated output
                proxy_delta = _state_diff(
                    nested.state_of(_proxy_node(inner_graph, name)), out_state
                )
                if len(proxy_delta):
                    changed = True
                    sources[name].feed(proxy_delta)
            if not changed:
                break

        # diff nested outputs against previously emitted
        for name, table in result_map.items():
            final_state = nested.state_of(table._node)
            delta = _state_diff(self.emitted[name], final_state)
            self.emitted[name].apply(delta)
            self.pending_outputs[name] = delta
        first = next(iter(result_map))
        return self.pending_outputs.pop(first)

    def take_output(self, name: str) -> Delta:
        return self.pending_outputs.pop(
            name, Delta.empty(self.node.config["result_map"][name].column_names())
        )


def _proxy_node(inner_graph: pg.ParseGraph, name: str) -> pg.Node:
    for node in inner_graph.nodes:
        if isinstance(node, pg.InputNode) and node.name == f"iterate:{name}":
            return node
    raise KeyError(name)


def _state_diff(old: StateTable, new: StateTable) -> Delta:
    """Delta transforming old's contents into new's."""
    from pathway_tpu.engine.evaluators import _delta_from_rows

    out_keys: list = []
    out_diffs: list = []
    out_rows: list = []
    new_snapshot = new.snapshot()
    new_keys = {new_snapshot.keys[i].tobytes() for i in range(len(new_snapshot))}
    old_snapshot = old.snapshot()
    for i in range(len(old_snapshot)):
        kb = old_snapshot.keys[i].tobytes()
        new_row = new.get_row(kb)
        old_row = {c: old_snapshot.columns[c][i] for c in old_snapshot.column_names}
        if new_row is None:
            out_keys.append(old_snapshot.keys[i])
            out_diffs.append(-1)
            out_rows.append(old_row)
        elif not _rows_equal(new_row, old_row):
            out_keys.append(old_snapshot.keys[i])
            out_diffs.append(-1)
            out_rows.append(old_row)
            out_keys.append(old_snapshot.keys[i])
            out_diffs.append(1)
            out_rows.append(new_row)
    for i in range(len(new_snapshot)):
        kb = new_snapshot.keys[i].tobytes()
        if old.get_row(kb) is None:
            out_keys.append(new_snapshot.keys[i])
            out_diffs.append(1)
            out_rows.append({c: new_snapshot.columns[c][i] for c in new_snapshot.column_names})
    return _delta_from_rows(out_keys, out_diffs, out_rows, old.column_names)


def _rows_equal(a: dict, b: dict) -> bool:
    for k, va in a.items():
        vb = b.get(k)
        if isinstance(va, np.ndarray) or isinstance(vb, np.ndarray):
            try:
                eq = np.array_equal(va, vb, equal_nan=True)
            except TypeError:  # non-numeric dtypes reject equal_nan
                eq = np.array_equal(va, vb)
            if not eq:
                return False
        elif va != vb:
            # NaN must equal NaN for the FIXPOINT check: value semantics, not
            # IEEE semantics — otherwise any iterated float column that ever
            # holds NaN re-emits the same row forever and the loop never ends
            if not (
                isinstance(va, float)
                and isinstance(vb, float)
                and va != va
                and vb != vb
            ):
                return False
    return True


class IterateResultEvaluator:
    _NON_STATE_ATTRS = ("node", "runner")
    state_dict = None  # assigned below
    load_state_dict = None

    def __init__(self, node: pg.Node, runner: Any):
        self.node = node
        self.runner = runner

    def process(self, input_deltas: List[Delta]) -> Delta:
        parent = self.node.config["parent"]
        parent_eval = self.runner.evaluators[parent.id]
        return parent_eval.take_output(self.node.config["result_name"])


def _wire_snapshot_protocol() -> None:
    from pathway_tpu.engine.evaluators import Evaluator, wire_cluster_defaults

    for cls in (IterateEvaluator, IterateResultEvaluator):
        cls.state_dict = Evaluator.state_dict
        cls.load_state_dict = Evaluator.load_state_dict
    # multi-process lane: iterate CENTRALIZES on process 0 (the nested fixpoint
    # recomputes from full input state, which cannot be co-partitioned — the
    # reference threads a DD Variable feedback through every worker,
    # ``src/engine/dataflow/variable.rs``; here the root runs the whole nested
    # graph and downstream operators re-exchange its output by their own keys)
    wire_cluster_defaults(IterateEvaluator, "root")
    wire_cluster_defaults(IterateResultEvaluator)


_wire_snapshot_protocol()
