"""Json value wrapper (parity: reference ``python/pathway/internals/json.py``)."""

from __future__ import annotations

import json as _json
from typing import Any


class Json:
    """Immutable wrapper around a parsed JSON value, with indexing helpers."""

    __slots__ = ("_value",)

    NULL: "Json"

    def __init__(self, value: Any):
        if isinstance(value, Json):
            value = value._value
        object.__setattr__(self, "_value", value)

    def __setattr__(self, *a: Any) -> None:
        raise AttributeError("Json is immutable")

    @property
    def value(self) -> Any:
        return self._value

    @staticmethod
    def parse(text: str | bytes) -> "Json":
        return Json(_json.loads(text))

    def dumps(self) -> str:
        return _json.dumps(
            self._value, sort_keys=True, separators=(",", ":"), default=_jsonify
        )

    def __getitem__(self, item: Any) -> "Json":
        return Json(self._value[item])

    def get(self, key: Any, default: Any = None) -> Any:
        if isinstance(self._value, dict):
            result = self._value.get(key, default)
            return Json(result) if result is not default else default
        return default

    def as_int(self) -> int:
        return int(self._value)

    def as_float(self) -> float:
        return float(self._value)

    def as_str(self) -> str:
        return str(self._value)

    def as_bool(self) -> bool:
        if not isinstance(self._value, bool):
            raise ValueError(f"not a bool: {self._value!r}")
        return self._value

    def as_list(self) -> list:
        return list(self._value)

    def as_dict(self) -> dict:
        return dict(self._value)

    def __len__(self) -> int:
        return len(self._value)

    def __iter__(self):
        return (Json(v) for v in self._value)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Json):
            return self._value == other._value
        return self._value == other

    def __hash__(self) -> int:
        return hash(self.dumps())

    def __repr__(self) -> str:
        return f"pw.Json({self._value!r})"

    def __str__(self) -> str:
        return self.dumps()


def _jsonify(value: Any) -> Any:
    import numpy as np

    if isinstance(value, Json):
        return value.value
    if isinstance(value, np.integer):
        return int(value)
    if isinstance(value, np.floating):
        return float(value)
    if isinstance(value, np.bool_):
        return bool(value)
    if isinstance(value, np.ndarray):
        return value.tolist()
    raise TypeError(f"not JSON serializable: {type(value).__name__}")


def jsonable_value(v: Any) -> Any:
    """Recursively coerce engine values (Json, Pointer, numpy, tuples) to plain JSON.

    Single source of truth for numpy→JSON coercion (also used by the REST layer).
    """
    import numpy as np

    if isinstance(v, Json):
        return jsonable_value(v.value)
    if isinstance(v, (tuple, list)):
        return [jsonable_value(x) for x in v]
    if isinstance(v, dict):
        return {k: jsonable_value(x) for k, x in v.items()}
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (np.integer, np.floating, np.bool_)):
        return _jsonify(v)
    from pathway_tpu.internals.keys import Pointer

    if isinstance(v, Pointer):
        return repr(v)
    return v


Json.NULL = Json(None)
