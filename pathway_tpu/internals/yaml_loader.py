"""YAML app templates — ``pw.load_yaml``.

Parity: reference ``internals/yaml_loader.py:214``: ``!pw.<dotted.path>`` instantiates objects,
``$ref``-style anchors via ``$<name>`` variables; powers RAG app templates.
"""

from __future__ import annotations

import importlib
from typing import Any, Dict

import yaml


def _resolve_path(path: str) -> Any:
    if path.startswith("pw."):
        import pathway_tpu as pw

        obj: Any = pw
        parts = path.split(".")[1:]
    else:
        module_path, _, attr = path.rpartition(".")
        try:
            return getattr(importlib.import_module(module_path), attr)
        except (ImportError, AttributeError):
            parts = path.split(".")
            obj = importlib.import_module(parts[0])
            parts = parts[1:]
    for part in parts:
        if hasattr(obj, part):
            obj = getattr(obj, part)
        else:
            obj = importlib.import_module(f"{obj.__name__}.{part}")
    return obj


class _PwLoader(yaml.SafeLoader):
    pass


def _pw_constructor(loader: _PwLoader, tag_suffix: str, node: yaml.Node) -> Any:
    target = _resolve_path("pw." + tag_suffix if not tag_suffix.startswith("pw.") else tag_suffix)
    if isinstance(node, yaml.MappingNode):
        kwargs = loader.construct_mapping(node, deep=True)
        return _Instantiate(target, kwargs)
    if isinstance(node, yaml.SequenceNode):
        args = loader.construct_sequence(node, deep=True)
        return _Instantiate(target, None, args)
    value = loader.construct_scalar(node)
    if value in (None, ""):
        return _Instantiate(target, {})
    return _Instantiate(target, None, [value])


class _Instantiate:
    def __init__(self, target: Any, kwargs: Dict | None, args: list | None = None):
        self.target = target
        self.kwargs = kwargs
        self.args = args

    def build(self, variables: Dict[str, Any]) -> Any:
        args = [_materialize(a, variables) for a in (self.args or [])]
        kwargs = {k: _materialize(v, variables) for k, v in (self.kwargs or {}).items()}
        if callable(self.target):
            return self.target(*args, **kwargs)
        return self.target


_PwLoader.add_multi_constructor("!pw.", _pw_constructor)
_PwLoader.add_multi_constructor("!", lambda l, s, n: _pw_constructor(l, s, n))


def _materialize(value: Any, variables: Dict[str, Any]) -> Any:
    if isinstance(value, _Instantiate):
        return value.build(variables)
    if isinstance(value, str) and value.startswith("$") and value[1:] in variables:
        return _materialize(variables[value[1:]], variables)
    if isinstance(value, dict):
        return {k: _materialize(v, variables) for k, v in value.items()}
    if isinstance(value, list):
        return [_materialize(v, variables) for v in value]
    return value


def load_yaml(stream: Any) -> Any:
    """Parse a YAML app template, instantiating ``!pw.*`` tags and ``$variables``."""
    if hasattr(stream, "read"):
        raw = yaml.load(stream, Loader=_PwLoader)
    else:
        raw = yaml.load(str(stream), Loader=_PwLoader)
    if isinstance(raw, dict):
        variables = {k.lstrip("$"): v for k, v in raw.items()}
        return {
            k.lstrip("$"): _materialize(v, variables)
            for k, v in raw.items()
        }
    return _materialize(raw, {})
