"""Deterministic fault injection — the chaos harness behind the failure tests.

The supervised cluster runtime (``parallel/supervisor.py``, the hardened
``parallel/cluster.py`` mesh) is only trustworthy if its failure paths are
exercised the same way every time. This module injects faults from a SEEDED
plan so a failure schedule replays exactly:

- worker kills at chosen commit ids (``GraphRunner.step`` calls
  :meth:`Chaos.maybe_kill` at every commit boundary);
- dropped / delayed / truncated exchange frames (``ClusterExchange._send``
  consults :meth:`Chaos.frame_action` for every DATA frame — heartbeats are
  exempt so the injection counter stream stays deterministic per peer pair);
- transient object-store write errors (:meth:`Chaos.wrap_object_store` wraps
  the persistence backend; the engine's retry layer must absorb them);
- coordinated-checkpoint-phase faults (``checkpoint`` plan entries, keyed on
  the per-process checkpoint ATTEMPT counter ``at``): ``pre_snapshot_kill``
  SIGKILLs a rank at the START of attempt N (so exactly N checkpoints have
  completed — the attempt counter ticks with the wall-clock cadence, which
  keeps the schedule deterministic on loaded hosts where commit-id gating
  races convergence), ``post_snapshot_kill``
  SIGKILLs a rank between its snapshot write and the manifest commit,
  ``torn_manifest`` tears the manifest bytes mid-write (a non-atomic store),
  ``snapshot_error`` fails the snapshot write transiently — every one must
  leave the PREVIOUS checkpoint recoverable bit-identically.

Environment contract::

    PATHWAY_CHAOS_SEED   integer seed (default 0)
    PATHWAY_CHAOS_PLAN   JSON plan, e.g.
        {"kill":   [{"rank": 0, "commit": 3, "run": 0}],
         "frames": {"drop_prob": 0.0, "delay_prob": 0.0, "delay_ms": 10,
                    "truncate_prob": 0.0},
         "rejoin": [{"rank": 0, "run": 1}],
         "backend": {"put_error_prob": 0.5, "max_errors": 4},
         "checkpoint": [{"op": "post_snapshot_kill", "rank": 0, "run": 0, "at": 1}],
         "scale": [{"op": "scale_join_kill", "rank": 2, "run": 0, "at": 0}],
         "replica": [{"op": "replica_kill", "replica": 1, "commit": 5}],
         "load": {"op": "oscillating_load", "period_s": 4.0,
                  "low": 50, "high": 400},
         "sched": {"seed": 7}}

``load`` shapes a DETERMINISTIC synthetic offered-load profile for the
autoscaler/backpressure tests and the ``bench.py autoscale`` section — load
generators consult :meth:`Chaos.load_rate` the way the engine consults kill
schedules, so an overload scenario replays exactly. Ops: ``load_spike``
(``low`` rows/s, stepping to ``high`` at ``at_s`` for ``duration_s``),
``oscillating_load`` (square wave between ``low``/``high`` every
``period_s`` — the flap-lock scenario), and ``noisy_neighbor`` (flood
parameters one REST client applies while the others stay polite:
``client``/``rps``/``rows``; read via :meth:`Chaos.noisy_neighbor`).

``sched`` pins the deterministic model-check scheduler's seed
(``internals/sched.py`` — :meth:`Chaos.sched_seed`): a chaos plan can name the
exact protocol interleaving a model-check suite replays, the same way it names
kill commits. ``PATHWAY_SCHED_SEED`` overrides it.

``run`` in a kill entry matches ``PATHWAY_RESTART_COUNT`` (set by the
supervisor, 0 for a first launch), so a kill fires once and the restarted
cluster survives the replayed schedule; an optional ``epoch`` field further
gates the kill on the live cluster epoch (surgical-restart protocol testing:
kill-one-rank-at-commit-N-in-epoch-E). ``rejoin`` entries drop a relaunched
rank's rejoin handshake (``ClusterExchange._connect_rejoin`` consults
:meth:`Chaos.drop_rejoin`), deterministically forcing the surgical →
restart-all escalation; ``run`` there matches the REPLACEMENT's restart count
when present (omitted = every surgical attempt for that rank is dropped —
each attempt is a fresh process, so ``run`` is the only cross-attempt key).
Determinism comes from per-stream ``random.Random``
instances keyed ``seed:kind:rank:peer`` — the Nth draw on a stream is a pure
function of the seed and N, never of wall clock or other streams.

With neither env var set, :func:`get_chaos` returns ``None`` and every hook is
a no-op attribute check on the caller's side — zero overhead in production.
"""

from __future__ import annotations

import json
import os
import random
import signal
from typing import Any, Dict, List, Optional


class ChaosBackendError(ConnectionError):
    """Injected transient object-store failure (retryable by design)."""


#: every NAMED plan op per plan key — one registry, greppable, and the
#: source of truth the CHAOS.md drift audit checks BOTH ways (every op here
#: has a documented row; every documented op exists here). Plan keys whose
#: entries carry no ``op`` field (kill/frames/rejoin/backend/sched) gate on
#: their own fields and are documented as whole sections instead.
PLAN_OPS: Dict[str, tuple] = {
    "checkpoint": (
        "pre_snapshot_kill",
        "post_snapshot_kill",
        "torn_manifest",
        "snapshot_error",
    ),
    "scale": (
        "scale_join_kill",
        "scale_drain_kill",
        "handoff_torn",
        "join_handoff_torn",
        "dedup_install_kill",
        "chunk_stream_kill",
        "dropped_scale_handshake",
        "scale_refused",
    ),
    "index": (
        "rebuild_kill",
        "tier_swap_torn",
        "quant",
    ),
    "replica": (
        "replica_kill",
        "replica_lag",
        "replica_torn_bootstrap",
    ),
    "load": (
        "load_spike",
        "oscillating_load",
        "noisy_neighbor",
    ),
}


class _FrameAction:
    """One injection decision for an outgoing exchange frame."""

    __slots__ = ("kind", "delay_s")

    def __init__(self, kind: str, delay_s: float = 0.0):
        self.kind = kind  # "pass" | "drop" | "delay" | "truncate"
        self.delay_s = delay_s

    def __repr__(self) -> str:  # test/debug readability
        return f"_FrameAction({self.kind!r}, {self.delay_s})"


_PASS = _FrameAction("pass")


class Chaos:
    """Seeded injection schedule, one instance per process."""

    def __init__(self, seed: int, plan: Dict[str, Any]):
        self.seed = seed
        self.plan = plan
        self.run_count = int(os.environ.get("PATHWAY_RESTART_COUNT", "0") or 0)
        self._kills: List[Dict[str, Any]] = list(plan.get("kill") or [])
        self._frames: Dict[str, Any] = dict(plan.get("frames") or {})
        self._rejoins: List[Dict[str, Any]] = [
            dict(e) for e in (plan.get("rejoin") or [])
        ]
        self._backend: Dict[str, Any] = dict(plan.get("backend") or {})
        self._checkpoint: List[Dict[str, Any]] = [
            dict(e) for e in (plan.get("checkpoint") or [])
        ]
        self._scale: List[Dict[str, Any]] = [
            dict(e) for e in (plan.get("scale") or [])
        ]
        self._index: List[Dict[str, Any]] = [
            dict(e) for e in (plan.get("index") or [])
        ]
        self._replica: List[Dict[str, Any]] = [
            dict(e) for e in (plan.get("replica") or [])
        ]
        self._load: Dict[str, Any] = dict(plan.get("load") or {})
        self._streams: Dict[str, random.Random] = {}
        self._backend_errors_left = int(self._backend.get("max_errors", 3))
        # coordinated-checkpoint attempt counter: bumped by the runner at the
        # START of every attempt, so `at` in a checkpoint entry deterministically
        # names the Nth attempt of this process incarnation (0-based)
        self.checkpoint_attempt = -1
        # elastic-membership attempt counter, same discipline: `at` in a
        # scale entry names the Nth transition attempt of this incarnation
        self.scale_attempt = -1
        # tiered-index background-rebuild attempt counter: `at` in an index
        # entry names the Nth rebuild scheduled by this incarnation
        self.rebuild_attempt = -1
        # observability for tests: what actually fired
        self.stats: Dict[str, int] = {
            "kills": 0,
            "frames_dropped": 0,
            "frames_delayed": 0,
            "frames_truncated": 0,
            "rejoins_dropped": 0,
            "backend_errors": 0,
            "checkpoint_faults": 0,
            "scale_faults": 0,
            "index_faults": 0,
            "replica_faults": 0,
        }

    # -- streams -------------------------------------------------------------

    def _stream(self, kind: str, *key: Any) -> random.Random:
        name = ":".join([str(self.seed), kind, *map(str, key)])
        rng = self._streams.get(name)
        if rng is None:
            rng = random.Random(name)
            self._streams[name] = rng
        return rng

    # -- worker kills ---------------------------------------------------------

    def maybe_kill(self, rank: int, commit_id: int, epoch: int = 0) -> None:
        """SIGKILL this process if the plan schedules a kill at (rank, commit)
        for the current run (restart) count — and, when the entry carries an
        ``epoch`` field, only in that cluster epoch (kill-one-rank-at-commit-N
        schedules that target a specific incarnation of the mesh). Called at
        every LIVE commit boundary; journal replay never re-fires a kill."""
        for entry in self._kills:
            want_epoch = entry.get("epoch")
            if (
                int(entry.get("rank", -1)) == rank
                and int(entry.get("commit", -1)) == commit_id
                and int(entry.get("run", 0)) == self.run_count
                and (want_epoch is None or int(want_epoch) == int(epoch))
            ):
                self.stats["kills"] += 1
                # SIGKILL is uncatchable, so the flight recorder dumps HERE —
                # the injected death is the one failure mode that can leave a
                # complete black box behind (deferred import: internals-layer
                # modules must stay light at module load)
                try:
                    from pathway_tpu.engine.profile import get_flight_recorder

                    recorder = get_flight_recorder()
                    recorder.record_event(
                        "chaos_kill", rank=rank, commit=commit_id, epoch=epoch
                    )
                    recorder.dump("chaos_kill")
                except Exception:
                    pass  # the kill must fire regardless
                os.kill(os.getpid(), signal.SIGKILL)

    # -- coordinated-checkpoint faults ----------------------------------------

    def begin_checkpoint_attempt(self) -> int:
        """Called by the runner at the start of every coordinated checkpoint
        attempt; returns the 0-based attempt index the ``at`` field gates on."""
        self.checkpoint_attempt += 1
        return self.checkpoint_attempt

    def checkpoint_fault(self, op: str, rank: int) -> bool:
        """True when the plan schedules fault ``op`` for this rank at the
        CURRENT checkpoint attempt (and restart count). ``at`` defaults to
        every attempt; ``run`` defaults to every incarnation."""
        for entry in self._checkpoint:
            if entry.get("op") != op:
                continue
            if int(entry.get("rank", -1)) != rank:
                continue
            want_run = entry.get("run")
            if want_run is not None and int(want_run) != self.run_count:
                continue
            want_at = entry.get("at")
            if want_at is not None and int(want_at) != self.checkpoint_attempt:
                continue
            self.stats["checkpoint_faults"] += 1
            self._record_injection(
                f"chaos_checkpoint_{op}", rank=rank, attempt=self.checkpoint_attempt
            )
            return True
        return False

    def maybe_checkpoint_kill(
        self, rank: int, commit_id: int, epoch: int = 0,
        op: str = "post_snapshot_kill",
    ) -> None:
        """SIGKILL this rank when a checkpoint-phase kill entry matches.
        ``post_snapshot_kill`` fires between the snapshot write and the
        manifest commit — the mid-protocol crash the manifest barrier exists
        to survive; ``pre_snapshot_kill`` fires at the start of the attempt,
        i.e. a plain rank death scheduled AFTER ``at`` completed checkpoints."""
        if not self.checkpoint_fault(op, rank):
            return
        self.stats["kills"] += 1
        try:
            from pathway_tpu.engine.profile import get_flight_recorder

            recorder = get_flight_recorder()
            recorder.record_event(
                "chaos_checkpoint_kill", rank=rank, commit=commit_id, epoch=epoch,
                attempt=self.checkpoint_attempt,
            )
            recorder.dump("chaos_checkpoint_kill")
        except Exception:
            pass  # the kill must fire regardless
        os.kill(os.getpid(), signal.SIGKILL)

    # -- elastic-membership faults ---------------------------------------------

    def begin_scale_attempt(self) -> int:
        """Called by the runner at the start of every membership-transition
        attempt; returns the 0-based attempt index ``at`` gates on."""
        self.scale_attempt += 1
        return self.scale_attempt

    def scale_fault(self, op: str, rank: int) -> bool:
        """True when the plan schedules membership fault ``op`` for this rank
        at the CURRENT scale attempt (and restart count). Ops:

        - ``scale_join_kill``   — SIGKILL a joiner before it installs;
        - ``scale_drain_kill``  — SIGKILL a donor/leaver mid-handoff (after
          the quiesce vote, before its fragments are acked durable);
        - ``handoff_torn``     — tear a handoff-fragment write (the read-back
          verification must fail the attempt's ack barrier, previous state
          stands, the transition retries);
        - ``join_handoff_torn`` — tear ONLY a handoff chunk carrying join
          arrangement state (chunked transport; read-back verification fails
          the ack barrier exactly like ``handoff_torn``);
        - ``dedup_install_kill`` — SIGKILL the importer right before it
          applies a chunk carrying dedup instance state (the install barrier
          fails, the previous topology's state stands, the ladder replays);
        - ``chunk_stream_kill`` — SIGKILL the donor after its FIRST chunk
          write: the stream has no chunk manifest yet, so the half-written
          stream reads as absent (complete-or-abort);
        - ``dropped_scale_handshake`` — drop a joiner's membership hello so
          its wiring fails typed and the supervisor escalates;
        - ``scale_refused``    — inject a preflight-vote refusal (the runner
          appends a synthetic refusal reason), exercising the autoscaler's
          typed refusal-backoff path without a non-reshardable graph.

        ``at`` defaults to every attempt; ``run`` defaults to every
        incarnation (joiner relaunches bump PATHWAY_RESTART_COUNT, the
        cross-attempt key — same contract as ``rejoin`` entries). Joiner-side
        ops fire in a fresh process where ``begin_scale_attempt`` never ran:
        that counts as attempt 0, so ``at: 0`` gates them too."""
        current_attempt = max(0, self.scale_attempt)
        for entry in self._scale:
            if entry.get("op") != op:
                continue
            if int(entry.get("rank", -1)) != rank:
                continue
            want_run = entry.get("run")
            if want_run is not None and int(want_run) != self.run_count:
                continue
            want_at = entry.get("at")
            if want_at is not None and int(want_at) != current_attempt:
                continue
            self.stats["scale_faults"] += 1
            self._record_injection(
                f"chaos_{op}", rank=rank, attempt=self.scale_attempt,
                run=self.run_count,
            )
            return True
        return False

    def maybe_scale_kill(self, rank: int, op: str, **details: Any) -> None:
        """SIGKILL this rank when a membership fault entry matches (the
        ``scale_join_kill`` / ``scale_drain_kill`` ops)."""
        if not self.scale_fault(op, rank):
            return
        self.stats["kills"] += 1
        try:
            from pathway_tpu.engine.profile import get_flight_recorder

            recorder = get_flight_recorder()
            recorder.record_event(
                f"chaos_{op}_kill", rank=rank, attempt=self.scale_attempt,
                **details,
            )
            recorder.dump(f"chaos_{op}")
        except Exception:
            pass  # the kill must fire regardless
        os.kill(os.getpid(), signal.SIGKILL)

    # -- tiered-index rebuild/swap faults ---------------------------------------

    def begin_rebuild_attempt(self) -> int:
        """Called by the tiered IVF store when it schedules a background
        rebuild; returns the 0-based attempt index ``at`` gates on."""
        self.rebuild_attempt += 1
        return self.rebuild_attempt

    def index_fault(self, op: str, rank: int) -> bool:
        """True when the plan schedules tiered-index fault ``op`` for this
        rank at the CURRENT rebuild attempt (and restart count). Ops:

        - ``rebuild_kill``   — SIGKILL the rank while a background index
          rebuild is in flight (the new generation must be discarded on
          recovery; journal replay rebuilds the index bit-identically);
        - ``tier_swap_torn`` — abort the generation swap at the commit
          boundary (the pending generation is dropped, the OLD generation
          keeps serving, and the next maintenance pass retries);
        - ``quant``          — abort a quantization-scale recalibration
          before the sidecar install (the OLD per-page scales keep serving;
          fp32 rows are untouched, so the exact rescore epilogue is
          unaffected and the next maintenance pass recalibrates).

        ``at`` defaults to every attempt; ``run`` defaults to every
        incarnation (the cross-restart key, same contract as ``scale``
        entries)."""
        current_attempt = max(0, self.rebuild_attempt)
        for entry in self._index:
            if entry.get("op") != op:
                continue
            if int(entry.get("rank", -1)) != rank:
                continue
            want_run = entry.get("run")
            if want_run is not None and int(want_run) != self.run_count:
                continue
            want_at = entry.get("at")
            if want_at is not None and int(want_at) != current_attempt:
                continue
            self.stats["index_faults"] += 1
            self._record_injection(
                f"chaos_{op}", rank=rank, attempt=self.rebuild_attempt,
                run=self.run_count,
            )
            return True
        return False

    def maybe_rebuild_kill(self, rank: int, **details: Any) -> None:
        """SIGKILL this rank when a ``rebuild_kill`` index entry matches —
        the kill lands while the background rebuild thread is mid-build, so
        recovery must come up serving the OLD generation (or a journal-replay
        rebuild), never a torn new one."""
        if not self.index_fault("rebuild_kill", rank):
            return
        self.stats["kills"] += 1
        try:
            from pathway_tpu.engine.profile import get_flight_recorder

            recorder = get_flight_recorder()
            recorder.record_event(
                "chaos_rebuild_kill", rank=rank, attempt=self.rebuild_attempt,
                **details,
            )
            recorder.dump("chaos_rebuild_kill")
        except Exception:
            pass  # the kill must fire regardless
        os.kill(os.getpid(), signal.SIGKILL)

    # -- read-replica faults -----------------------------------------------------

    def replica_fault(self, op: str, replica: int) -> bool:
        """True when the plan schedules replica fault ``op`` for this replica
        id (and restart count). Ops:

        - ``replica_torn_bootstrap`` — tear a bootstrap-fragment read so the
          checksum verification fails typed (the replica must refuse and stay
          OUT of rotation, never serve from a torn install);
        - ``replica_lag``  — matched via :meth:`replica_lag_s` (injected
          apply delay, the deterministic staleness-shed scenario);
        - ``replica_kill`` — matched via :meth:`maybe_replica_kill`.

        ``run`` defaults to every incarnation (replica relaunches bump
        PATHWAY_RESTART_COUNT — the cross-attempt key, same contract as
        ``rejoin`` entries)."""
        for entry in self._replica:
            if entry.get("op") != op:
                continue
            if int(entry.get("replica", -1)) != replica:
                continue
            want_run = entry.get("run")
            if want_run is not None and int(want_run) != self.run_count:
                continue
            self.stats["replica_faults"] += 1
            self._record_injection(
                f"chaos_{op}", replica=replica, run=self.run_count
            )
            return True
        return False

    def replica_lag_s(self, replica: int) -> float:
        """Injected per-frame apply delay (seconds) for this replica, or 0.0.
        A ``frames`` field bounds how many applies pay the delay (default:
        every apply while the entry matches) — the bounded form lets a test
        drive the replica stale past its bound and then watch it catch up."""
        for entry in self._replica:
            if entry.get("op") != "replica_lag":
                continue
            if int(entry.get("replica", -1)) != replica:
                continue
            want_run = entry.get("run")
            if want_run is not None and int(want_run) != self.run_count:
                continue
            frames_left = entry.get("frames")
            if frames_left is not None:
                if int(frames_left) <= 0:
                    continue
                entry["frames"] = int(frames_left) - 1
            self.stats["replica_faults"] += 1
            self._record_injection(
                "chaos_replica_lag", replica=replica, run=self.run_count
            )
            return float(entry.get("lag_s", 0.1))
        return 0.0

    def maybe_replica_kill(self, replica: int, commit_id: int) -> None:
        """SIGKILL this replica process when a ``replica_kill`` entry matches
        (``commit`` gates on the replica's APPLIED commit id — omitted fires
        at the first applied frame). The router must route around the corpse:
        no client-visible 5xx."""
        for entry in self._replica:
            if entry.get("op") != "replica_kill":
                continue
            if int(entry.get("replica", -1)) != replica:
                continue
            want_commit = entry.get("commit")
            if want_commit is not None and int(want_commit) != commit_id:
                continue
            want_run = entry.get("run")
            if want_run is not None and int(want_run) != self.run_count:
                continue
            self.stats["kills"] += 1
            self.stats["replica_faults"] += 1
            try:
                from pathway_tpu.engine.profile import get_flight_recorder

                recorder = get_flight_recorder()
                recorder.record_event(
                    "chaos_replica_kill", replica=replica, commit=commit_id,
                    run=self.run_count,
                )
                recorder.dump("chaos_replica_kill")
            except Exception:
                pass  # the kill must fire regardless
            os.kill(os.getpid(), signal.SIGKILL)

    # -- synthetic load profiles -----------------------------------------------

    def load_rate(self, elapsed_s: float) -> "Optional[float]":
        """Offered rows/s at ``elapsed_s`` into the run per the plan's
        ``load`` op, or None when no load profile is configured. A pure
        function of the plan and elapsed time — the autoscaler acceptance
        scenarios (ramp, spike, oscillation) replay exactly.

        - ``load_spike``: ``low`` until ``at_s``, then ``high`` for
          ``duration_s``, then ``low`` again;
        - ``oscillating_load``: square wave — ``high`` for the first half of
          every ``period_s`` window, ``low`` for the second (the scenario the
          controller's flap lock must survive)."""
        op = self._load.get("op")
        if op not in ("load_spike", "oscillating_load"):
            return None
        low = float(self._load.get("low", 0.0))
        high = float(self._load.get("high", low))
        if op == "load_spike":
            at_s = float(self._load.get("at_s", 0.0))
            duration_s = float(self._load.get("duration_s", 1.0))
            return high if at_s <= elapsed_s < at_s + duration_s else low
        period_s = max(1e-6, float(self._load.get("period_s", 2.0)))
        return high if (elapsed_s % period_s) < period_s / 2.0 else low

    def noisy_neighbor(self) -> "Optional[Dict[str, Any]]":
        """Flood parameters for the noisy-neighbor REST scenario (one client
        hammers ``/v1/retrieve`` while the others stay polite), or None.
        Keys: ``client`` (the flooding client id, default "noisy"), ``rps``
        (its request rate), ``rows`` (texts per request)."""
        if self._load.get("op") != "noisy_neighbor":
            return None
        return {
            "client": str(self._load.get("client", "noisy")),
            "rps": float(self._load.get("rps", 100.0)),
            "rows": int(self._load.get("rows", 4)),
        }

    # -- deterministic schedule seeds ------------------------------------------

    def sched_seed(self) -> "Optional[int]":
        """The plan's pinned model-check scheduler seed, or None. Consumed by
        ``internals/sched.py`` when neither an explicit seed nor
        ``PATHWAY_SCHED_SEED`` is given — chaos plans name protocol
        interleavings exactly like they name kill commits."""
        entry = self.plan.get("sched") or {}
        seed = entry.get("seed")
        return int(seed) if seed is not None else None

    # -- rejoin handshakes -----------------------------------------------------

    def drop_rejoin(self, rank: int) -> bool:
        """True when the plan schedules this relaunched rank's rejoin handshake
        to be dropped (the replacement's hello never reaches the survivors, so
        its wiring fails typed and the supervisor degrades to restart-all).

        Every replacement is a FRESH process that rebuilds this harness from
        the env, so cross-attempt gating must key on ``run`` (the
        replacement's ``PATHWAY_RESTART_COUNT`` — each escalation attempt has
        a distinct one), not on in-process counters. An entry without ``run``
        drops EVERY surgical attempt for that rank; recovery still terminates
        because the restart-all fallback never consults this schedule."""
        for entry in self._rejoins:
            if int(entry.get("rank", -1)) != rank:
                continue
            want_run = entry.get("run")
            if want_run is not None and int(want_run) != self.run_count:
                continue
            self.stats["rejoins_dropped"] += 1
            self._record_injection("chaos_rejoin_drop", rank=rank, run=self.run_count)
            return True
        return False

    # -- exchange frames -------------------------------------------------------

    def frame_action(self, rank: int, peer: int) -> _FrameAction:
        """Decide the fate of the next data frame ``rank -> peer``. Draws come
        from the per-(rank, peer) stream, so the schedule is independent of
        timing and of traffic to other peers."""
        if not self._frames:
            return _PASS
        rng = self._stream("frames", rank, peer)
        roll = rng.random()
        drop = float(self._frames.get("drop_prob", 0.0))
        trunc = float(self._frames.get("truncate_prob", 0.0))
        delay = float(self._frames.get("delay_prob", 0.0))
        if roll < drop:
            self.stats["frames_dropped"] += 1
            self._record_injection("chaos_frame_drop", rank=rank, peer=peer)
            return _FrameAction("drop")
        if roll < drop + trunc:
            self.stats["frames_truncated"] += 1
            self._record_injection("chaos_frame_truncate", rank=rank, peer=peer)
            return _FrameAction("truncate")
        if roll < drop + trunc + delay:
            self.stats["frames_delayed"] += 1
            return _FrameAction("delay", float(self._frames.get("delay_ms", 10)) / 1000.0)
        return _PASS

    @staticmethod
    def _record_injection(kind: str, **details: Any) -> None:
        """Destructive injections land in the flight recorder's event ring so
        a dump distinguishes injected faults from organic ones."""
        try:
            from pathway_tpu.engine.profile import get_flight_recorder

            get_flight_recorder().record_event(kind, **details)
        except Exception:
            pass

    # -- persistence backends --------------------------------------------------

    def wrap_object_store(self, store: Any) -> Any:
        """Wrap an ``ObjectStore`` so PUTs fail transiently per the plan (a
        bounded number of times — the retry layer above must converge)."""
        if not self._backend:
            return store
        return _ChaosObjectStore(store, self)

    def _put_should_fail(self, key: str) -> bool:
        if self._backend_errors_left <= 0:
            return False
        prob = float(self._backend.get("put_error_prob", 0.0))
        if prob <= 0.0:
            return False
        if self._stream("backend").random() < prob:
            self._backend_errors_left -= 1
            self.stats["backend_errors"] += 1
            return True
        return False


class _ChaosObjectStore:
    """Injects transient write errors in front of a real ``ObjectStore``.

    Deliberately duck-typed (not an ``ObjectStore`` subclass): internals-layer
    code must not import the persistence package at module load."""

    def __init__(self, inner: Any, chaos: Chaos):
        self._inner = inner
        self._chaos = chaos

    def put(self, key: str, data: bytes) -> None:
        if self._chaos._put_should_fail(key):
            raise ChaosBackendError(
                f"chaos: injected transient write error for {key!r} "
                f"(seed {self._chaos.seed})"
            )
        self._inner.put(key, data)

    def get(self, key: str) -> "bytes | None":
        return self._inner.get(key)

    def list(self, prefix: str) -> List[str]:
        return self._inner.list(prefix)

    def delete(self, key: str) -> None:
        self._inner.delete(key)


_chaos: Optional[Chaos] = None
_chaos_tried = False


def get_chaos() -> Optional[Chaos]:
    """The process-wide chaos harness, or None when no plan is configured.
    Built once from the env; :func:`reset_chaos` rebuilds (tests)."""
    global _chaos, _chaos_tried
    if _chaos_tried:
        return _chaos
    plan_env = os.environ.get("PATHWAY_CHAOS_PLAN")
    if plan_env:
        try:
            plan = json.loads(plan_env)
        except ValueError as exc:
            raise ValueError(
                f"PATHWAY_CHAOS_PLAN is not valid JSON: {exc}"
            ) from exc
        seed = int(os.environ.get("PATHWAY_CHAOS_SEED", "0") or 0)
        _chaos = Chaos(seed, plan)
    else:
        _chaos = None
    _chaos_tried = True
    return _chaos


def reset_chaos() -> None:
    """Drop the cached harness so the next :func:`get_chaos` re-reads the env."""
    global _chaos, _chaos_tried
    _chaos = None
    _chaos_tried = False
