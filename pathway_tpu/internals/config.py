"""Environment-driven runtime configuration.

Parity: reference ``src/engine/dataflow/config.rs:88`` (``Config::from_env`` —
``PATHWAY_THREADS``/``PATHWAY_PROCESSES``/``PATHWAY_PROCESS_ID``/``PATHWAY_FIRST_PORT``)
plus the record/replay env contract set by the CLI (``python/pathway/cli.py:166-284``:
``PATHWAY_REPLAY_STORAGE``, ``PATHWAY_SNAPSHOT_ACCESS``, ``PATHWAY_PERSISTENCE_MODE``,
``PATHWAY_CONTINUE_AFTER_REPLAY``) and ``internals/config.py`` (``pathway_config``).

Here processes are partitioned-ingest replicas (each process owns a hash-shard of the
source partitions — the analogue of ``parallel_readers``); on-device scale-out rides the
JAX mesh in ``pathway_tpu.parallel`` instead of OS threads.
"""

from __future__ import annotations

import os
import threading as _threading
from dataclasses import dataclass, field


def _int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    """Float knob from the env; blank or malformed values fall back to the
    default (an optional tuning knob must never kill the pipeline). One home
    for the parse so the mesh (cluster.py) and the supervisor read the shared
    PATHWAY_* knobs identically."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        return default


@dataclass
class PathwayConfig:
    threads: int = 1
    processes: int = 1
    process_id: int = 0
    first_port: int = 10000
    run_id: str | None = None
    monitoring_http_port: int | None = None
    replay_storage: str | None = None
    snapshot_access: str | None = None  # "record" | "replay" | None
    persistence_mode: str | None = None  # "batch" | "speedrun" | None
    continue_after_replay: bool = True

    @classmethod
    def from_env(cls) -> "PathwayConfig":
        port_env = os.environ.get("PATHWAY_MONITORING_HTTP_PORT")
        try:
            port = int(port_env) if port_env else None
        except ValueError:
            port = None  # malformed optional knob must not kill the pipeline
        cont_env = os.environ.get("PATHWAY_CONTINUE_AFTER_REPLAY")
        if cont_env is not None:
            cont = cont_env.lower() in ("true", "1", "yes")
        else:
            # like the reference: `pathway replay` stops after the recording unless
            # --continue; normal and record runs keep consuming realtime data
            cont = os.environ.get("PATHWAY_SNAPSHOT_ACCESS") != "replay"
        return cls(
            threads=max(_int_env("PATHWAY_THREADS", 1), 1),
            processes=max(_int_env("PATHWAY_PROCESSES", 1), 1),
            process_id=_int_env("PATHWAY_PROCESS_ID", 0),
            first_port=_int_env("PATHWAY_FIRST_PORT", 10000),
            run_id=os.environ.get("PATHWAY_RUN_ID"),
            monitoring_http_port=port,
            replay_storage=os.environ.get("PATHWAY_REPLAY_STORAGE"),
            snapshot_access=os.environ.get("PATHWAY_SNAPSHOT_ACCESS"),
            persistence_mode=os.environ.get("PATHWAY_PERSISTENCE_MODE") or None,
            continue_after_replay=cont,
        )


_tls = _threading.local()


def set_thread_config(config: "PathwayConfig | None") -> None:
    """Install (or clear, with None) a per-thread config override. Thread
    workers (``parallel.threads.run_threads``) use this to present themselves
    as rank ``process_id`` of a ``processes``-worker cluster — all the
    process-keyed machinery (cluster policies, key bases, persistence shards,
    parallel-reader partitioning) follows without knowing about threads."""
    _tls.override = config


def current_thread_config_override() -> "PathwayConfig | None":
    """The override active on THIS thread, if any — threads spawned on behalf
    of a worker (connector reader threads) must re-install it, since
    threading.local state does not inherit."""
    return getattr(_tls, "override", None)


def get_pathway_config() -> PathwayConfig:
    override = getattr(_tls, "override", None)
    if override is not None:
        return override
    return PathwayConfig.from_env()
