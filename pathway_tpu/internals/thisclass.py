"""``pw.this`` / ``pw.left`` / ``pw.right`` deferred column references.

Parity: reference ``internals/thisclass.py`` + ``internals/desugaring.py``.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import expression as expr


class ThisWildcard:
    """Deferred "all columns of this table" marker (minus exclusions); expanded
    by ``Table.select`` (reference ``*pw.this`` / ``pw.this.without(...)``)."""

    def __init__(self, kind: type, exclude: tuple = ()):
        self._kind = kind
        self._exclude = tuple(exclude)

    def __iter__(self):
        # ``select(*pw.this.without(x))`` unpacks the wildcard itself
        return iter((self,))


class ThisMetaclass(type):
    def __getattr__(cls, name: str) -> "ThisColumnReference":
        if name.startswith("__"):
            raise AttributeError(name)
        return ThisColumnReference(cls, name)

    def __getitem__(cls, name: str) -> Any:
        if isinstance(name, (list, tuple)):
            return [ThisColumnReference(cls, n) for n in name]
        return ThisColumnReference(cls, name)

    def __iter__(cls):
        # ``select(*pw.this)``: every column of the operated-on table
        return iter((ThisWildcard(cls),))

    def without(cls, *columns: Any) -> ThisWildcard:
        names = tuple(
            c.name if hasattr(c, "name") and not isinstance(c, str) else str(c)
            for c in columns
        )
        return ThisWildcard(cls, names)


class this(metaclass=ThisMetaclass):
    """Deferred reference to "the table this operation applies to"."""


class left(metaclass=ThisMetaclass):
    """Deferred reference to the left side of a join."""


class right(metaclass=ThisMetaclass):
    """Deferred reference to the right side of a join."""


class ThisColumnReference(expr.ColumnExpression):
    def __init__(self, kind: type, name: str):
        self._kind = kind
        self._name = name

    @property
    def name(self) -> str:
        return self._name

    def __repr__(self) -> str:
        return f"pw.{self._kind.__name__}.{self._name}"

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        raise TypeError(f"column {self._name!r} is not callable")


def substitute(e: Any, mapping: dict[type, Any]) -> Any:
    """Replace this/left/right references by concrete table column references.

    ``mapping`` maps the marker class (this/left/right) to a Table (or Joinable).
    """
    if not isinstance(e, expr.ColumnExpression):
        return e
    return _substitute(e, mapping)


def _substitute(e: expr.ColumnExpression, mapping: dict[type, Any]) -> expr.ColumnExpression:
    import copy

    if isinstance(e, ThisColumnReference):
        target = mapping.get(e._kind)
        if target is None:
            raise ValueError(f"cannot resolve {e!r} in this context")
        if e._name == "id":
            return target.id
        return target[e._name]
    if isinstance(e, expr.ColumnReference):
        # a reference to a this-substituted table may itself need rebinding when the
        # table participating in the op was replaced (e.g. ix); leave as-is
        return e
    clone = copy.copy(e)
    for attr, value in list(vars(e).items()):
        if isinstance(value, expr.ColumnExpression):
            setattr(clone, attr, _substitute(value, mapping))
        elif isinstance(value, tuple) and any(isinstance(v, expr.ColumnExpression) for v in value):
            setattr(
                clone,
                attr,
                tuple(
                    _substitute(v, mapping) if isinstance(v, expr.ColumnExpression) else v
                    for v in value
                ),
            )
        elif isinstance(value, dict) and any(
            isinstance(v, expr.ColumnExpression) for v in value.values()
        ):
            setattr(
                clone,
                attr,
                {
                    k: _substitute(v, mapping) if isinstance(v, expr.ColumnExpression) else v
                    for k, v in value.items()
                },
            )
    return clone
