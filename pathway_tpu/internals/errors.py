"""Error-log tables — ``pw.global_error_log`` / ``pw.local_error_log``.

Parity: reference ``internals/errors.py`` + ``Graph::error_log`` (``graph.rs:996``):
with ``pw.run(terminate_on_error=False)`` a raising UDF poisons its cell with ``Error``
and appends a row (operator_id, message, trace) to the error-log table instead of
failing the run.
"""

from __future__ import annotations

import contextlib
from typing import Any, Generator, List

import numpy as np

from pathway_tpu.engine.columnar import Delta
from pathway_tpu.engine.datasource import DataSource
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import sequential_keys
from pathway_tpu.internals.parse_graph import G


class ErrorLogSource(DataSource):
    """Engine-thread error collector; drained one commit after the errors occur."""

    def __init__(self) -> None:
        self.pending: List[tuple] = []
        self._seq = 0

    def push(self, operator_id: int, message: str, trace: Any = None) -> None:
        self.pending.append((operator_id, message, trace))

    def on_start(self) -> None:
        pass

    def next_batch(self, column_names: List[str]) -> Delta:
        if not self.pending:
            return Delta.empty(column_names)
        rows, self.pending = self.pending, []
        n = len(rows)
        keys = sequential_keys(self._seq, n)
        self._seq += n
        columns = {}
        for j, name in enumerate(["operator_id", "message", "trace"]):
            col = np.empty(n, dtype=object)
            for i, row in enumerate(rows):
                col[i] = row[j]
            columns[name] = col
        return Delta(keys, np.ones(n, dtype=np.int64), columns)

    def is_finished(self) -> bool:
        return not self.pending

    def offset_state(self) -> dict:
        return {"seq": self._seq}

    def restore(self, offset: dict, state_deltas: list, tail: dict | None) -> None:
        self._seq = offset.get("seq", 0)


def _error_log_schema() -> sch.SchemaMetaclass:
    from pathway_tpu.internals import dtype as dt

    return sch.schema_from_columns(
        {
            "operator_id": sch.ColumnSchema("operator_id", dt.INT),
            "message": sch.ColumnSchema("message", dt.STR),
            "trace": sch.ColumnSchema("trace", dt.ANY),
        },
        "ErrorLog",
    )


def global_error_log() -> Any:
    """The run's error-log table (created lazily, one per graph)."""
    from pathway_tpu.internals.table import Table

    graph = G._current
    existing = getattr(graph, "_global_error_log", None)
    if existing is not None:
        return existing
    source = ErrorLogSource()
    node = G.add_node(pg.InputNode(source=source, name="error_log"))
    table = Table(node, _error_log_schema(), name="error_log")
    graph._global_error_log = table
    graph._error_log_source = source
    graph.error_logs.append(table)
    return table


@contextlib.contextmanager
def local_error_log() -> Generator[Any, None, None]:
    """Scoped error log: errors raised while the context is active go to this table."""
    from pathway_tpu.internals.table import Table

    source = ErrorLogSource()
    node = G.add_node(pg.InputNode(source=source, name="local_error_log"))
    table = Table(node, _error_log_schema(), name="local_error_log")
    graph = G._current
    stack = getattr(graph, "_error_log_stack", None)
    if stack is None:
        stack = graph._error_log_stack = []
    stack.append(source)
    try:
        yield table
    finally:
        stack.pop()


