"""User-frame trace capture for operator errors.

Parity: reference ``internals/trace.py`` — every operator remembers the user code line
that created it, so an engine error during execution points at the user's pipeline code
(``EngineErrorWithTrace``), not at framework internals.
"""

from __future__ import annotations


from dataclasses import dataclass
from typing import Any, Optional


@dataclass(frozen=True)
class Frame:
    filename: str
    line_number: int | None
    line: str | None
    function: str

    def is_external(self) -> bool:
        return _is_external_path(self.filename)


def _is_external_path(filename: str) -> bool:
    normalized = filename.replace("\\", "/")  # windows tracebacks
    if "tests/test_" in normalized:
        return True
    exclude = ["pathway_tpu/internals", "pathway_tpu/io", "pathway_tpu/stdlib",
               "pathway_tpu/debug", "pathway_tpu/engine", "pathway_tpu/xpacks"]
    return all(pattern not in normalized for pattern in exclude)


def capture_user_frame() -> Optional[Frame]:
    """The innermost stack frame belonging to user code (not the framework).

    Walks raw frames (cheap) and reads source for the single matched frame only —
    this runs on every operator creation."""
    import linecache
    import sys

    frame = sys._getframe(1)
    while frame is not None:
        filename = frame.f_code.co_filename
        if _is_external_path(filename):
            lineno = frame.f_lineno
            return Frame(
                filename=filename,
                line_number=lineno,
                line=linecache.getline(filename, lineno).rstrip() or None,
                function=frame.f_code.co_name,
            )
        frame = frame.f_back
    return None


class EngineErrorWithTrace(Exception):
    """Engine failure annotated with the user line that defined the failing operator."""

    def __init__(self, cause: BaseException, operator: str, frame: Optional[Frame]):
        self.cause = cause
        self.operator = operator
        self.user_frame = frame
        location = ""
        if frame is not None:
            location = (
                f"\noccurred in operator {operator!r} defined at "
                f"{frame.filename}:{frame.line_number}"
            )
            if frame.line:
                location += f"\n    {frame.line.strip()}"
        else:
            location = f"\noccurred in operator {operator!r}"
        super().__init__(f"{type(cause).__name__}: {cause}{location}")


def add_error_context(exc: BaseException, node: Any) -> BaseException:
    """Wrap ``exc`` with the node's creation trace (no-op if already wrapped)."""
    if isinstance(exc, EngineErrorWithTrace):
        return exc
    frame = getattr(node, "user_frame", None)
    return EngineErrorWithTrace(exc, getattr(node, "name", node.kind), frame)
