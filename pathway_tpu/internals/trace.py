"""User-frame trace capture for operator errors.

Parity: reference ``internals/trace.py`` — every operator remembers the user code line
that created it, so an engine error during execution points at the user's pipeline code
(``EngineErrorWithTrace``), not at framework internals.
"""

from __future__ import annotations

import traceback
from dataclasses import dataclass
from typing import Any, Optional


@dataclass(frozen=True)
class Frame:
    filename: str
    line_number: int | None
    line: str | None
    function: str

    def is_external(self) -> bool:
        if "tests/test_" in self.filename:
            return True
        exclude = ["pathway_tpu/internals", "pathway_tpu/io", "pathway_tpu/stdlib",
                   "pathway_tpu/debug", "pathway_tpu/engine", "pathway_tpu/xpacks"]
        return all(pattern not in self.filename for pattern in exclude)


def capture_user_frame() -> Optional[Frame]:
    """The innermost stack frame belonging to user code (not the framework)."""
    for entry in reversed(traceback.extract_stack()[:-1]):
        frame = Frame(
            filename=entry.filename,
            line_number=entry.lineno,
            line=entry.line,
            function=entry.name,
        )
        if frame.is_external():
            return frame
    return None


class EngineErrorWithTrace(Exception):
    """Engine failure annotated with the user line that defined the failing operator."""

    def __init__(self, cause: BaseException, operator: str, frame: Optional[Frame]):
        self.cause = cause
        self.operator = operator
        self.user_frame = frame
        location = ""
        if frame is not None:
            location = (
                f"\noccurred in operator {operator!r} defined at "
                f"{frame.filename}:{frame.line_number}"
            )
            if frame.line:
                location += f"\n    {frame.line.strip()}"
        else:
            location = f"\noccurred in operator {operator!r}"
        super().__init__(f"{type(cause).__name__}: {cause}{location}")


def add_error_context(exc: BaseException, node: Any) -> BaseException:
    """Wrap ``exc`` with the node's creation trace (no-op if already wrapped)."""
    if isinstance(exc, EngineErrorWithTrace):
        return exc
    frame = getattr(node, "user_frame", None)
    return EngineErrorWithTrace(exc, getattr(node, "name", node.kind), frame)
