"""User-facing relational Table API.

Parity: reference ``python/pathway/internals/table.py`` (class ``Table``, ``:52``) — the
declarative surface (select/filter/groupby/join/ix/concat/update/flatten/sort/deduplicate...)
that lowers to graph nodes executed incrementally by the TPU engine. The mechanism differs from
the reference (no DD arrangements; batch deltas over columnar state, JAX kernels for the dense
paths) but the contract is the same.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.parse_graph import G, Universe, new_universe, universe_solver


class Joinable:
    """Common base for Table and JoinResult (reference ``Joinable``)."""


def _name_of(arg: Any) -> str:
    if isinstance(arg, expr.ColumnReference):
        return arg.name
    if isinstance(arg, thisclass.ThisColumnReference):
        return arg.name
    if isinstance(arg, str):
        return arg
    raise ValueError(f"cannot infer a column name from {arg!r}")


class Table(Joinable):
    """A keyed collection of rows with typed columns, updated incrementally."""

    def __init__(
        self,
        node: pg.Node,
        schema: sch.SchemaMetaclass,
        universe: Universe | None = None,
        name: str = "table",
    ):
        self._node = node
        self._schema = schema
        self._universe = universe if universe is not None else new_universe()
        self._name = name
        node.output = self

    # -- metadata -----------------------------------------------------------

    @property
    def schema(self) -> sch.SchemaMetaclass:
        return self._schema

    @property
    def id(self) -> expr.ColumnReference:
        return expr.ColumnReference(self, "id")

    def column_names(self) -> list[str]:
        return self._schema.column_names()

    def keys(self) -> Dict[str, sch.ColumnSchema]:
        return self._schema.columns()

    def typehints(self) -> Dict[str, Any]:
        return self._schema.typehints()

    def __repr__(self) -> str:
        return f"<pw.Table {self._name!r} schema={self._schema!r}>"

    # -- column access ------------------------------------------------------

    def __getattr__(self, name: str) -> expr.ColumnReference:
        if name.startswith("__") or name in ("_node", "_schema", "_universe", "_name"):
            raise AttributeError(name)
        if name not in self._schema.columns():
            raise AttributeError(f"table has no column {name!r}; columns: {self.column_names()}")
        return expr.ColumnReference(self, name)

    def __getitem__(self, name: Any) -> Any:
        if isinstance(name, (list, tuple)):
            return [self[n] for n in name]
        if isinstance(name, expr.ColumnReference):
            name = name.name
        if isinstance(name, thisclass.ThisColumnReference):
            name = name.name
        if name == "id":
            return self.id
        if name not in self._schema.columns():
            raise KeyError(f"table has no column {name!r}; columns: {self.column_names()}")
        return expr.ColumnReference(self, name)

    def __iter__(self):
        raise TypeError("Table is not iterable; use pw.debug helpers to inspect contents")

    @property
    def C(self) -> "Table":
        return self

    # -- desugaring ---------------------------------------------------------

    def _resolve(self, e: Any) -> expr.ColumnExpression:
        e = thisclass.substitute(e, {thisclass.this: self})
        return expr.smart_coerce(e)

    def _infer_dtype(self, e: expr.ColumnExpression) -> dt.DType:
        from pathway_tpu.internals.type_interpreter import infer_dtype

        return infer_dtype(e)

    def _make_output_schema(self, exprs: Dict[str, expr.ColumnExpression], name: str) -> sch.SchemaMetaclass:
        columns = {
            out_name: sch.ColumnSchema(out_name, self._infer_dtype(e))
            for out_name, e in exprs.items()
        }
        return sch.schema_from_columns(columns, name=name)

    # -- core ops -----------------------------------------------------------

    def select(self, *args: Any, **kwargs: Any) -> "Table":
        """Project/compute columns; keys are preserved (reference ``table.py`` select)."""
        from pathway_tpu.internals.thisclass import ThisWildcard

        exprs: Dict[str, expr.ColumnExpression] = {}
        for arg in args:
            if isinstance(arg, ThisWildcard):
                from pathway_tpu.internals import thisclass as _tc

                if arg._kind is not _tc.this:
                    raise TypeError(
                        f"*pw.{arg._kind.__name__} wildcards only apply inside a "
                        "join's select; use *pw.this on a plain table"
                    )
                # ``*pw.this`` / ``*pw.this.without(...)``: all columns except
                # the exclusions; later kwargs may shadow individual names
                for n in self.column_names():
                    if n not in arg._exclude:
                        exprs[n] = self[n]
                continue
            exprs[_name_of(arg)] = self._resolve(arg)
        for out_name, e in kwargs.items():
            exprs[out_name] = self._resolve(e)
        node = G.add_node(pg.RowwiseNode(inputs=[self], exprs=exprs))
        out_schema = self._make_output_schema(exprs, "select")
        result = Table(node, out_schema, universe=self._universe, name="select")
        node.config["exprs"] = exprs
        return result

    def with_columns(self, *args: Any, **kwargs: Any) -> "Table":
        existing: Dict[str, Any] = {name: self[name] for name in self.column_names()}
        for arg in args:
            existing[_name_of(arg)] = arg
        existing.update(kwargs)
        return self.select(**existing)

    def without(self, *columns: Any) -> "Table":
        drop = {_name_of(c) for c in columns}
        keep = {n: self[n] for n in self.column_names() if n not in drop}
        return self.select(**keep)

    def rename_columns(self, **kwargs: Any) -> "Table":
        # new_name=old_column
        mapping = {new: _name_of(old) for new, old in kwargs.items()}
        exprs = {n: self[n] for n in self.column_names() if n not in mapping.values()}
        for new, old in mapping.items():
            exprs[new] = self[old]
        return self.select(**exprs)

    def rename_by_dict(self, names_mapping: Mapping[Any, str]) -> "Table":
        mapping = {_name_of(old): new for old, new in names_mapping.items()}
        exprs = {mapping.get(n, n): self[n] for n in self.column_names()}
        return self.select(**exprs)

    def rename(self, names_mapping: Mapping[Any, str] | None = None, **kwargs: Any) -> "Table":
        if names_mapping is not None:
            return self.rename_by_dict(names_mapping)
        return self.rename_columns(**kwargs)

    def filter(self, filter_expression: Any) -> "Table":
        e = self._resolve(filter_expression)
        for ref in e._column_refs:
            if ref.table is self or ref.table._universe is self._universe:
                continue
            if universe_solver.query_are_equal(ref.table._universe, self._universe):
                continue
            # resolving a foreign-universe column per THIS table's row keys
            # would silently produce misses (reference raises the same way)
            raise ValueError(
                f"filter: column {ref.name!r} belongs to a table with a "
                "different universe; use promise_universes_are_equal or filter "
                "on this table's own columns"
            )
        node = G.add_node(pg.FilterNode(inputs=[self], expression=e))
        result = Table(node, self._schema, name="filter")
        universe_solver.register_subset(result._universe, self._universe)
        return result

    def split(self, split_expression: Any) -> tuple["Table", "Table"]:
        positive = self.filter(split_expression)
        negative = self.filter(~self._resolve(split_expression))
        return positive, negative

    def copy(self) -> "Table":
        return self.select(**{n: self[n] for n in self.column_names()})

    # -- groupby / reduce ---------------------------------------------------

    def groupby(
        self,
        *args: Any,
        id: Any = None,
        sort_by: Any = None,
        instance: Any = None,
        **kwargs: Any,
    ) -> "GroupedTable":
        from pathway_tpu.internals.groupbys import GroupedTable

        grouping = [self._resolve(a) for a in args]
        names = [_name_of(a) for a in args]
        if instance is not None:
            grouping.append(self._resolve(instance))
            names.append(_name_of(instance))
        if id is not None:
            grouping = [self._resolve(id)]
            names = ["id"]
        return GroupedTable(
            self,
            grouping,
            names,
            set_id=id is not None,
            sort_by=self._resolve(sort_by) if sort_by is not None else None,
        )

    def reduce(self, *args: Any, **kwargs: Any) -> "Table":
        return self.groupby().reduce(*args, **kwargs)

    def deduplicate(
        self,
        *,
        value: Any = None,
        instance: Any = None,
        acceptor: Callable[[Any, Any], bool] | None = None,
        persistent_id: str | None = None,
        name: str | None = None,
    ) -> "Table":
        """Keep one row per instance, advancing only when ``acceptor(new, old)`` accepts
        (reference ``table.py`` deduplicate / stateful deduplicate)."""
        value_e = self._resolve(value) if value is not None else None
        instance_e = self._resolve(instance) if instance is not None else None
        node = G.add_node(
            pg.DeduplicateNode(
                inputs=[self], value=value_e, instance=instance_e, acceptor=acceptor
            )
        )
        return Table(node, self._schema, name="deduplicate")

    # -- joins --------------------------------------------------------------

    def join(
        self,
        other: "Table",
        *on: Any,
        id: Any = None,
        how: Any = None,
        left_instance: Any = None,
        right_instance: Any = None,
    ) -> "JoinResult":
        from pathway_tpu.internals.joins import JoinKind, JoinResult

        kind = how if how is not None else JoinKind.INNER
        return JoinResult(
            self, other, on, kind, id=id, left_instance=left_instance, right_instance=right_instance
        )

    def join_inner(self, other: "Table", *on: Any, **kw: Any) -> "JoinResult":
        from pathway_tpu.internals.joins import JoinKind

        return self.join(other, *on, how=JoinKind.INNER, **kw)

    def join_left(self, other: "Table", *on: Any, **kw: Any) -> "JoinResult":
        from pathway_tpu.internals.joins import JoinKind

        return self.join(other, *on, how=JoinKind.LEFT, **kw)

    def join_right(self, other: "Table", *on: Any, **kw: Any) -> "JoinResult":
        from pathway_tpu.internals.joins import JoinKind

        return self.join(other, *on, how=JoinKind.RIGHT, **kw)

    def join_outer(self, other: "Table", *on: Any, **kw: Any) -> "JoinResult":
        from pathway_tpu.internals.joins import JoinKind

        return self.join(other, *on, how=JoinKind.OUTER, **kw)

    # -- pointer ops --------------------------------------------------------

    def pointer_from(self, *args: Any, optional: bool = False, instance: Any = None) -> expr.PointerExpression:
        return expr.PointerExpression(
            self,
            *[self._resolve(a) for a in args],
            optional=optional,
            instance=instance,
        )

    def ix(
        self,
        expression: Any,
        *,
        optional: bool = False,
        context: Any = None,
        allow_misses: bool = False,
    ) -> "Table":
        """Reindex this table by pointers coming from another table's column."""
        key_expr = expr.smart_coerce(expression)
        refs = key_expr._column_refs
        if context is not None:
            # constant-key lookups broadcast across an explicit calling table
            source = context
        elif refs:
            source = refs[0].table
        elif isinstance(key_expr, expr.PointerExpression):
            # zero-argument pointer_from still knows its origin table
            source = key_expr._table
        else:
            raise ValueError("ix requires an expression over some table's columns")
        node = G.add_node(
            pg.IxNode(
                inputs=[source, self],
                key_expression=key_expr,
                optional=optional or allow_misses,
            )
        )
        result = Table(node, self._schema, universe=source._universe, name="ix")
        return result

    def ix_ref(self, *args: Any, optional: bool = False, context: Any = None, instance: Any = None) -> "Table":
        """Row lookup by primary-key VALUES (reference ``table.ix_ref``):
        ``t.ix_ref(q.key)`` re-keys through ``t.pointer_from`` — matching keys
        assigned by ``with_id_from``/primary-key schemas. Constant args
        broadcast the looked-up row across ``context``'s universe (pass
        ``context=...`` when calling from another table; without it the
        broadcast spans the target's own universe)."""
        return self.ix(
            self.pointer_from(*args, instance=instance), optional=optional, context=context
        )

    def _gradual_broadcast(
        self,
        threshold_table: "Table",
        lower_column: expr.ColumnReference,
        value_column: expr.ColumnReference,
        upper_column: expr.ColumnReference,
    ) -> "Table":
        """Add an ``apx_value`` column broadcasting the threshold table's
        (lower, value, upper) band with per-key staggering + hysteresis (reference
        ``Table._gradual_broadcast`` over ``gradual_broadcast.rs``; used by
        louvain refinement to bound retraction churn)."""
        from pathway_tpu.internals import dtype as dt_mod
        from pathway_tpu.internals import schema as sch_mod

        node = G.add_node(
            pg.GradualBroadcastNode(
                inputs=[self, threshold_table],
                lower=lower_column.name,
                value=value_column.name,
                upper=upper_column.name,
            )
        )
        schema = sch_mod.schema_from_columns(
            {
                **self._schema.columns(),
                "apx_value": sch_mod.ColumnSchema("apx_value", dt_mod.FLOAT),
            },
            name="gradual_broadcast",
        )
        result = Table(node, schema, name="gradual_broadcast")
        universe_solver.register_subset(result._universe, self._universe)
        return result

    def having(self, *indexers: expr.ColumnReference) -> "Table":
        """Restrict to rows whose pointer exists in the indexer's table."""
        # the indexer tables are real dataflow inputs: their deltas drive the
        # membership counts (HavingEvaluator reads input_deltas[1:])
        node = G.add_node(
            pg.HavingNode(
                inputs=[self, *(ix.table for ix in indexers)],
                indexers=list(indexers),
            )
        )
        result = Table(node, self._schema, name="having")
        universe_solver.register_subset(result._universe, self._universe)
        return result

    # -- universe ops -------------------------------------------------------

    def update_rows(self, other: "Table") -> "Table":
        """Union of rows; on key clash ``other`` wins (reference update_rows)."""
        schema = _merge_schema_strict(self._schema, other._schema, "update_rows")
        node = G.add_node(pg.UpdateRowsNode(inputs=[self, other]))
        result = Table(node, schema, name="update_rows")
        universe_solver.register_union(
            result._universe, [self._universe, other._universe]
        )
        return result

    def update_cells(self, other: "Table") -> "Table":
        """Update values of other's columns on matching keys (other ⊆ self)."""
        unknown = [c for c in other.column_names() if c not in self.column_names()]
        if unknown:
            # silently ignoring them would make typos no-ops (reference raises)
            raise ValueError(
                f"update_cells: column(s) {unknown} do not exist in the updated "
                f"table (columns: {self.column_names()})"
            )
        node = G.add_node(pg.UpdateCellsNode(inputs=[self, other]))
        return Table(node, self._schema, universe=self._universe, name="update_cells")

    def __lshift__(self, other: "Table") -> "Table":
        return self.update_cells(other)

    def concat(self, *others: "Table") -> "Table":
        """Disjoint union of rows; runtime error on key clash."""
        tables = [self, *others]
        schema = tables[0]._schema
        for t in tables[1:]:
            schema = _merge_schema_strict(schema, t._schema, "concat")
        node = G.add_node(pg.ConcatNode(inputs=tables, reindex=False))
        result = Table(node, schema, name="concat")
        universe_solver.register_union(
            result._universe, [t._universe for t in tables]
        )
        return result

    def concat_reindex(self, *others: "Table") -> "Table":
        tables = [self, *others]
        schema = tables[0]._schema
        for t in tables[1:]:
            schema = _merge_schema_strict(schema, t._schema, "concat_reindex")
        node = G.add_node(pg.ConcatNode(inputs=tables, reindex=True))
        return Table(node, schema, name="concat_reindex")

    def intersect(self, *others: "Table") -> "Table":
        node = G.add_node(pg.IntersectNode(inputs=[self, *others]))
        result = Table(node, self._schema, name="intersect")
        universe_solver.register_intersection(
            result._universe, [self._universe, *(o._universe for o in others)]
        )
        return result

    def difference(self, other: "Table") -> "Table":
        node = G.add_node(pg.DifferenceNode(inputs=[self, other]))
        result = Table(node, self._schema, name="difference")
        universe_solver.register_difference(
            result._universe, self._universe, other._universe
        )
        return result

    def restrict(self, other: "Table") -> "Table":
        if not universe_solver.query_is_subset(other._universe, self._universe):
            raise ValueError(
                "table.restrict(other): other's universe is not a subset of table's; "
                "use promise_universe_is_subset_of first"
            )
        node = G.add_node(pg.RestrictNode(inputs=[self, other]))
        return Table(node, self._schema, universe=other._universe, name="restrict")

    def with_universe_of(self, other: "Table") -> "Table":
        if not universe_solver.query_are_equal(self._universe, other._universe):
            raise ValueError(
                "with_universe_of: universes not known to be equal; "
                "use promise_universes_are_equal first"
            )
        node = G.add_node(pg.WithUniverseOfNode(inputs=[self, other]))
        return Table(node, self._schema, universe=other._universe, name="with_universe_of")

    def promise_universes_are_disjoint(self, other: "Table") -> "Table":
        universe_solver.register_disjoint(self._universe, other._universe)
        return self

    def promise_universe_is_subset_of(self, other: "Table") -> "Table":
        universe_solver.register_subset(self._universe, other._universe)
        return self

    def promise_universe_is_equal_to(self, other: "Table") -> "Table":
        universe_solver.register_equal(self._universe, other._universe)
        return self

    def promise_universes_are_equal(self, other: "Table") -> "Table":
        return self.promise_universe_is_equal_to(other)

    # -- reindex ------------------------------------------------------------

    def with_id(self, new_index: Any) -> "Table":
        e = self._resolve(new_index)
        node = G.add_node(pg.ReindexNode(inputs=[self], expression=e))
        return Table(node, self._schema, name="with_id")

    def with_id_from(self, *args: Any, instance: Any = None) -> "Table":
        e = self.pointer_from(*args, instance=instance)
        return self.with_id(e)

    # -- flatten / sort -----------------------------------------------------

    def flatten(self, to_flatten: Any, *, origin_id: str | None = None) -> "Table":
        flat_ref = self._resolve(to_flatten)
        name = _name_of(to_flatten)
        node = G.add_node(
            pg.FlattenNode(inputs=[self], expression=flat_ref, flat_name=name, origin_id=origin_id)
        )
        columns = dict(self._schema.columns())
        inner = columns[name].dtype
        if isinstance(inner, dt.List_):
            columns[name] = sch.ColumnSchema(name, inner.wrapped)
        elif isinstance(inner, dt.Tuple_) and inner.args:
            columns[name] = sch.ColumnSchema(name, inner.args[0])
        elif inner == dt.STR:
            columns[name] = sch.ColumnSchema(name, dt.STR)
        else:
            columns[name] = sch.ColumnSchema(name, dt.ANY)
        if origin_id:
            columns[origin_id] = sch.ColumnSchema(origin_id, dt.POINTER)
        schema = sch.schema_from_columns(columns, "flatten")
        return Table(node, schema, name="flatten")

    def sort(self, key: Any, instance: Any = None) -> "Table":
        key_e = self._resolve(key)
        instance_e = self._resolve(instance) if instance is not None else None
        node = G.add_node(pg.SortNode(inputs=[self], key=key_e, instance=instance_e))
        columns = {
            "prev": sch.ColumnSchema("prev", dt.Optional_(dt.POINTER)),
            "next": sch.ColumnSchema("next", dt.Optional_(dt.POINTER)),
        }
        schema = sch.schema_from_columns(columns, "sort")
        return Table(node, schema, universe=self._universe, name="sort")

    # -- typing -------------------------------------------------------------

    def cast_to_types(self, **kwargs: Any) -> "Table":
        exprs = {
            n: (expr.cast(kwargs[n], self[n]) if n in kwargs else self[n])
            for n in self.column_names()
        }
        return self.select(**exprs)

    def update_types(self, **kwargs: Any) -> "Table":
        exprs = {
            n: (expr.declare_type(kwargs[n], self[n]) if n in kwargs else self[n])
            for n in self.column_names()
        }
        return self.select(**exprs)

    # -- slicing ------------------------------------------------------------

    @property
    def slice(self) -> "TableSlice":
        return TableSlice(self, {n: self[n] for n in self.column_names()})

    # -- errors / asof-now --------------------------------------------------

    def remove_errors(self) -> "Table":
        node = G.add_node(pg.RemoveErrorsNode(inputs=[self]))
        result = Table(node, self._schema, name="remove_errors")
        universe_solver.register_subset(result._universe, self._universe)
        return result

    def _buffer(self, threshold: Any, time: Any) -> "Table":
        """Postpone rows until the stream's time passes ``threshold`` (reference
        ``Table._buffer`` → ``time_column.rs:255``)."""
        node = G.add_node(
            pg.BufferNode(
                inputs=[self],
                threshold=self._resolve(threshold),
                time=self._resolve(time),
            )
        )
        return Table(node, self._schema, name="buffer")

    def _freeze(self, threshold: Any, time: Any) -> "Table":
        """Ignore rows arriving after the stream's time passed ``threshold`` (reference
        ``Table._freeze`` → ``time_column.rs:631``)."""
        node = G.add_node(
            pg.FreezeNode(
                inputs=[self],
                threshold=self._resolve(threshold),
                time=self._resolve(time),
            )
        )
        result = Table(node, self._schema, name="freeze")
        universe_solver.register_subset(result._universe, self._universe)
        return result

    def _forget(
        self, threshold: Any, time: Any, mark_forgetting_records: bool = True
    ) -> "Table":
        """Retract rows once the stream's time passes ``threshold`` (reference
        ``Table._forget`` → ``time_column.rs:556``)."""
        node = G.add_node(
            pg.ForgetNode(
                inputs=[self],
                threshold=self._resolve(threshold),
                time=self._resolve(time),
                mark=mark_forgetting_records,
            )
        )
        return Table(node, self._schema, name="forget")

    def _forget_immediately(self) -> "Table":
        node = G.add_node(pg.AsofNowUpdateNode(inputs=[self], mode="forget"))
        return Table(node, self._schema, name="forget_immediately")

    def _filter_out_results_of_forgetting(self) -> "Table":
        node = G.add_node(pg.AsofNowUpdateNode(inputs=[self], mode="filter_forgotten"))
        return Table(node, self._schema, name="filter_out_forgetting")

    def _external_index_as_of_now(
        self,
        index_table: "Table",
        *,
        index_column: expr.ColumnReference,
        query_column: expr.ColumnReference,
        index_factory: Any,
        res_type: dt.DType = dt.ANY,
        query_responses_limit_column: expr.ColumnReference | None = None,
        index_filter_data_column: expr.ColumnReference | None = None,
        query_filter_column: expr.ColumnReference | None = None,
        asof_now: bool = True,
    ) -> "Table":
        """Query a pluggable external index (reference ``graph.rs:917``,
        ``external_index.rs:38``). ``self`` is the query table. With ``asof_now=False``
        live queries are re-answered when the index changes."""
        node = G.add_node(
            pg.ExternalIndexNode(
                inputs=[index_table, self],
                index_column=index_column,
                query_column=query_column,
                index_factory=index_factory,
                query_responses_limit_column=query_responses_limit_column,
                index_filter_data_column=index_filter_data_column,
                query_filter_column=query_filter_column,
                asof_now=asof_now,
            )
        )
        columns = {"_pw_index_reply": sch.ColumnSchema("_pw_index_reply", res_type)}
        schema = sch.schema_from_columns(columns, "external_index")
        return Table(node, schema, universe=self._universe, name="external_index")

    # -- temporal hooks (stdlib.temporal patches richer versions) -----------

    def windowby(self, time_expr: Any, *, window: Any, behavior: Any = None, instance: Any = None, **kwargs: Any):
        from pathway_tpu.stdlib.temporal import windowby as _windowby

        return _windowby(self, time_expr, window=window, behavior=behavior, instance=instance, **kwargs)

    def interval_join(self, other: "Table", self_time: Any, other_time: Any, interval: Any, *on: Any, **kw: Any):
        from pathway_tpu.stdlib.temporal import interval_join as _ij

        return _ij(self, other, self_time, other_time, interval, *on, **kw)

    def interval_join_inner(self, other: "Table", self_time: Any, other_time: Any, interval: Any, *on: Any, **kw: Any):
        from pathway_tpu.stdlib.temporal import interval_join_inner as _f

        return _f(self, other, self_time, other_time, interval, *on, **kw)

    def interval_join_left(self, other: "Table", self_time: Any, other_time: Any, interval: Any, *on: Any, **kw: Any):
        from pathway_tpu.stdlib.temporal import interval_join_left as _f

        return _f(self, other, self_time, other_time, interval, *on, **kw)

    def interval_join_right(self, other: "Table", self_time: Any, other_time: Any, interval: Any, *on: Any, **kw: Any):
        from pathway_tpu.stdlib.temporal import interval_join_right as _f

        return _f(self, other, self_time, other_time, interval, *on, **kw)

    def interval_join_outer(self, other: "Table", self_time: Any, other_time: Any, interval: Any, *on: Any, **kw: Any):
        from pathway_tpu.stdlib.temporal import interval_join_outer as _f

        return _f(self, other, self_time, other_time, interval, *on, **kw)

    def asof_join(self, other: "Table", self_time: Any, other_time: Any, *on: Any, **kw: Any):
        from pathway_tpu.stdlib.temporal import asof_join as _f

        return _f(self, other, self_time, other_time, *on, **kw)

    def asof_join_left(self, other: "Table", self_time: Any, other_time: Any, *on: Any, **kw: Any):
        from pathway_tpu.stdlib.temporal import asof_join_left as _f

        return _f(self, other, self_time, other_time, *on, **kw)

    def asof_join_right(self, other: "Table", self_time: Any, other_time: Any, *on: Any, **kw: Any):
        from pathway_tpu.stdlib.temporal import asof_join_right as _f

        return _f(self, other, self_time, other_time, *on, **kw)

    def asof_join_outer(self, other: "Table", self_time: Any, other_time: Any, *on: Any, **kw: Any):
        from pathway_tpu.stdlib.temporal import asof_join_outer as _f

        return _f(self, other, self_time, other_time, *on, **kw)

    def asof_now_join(self, other: "Table", *on: Any, **kw: Any):
        from pathway_tpu.stdlib.temporal import asof_now_join as _f

        return _f(self, other, *on, **kw)

    def asof_now_join_inner(self, other: "Table", *on: Any, **kw: Any):
        from pathway_tpu.stdlib.temporal import asof_now_join_inner as _f

        return _f(self, other, *on, **kw)

    def asof_now_join_left(self, other: "Table", *on: Any, **kw: Any):
        from pathway_tpu.stdlib.temporal import asof_now_join_left as _f

        return _f(self, other, *on, **kw)

    def window_join(self, other: "Table", self_time: Any, other_time: Any, window: Any, *on: Any, **kw: Any):
        from pathway_tpu.stdlib.temporal import window_join as _f

        return _f(self, other, self_time, other_time, window, *on, **kw)

    def window_join_inner(self, other: "Table", self_time: Any, other_time: Any, window: Any, *on: Any):
        from pathway_tpu.stdlib.temporal import window_join_inner as _f

        return _f(self, other, self_time, other_time, window, *on)

    def window_join_left(self, other: "Table", self_time: Any, other_time: Any, window: Any, *on: Any):
        from pathway_tpu.stdlib.temporal import window_join_left as _f

        return _f(self, other, self_time, other_time, window, *on)

    def window_join_right(self, other: "Table", self_time: Any, other_time: Any, window: Any, *on: Any):
        from pathway_tpu.stdlib.temporal import window_join_right as _f

        return _f(self, other, self_time, other_time, window, *on)

    def window_join_outer(self, other: "Table", self_time: Any, other_time: Any, window: Any, *on: Any):
        from pathway_tpu.stdlib.temporal import window_join_outer as _f

        return _f(self, other, self_time, other_time, window, *on)

    def diff(self, timestamp: Any, *values: Any, instance: Any = None) -> "Table":
        from pathway_tpu.stdlib.ordered import diff as _diff

        return _diff(self, timestamp, *values, instance=instance)

    def interpolate(self, timestamp: Any, *values: Any, mode: Any = None) -> "Table":
        from pathway_tpu.stdlib.statistical import interpolate as _interpolate

        return _interpolate(self, timestamp, *values, mode=mode)


class TableSlice:
    """Parity: reference ``internals/table_slice.py`` — a named-column view helper."""

    def __init__(self, table: Table, mapping: Dict[str, expr.ColumnReference]):
        self._table = table
        self._mapping = mapping

    def __iter__(self):
        return iter(self._mapping.values())

    def keys(self) -> list[str]:
        return list(self._mapping)

    def __getitem__(self, name: str) -> expr.ColumnReference:
        return self._mapping[name]

    def __getattr__(self, name: str) -> expr.ColumnReference:
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self._mapping[name]
        except KeyError as exc:
            raise AttributeError(name) from exc

    def without(self, *cols: Any) -> "TableSlice":
        drop = {_name_of(c) for c in cols}
        return TableSlice(self._table, {k: v for k, v in self._mapping.items() if k not in drop})

    def with_prefix(self, prefix: str) -> "TableSlice":
        return TableSlice(self._table, {prefix + k: v for k, v in self._mapping.items()})

    def with_suffix(self, suffix: str) -> "TableSlice":
        return TableSlice(self._table, {k + suffix: v for k, v in self._mapping.items()})

    def rename(self, names_mapping: Mapping[str, str]) -> "TableSlice":
        return TableSlice(
            self._table,
            {names_mapping.get(k, k): v for k, v in self._mapping.items()},
        )


def _merge_schema_strict(
    a: sch.SchemaMetaclass, b: sch.SchemaMetaclass, op: str
) -> sch.SchemaMetaclass:
    a_cols, b_cols = a.columns(), b.columns()
    if set(a_cols) != set(b_cols):
        raise ValueError(
            f"{op}: column sets differ: {sorted(a_cols)} vs {sorted(b_cols)}"
        )
    merged = {
        n: sch.ColumnSchema(n, dt.types_lca(a_cols[n].dtype, b_cols[n].dtype))
        for n in a_cols
    }
    return sch.schema_from_columns(merged, op)


def table_from_datasource(node: pg.Node, schema: sch.SchemaMetaclass, name: str = "input") -> Table:
    return Table(node, schema, name=name)
