"""Global operator DAG built by the Table API.

Parity: reference ``internals/parse_graph.py`` (``ParseGraph``, global ``G``) +
``internals/operator.py``. Each node couples the declarative spec (what the reference calls a
``Context``) with enough info for the engine runner to instantiate an incremental evaluator.
"""

from __future__ import annotations

import hashlib
import itertools
import threading as _threading
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Callable, Dict, List, Optional

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table


class Node:
    """One operator in the dataflow DAG."""

    kind: str = "node"

    def __init__(self, **config: Any):
        self.id: int = -1
        self.config: Dict[str, Any] = config
        self.inputs: List["Table"] = config.pop("inputs", [])
        self.output: Optional["Table"] = None
        self.name: str = config.pop("name", self.kind)

    def __repr__(self) -> str:
        return f"<{type(self).__name__} #{self.id} {self.name}>"


class InputNode(Node):
    kind = "input"


class RowwiseNode(Node):
    kind = "rowwise"


class FilterNode(Node):
    kind = "filter"


class ReindexNode(Node):
    kind = "reindex"


class GroupbyNode(Node):
    kind = "groupby"


class DeduplicateNode(Node):
    kind = "deduplicate"


class JoinNode(Node):
    kind = "join"


class ConcatNode(Node):
    kind = "concat"


class UpdateRowsNode(Node):
    kind = "update_rows"


class UpdateCellsNode(Node):
    kind = "update_cells"


class IntersectNode(Node):
    kind = "intersect"


class DifferenceNode(Node):
    kind = "difference"


class RestrictNode(Node):
    kind = "restrict"


class HavingNode(Node):
    kind = "having"


class WithUniverseOfNode(Node):
    kind = "with_universe_of"


class FlattenNode(Node):
    kind = "flatten"


class IxNode(Node):
    kind = "ix"


class SortNode(Node):
    kind = "sort"


class SortedIndexNode(Node):
    """Sorted binary tree per instance (reference ``stdlib/indexing/sorting.py:92``
    ``build_sorted_index`` — a treap with key-hash priorities). Emits one row per
    input row with left/right/parent tree pointers."""

    kind = "sorted_index"


class OutputNode(Node):
    """A sink: subscribe callback, io writer, or debug capture."""

    kind = "output"


class GradualBroadcastNode(Node):
    """Threshold broadcast with per-key stagger + hysteresis (reference
    ``operators/gradual_broadcast.rs``)."""

    kind = "gradual_broadcast"


class ExternalIndexNode(Node):
    kind = "external_index"


class AsofNowUpdateNode(Node):
    """Marks a table whose updates must not retract earlier outputs (as-of-now)."""

    kind = "asof_now"


class IterateNode(Node):
    kind = "iterate"


class IterateResultNode(Node):
    kind = "iterate_result"


class BufferNode(Node):
    kind = "buffer"


class ForgetNode(Node):
    kind = "forget"


class FreezeNode(Node):
    kind = "freeze"


class RemoveErrorsNode(Node):
    kind = "remove_errors"


class StatefulReduceNode(Node):
    kind = "stateful_reduce"


class RowTransformerNode(Node):
    kind = "row_transformer"


class RowTransformerResultNode(Node):
    kind = "row_transformer_result"


class TimedSourceClock:
    """Serializes debug ``_TimedSource`` streams onto one global clock.

    Each poll round (one ``next_batch`` call per live source) releases the rows of
    exactly one globally-minimal ``__time__`` value, so interleaved streams arrive in
    deterministic commit order. The round's minimum is snapshotted when the round
    starts; a source re-polled within the commit cannot shift it.
    """

    def __init__(self) -> None:
        self.sources: List[Any] = []
        self._polled: set[int] = set()
        self._round_min: Any = None

    def clear(self) -> None:
        self.sources.clear()
        self._polled.clear()
        self._round_min = None

    def register(self, source: Any) -> None:
        self.sources.append(source)

    def may_release(self, source: Any) -> bool:
        pending = [t for t in (s._next_time() for s in self.sources) if t is not None]
        if not pending:
            return True
        if id(source) in self._polled or self._round_min is None:
            # a source polled twice means a new commit began: start a fresh round
            self._polled = set()
            self._round_min = min(pending)
        self._polled.add(id(source))
        nt = source._next_time()
        return nt is not None and nt == self._round_min


_GLOBAL_UNIVERSE_COUNTER = itertools.count()


class ParseGraph:
    """Global mutable DAG; cleared by ``G.clear()`` between test runs."""

    def __init__(self) -> None:
        self.nodes: List[Node] = []
        self.error_logs: List["Table"] = []
        # shared clock for debug _TimedSource streams (global __time__ order)
        self.timed_source_clock = TimedSourceClock()

    def add_node(self, node: Node) -> Node:
        node.id = len(self.nodes)
        from pathway_tpu.internals.trace import capture_user_frame

        # remember the user line that created this operator so runtime errors can
        # point at pipeline code (reference internals/trace.py)
        node.user_frame = capture_user_frame()
        # operators created inside a local_error_log context report there
        stack = getattr(self, "_error_log_stack", None)
        node.error_log_source = stack[-1] if stack else None
        self.nodes.append(node)
        return node

    def new_universe_id(self) -> int:
        # ids are PROCESS-global: iterate() builds nested ParseGraphs whose
        # universes share the one solver — per-graph counters would alias
        return next(_GLOBAL_UNIVERSE_COUNTER)

    def clear(self) -> None:
        self.nodes.clear()
        self.error_logs.clear()
        self.timed_source_clock.clear()
        # relations of the dropped graph's universes are garbage (ids are global
        # and never reused, but unbounded growth across test runs serves nothing)
        universe_solver.clear()

    def sig(self) -> str:
        digest = hashlib.sha256()
        for node in self.nodes:
            digest.update(f"{node.id}:{node.kind}:{[t._node.id for t in node.inputs]}".encode())
        return digest.hexdigest()

    def static_nodes(self) -> List[Node]:
        return [n for n in self.nodes if isinstance(n, InputNode)]


class _GraphProxy:
    """Delegates to the current graph; swapped during ``pw.iterate`` body
    construction. Thread workers (``parallel.threads.run_threads`` — the
    in-process analogue of ``spawn -n``) each own a PRIVATE graph: after
    ``enter_thread_graph()`` every read/write of ``_current`` on that thread
    resolves to the worker's graph, so N workers build N independent dataflows
    from the same program, exactly like N spawned processes would."""

    def __init__(self) -> None:
        self._main = ParseGraph()
        self._tls = _threading.local()

    @property
    def _current(self) -> ParseGraph:
        g = getattr(self._tls, "graph", None)
        return g if g is not None else self._main

    @_current.setter
    def _current(self, graph: ParseGraph) -> None:
        if getattr(self._tls, "graph", None) is not None:
            self._tls.graph = graph
        else:
            self._main = graph

    def enter_thread_graph(self) -> None:
        self._tls.graph = ParseGraph()

    def exit_thread_graph(self) -> None:
        self._tls.graph = None

    def __getattr__(self, name: str):
        return getattr(self._current, name)


G = _GraphProxy()


@dataclass(frozen=True)
class Universe:
    """Key-set identity; subset relations tracked for with_universe_of validation.

    Parity: reference ``internals/universe.py`` + universe solver (we use direct relation
    tracking instead of a SAT solver).
    """

    uid: int

    _subset_pairs: Any = field(default=None, repr=False, compare=False)


class UniverseSolver:
    """Key-set (universe) algebra (reference ``internals/universe_solver.py``, which
    drives a SAT solver; here the same queries resolve by structural derivation).

    Universes are related by subset/equal promises AND by the algebra of the ops
    that created them: an intersection is contained in each parent, a union
    contains each part, a difference is contained in its left argument and is
    disjoint from its right. ``query_is_subset`` derives through all of these.
    """

    def __init__(self) -> None:
        self.clear()

    def clear(self) -> None:
        self.subset: set[tuple[int, int]] = set()
        self.equal: dict[int, int] = {}
        self.intersections: dict[int, list[int]] = {}
        self.unions: dict[int, list[int]] = {}
        self.differences: dict[int, tuple[int, int]] = {}
        self.disjoint: set[tuple[int, int]] = set()

    def _root(self, u: int) -> int:
        while self.equal.get(u, u) != u:
            u = self.equal[u]
        return u

    def register_subset(self, sub: Universe, sup: Universe) -> None:
        self.subset.add((self._root(sub.uid), self._root(sup.uid)))

    def register_equal(self, a: Universe, b: Universe) -> None:
        self.equal[self._root(a.uid)] = self._root(b.uid)

    def register_intersection(self, result: Universe, parents: list) -> None:
        roots = [self._root(p.uid) for p in parents]
        r = self._root(result.uid)
        self.intersections[r] = roots
        for p in roots:
            self.subset.add((r, p))

    def register_union(self, result: Universe, parts: list) -> None:
        roots = [self._root(p.uid) for p in parts]
        r = self._root(result.uid)
        self.unions[r] = roots
        for p in roots:
            self.subset.add((p, r))

    def register_difference(self, result: Universe, a: Universe, b: Universe) -> None:
        r = self._root(result.uid)
        self.differences[r] = (self._root(a.uid), self._root(b.uid))
        self.subset.add((r, self._root(a.uid)))
        self._register_disjoint_roots(r, self._root(b.uid))

    def register_disjoint(self, a: Universe, b: Universe) -> None:
        self._register_disjoint_roots(self._root(a.uid), self._root(b.uid))

    def _register_disjoint_roots(self, a: int, b: int) -> None:
        self.disjoint.add((a, b))
        self.disjoint.add((b, a))

    def query_is_subset(self, sub: Universe, sup: Universe) -> bool:
        return self._subset_roots(self._root(sub.uid), self._root(sup.uid), set())

    def _subset_roots(self, a: int, b: int, busy: set) -> bool:
        if a == b:
            return True
        if (a, b) in busy:
            return False  # cycle guard for structural recursion
        busy = busy | {(a, b)}
        # transitive subset edges
        seen = {a}
        frontier = [a]
        while frontier:
            u = frontier.pop()
            if u == b:
                return True
            for (x, y) in self.subset:
                if x == u and y not in seen:
                    seen.add(y)
                    frontier.append(y)
        # a <= intersection(P...) iff a <= every P
        parents = self.intersections.get(b)
        if parents and all(self._subset_roots(a, p, busy) for p in parents):
            return True
        # union(Q...) <= b iff every Q <= b
        parts = self.unions.get(a)
        if parts and all(self._subset_roots(q, b, busy) for q in parts):
            return True
        return False

    def query_are_equal(self, a: Universe, b: Universe) -> bool:
        return self._root(a.uid) == self._root(b.uid) or (
            self.query_is_subset(a, b) and self.query_is_subset(b, a)
        )

    def query_are_disjoint(self, a: Universe, b: Universe) -> bool:
        ra, rb = self._root(a.uid), self._root(b.uid)
        if (ra, rb) in self.disjoint:
            return True
        # subsets of disjoint universes are disjoint
        for (x, y) in self.disjoint:
            if self._subset_roots(ra, x, set()) and self._subset_roots(rb, y, set()):
                return True
        return False


universe_solver = UniverseSolver()


def new_universe() -> Universe:
    return Universe(G.new_universe_id())
