"""``.dt`` expression namespace (parity: reference ``internals/expressions/date_time.py``).

Columns of DATE_TIME_NAIVE/UTC and DURATION are stored as numpy ``datetime64[ns]`` /
``timedelta64[ns]`` (vectorized host ops; the engine keeps time columns off-device since TPUs
have no int64-heavy win for calendar math).
"""

from __future__ import annotations

import datetime
from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr


def _as_dt64(a: np.ndarray) -> np.ndarray:
    if a.dtype == object:
        return a.astype("datetime64[ns]")
    return a


class DateTimeNamespace:
    def __init__(self, e: expr.ColumnExpression):
        self._e = e

    def _method(self, name: str, fun: Callable, ret: Any, *args: Any) -> expr.MethodCallExpression:
        return expr.MethodCallExpression(name, fun, ret, self._e, *args)

    def _field(self, name: str, extract: str) -> expr.MethodCallExpression:
        def fun(a: np.ndarray) -> np.ndarray:
            a = _as_dt64(a)
            import pandas as pd

            idx = pd.DatetimeIndex(a)
            return np.asarray(getattr(idx, extract), dtype=np.int64)

        return self._method(f"dt.{name}", fun, dt.INT)

    def year(self):
        return self._field("year", "year")

    def month(self):
        return self._field("month", "month")

    def day(self):
        return self._field("day", "day")

    def hour(self):
        return self._field("hour", "hour")

    def minute(self):
        return self._field("minute", "minute")

    def second(self):
        return self._field("second", "second")

    def millisecond(self):
        def fun(a: np.ndarray) -> np.ndarray:
            import pandas as pd

            idx = pd.DatetimeIndex(_as_dt64(a))
            return np.asarray(idx.microsecond // 1000 + idx.nanosecond // 1_000_000, dtype=np.int64)

        return self._method("dt.millisecond", fun, dt.INT)

    def microsecond(self):
        return self._field("microsecond", "microsecond")

    def nanosecond(self):
        return self._field("nanosecond", "nanosecond")

    def timestamp(self, unit: str = "ns"):
        divisors = {"ns": 1, "us": 1_000, "ms": 1_000_000, "s": 1_000_000_000}

        def fun(a: np.ndarray) -> np.ndarray:
            ns = _as_dt64(a).astype("datetime64[ns]").astype(np.int64)
            return (ns / divisors[unit]).astype(np.float64) if unit != "ns" else ns

        return self._method("dt.timestamp", fun, dt.INT if unit == "ns" else dt.FLOAT)

    def strftime(self, fmt: Any):
        def fun(a: np.ndarray, f: np.ndarray) -> np.ndarray:
            import pandas as pd

            idx = pd.DatetimeIndex(_as_dt64(a))
            out = np.empty(len(a), dtype=object)
            for i, (ts, fi) in enumerate(zip(idx, f)):
                out[i] = ts.strftime(_convert_fmt(fi))
            return out

        return self._method("dt.strftime", fun, dt.STR, fmt)

    def strptime(self, fmt: Any, contains_timezone: bool = False):
        def fun(a: np.ndarray, f: np.ndarray) -> np.ndarray:
            out = np.empty(len(a), dtype="datetime64[ns]")
            for i, (s, fi) in enumerate(zip(a, f)):
                out[i] = np.datetime64(datetime.datetime.strptime(s, _convert_fmt(fi)), "ns")
            return out

        return self._method(
            "dt.strptime", fun, dt.DATE_TIME_UTC if contains_timezone else dt.DATE_TIME_NAIVE, fmt
        )

    def round(self, duration: Any):
        def fun(a: np.ndarray, d: np.ndarray) -> np.ndarray:
            import pandas as pd

            idx = pd.DatetimeIndex(_as_dt64(a))
            return np.asarray(idx.round(pd.Timedelta(d[0])))

        return self._method("dt.round", fun, dt.DATE_TIME_NAIVE, duration)

    def floor(self, duration: Any):
        def fun(a: np.ndarray, d: np.ndarray) -> np.ndarray:
            import pandas as pd

            idx = pd.DatetimeIndex(_as_dt64(a))
            return np.asarray(idx.floor(pd.Timedelta(d[0])))

        return self._method("dt.floor", fun, dt.DATE_TIME_NAIVE, duration)

    # duration accessors
    def nanoseconds(self):
        return self._dur("nanoseconds", 1)

    def microseconds(self):
        return self._dur("microseconds", 1_000)

    def milliseconds(self):
        return self._dur("milliseconds", 1_000_000)

    def seconds(self):
        return self._dur("seconds", 1_000_000_000)

    def minutes(self):
        return self._dur("minutes", 60 * 1_000_000_000)

    def hours(self):
        return self._dur("hours", 3600 * 1_000_000_000)

    def days(self):
        return self._dur("days", 86400 * 1_000_000_000)

    def weeks(self):
        return self._dur("weeks", 7 * 86400 * 1_000_000_000)

    def _dur(self, name: str, divisor: int) -> expr.MethodCallExpression:
        def fun(a: np.ndarray) -> np.ndarray:
            ns = a.astype("timedelta64[ns]").astype(np.int64)
            return ns // divisor

        return self._method(f"dt.{name}", fun, dt.INT)

    def to_naive_in_timezone(self, timezone: Any):
        def fun(a: np.ndarray, tz: np.ndarray) -> np.ndarray:
            import pandas as pd

            idx = pd.DatetimeIndex(_as_dt64(a), tz="UTC")
            return np.asarray(idx.tz_convert(tz[0]).tz_localize(None))

        return self._method("dt.to_naive_in_timezone", fun, dt.DATE_TIME_NAIVE, timezone)

    def to_utc(self, from_timezone: Any):
        def fun(a: np.ndarray, tz: np.ndarray) -> np.ndarray:
            import pandas as pd

            idx = pd.DatetimeIndex(_as_dt64(a))
            return np.asarray(idx.tz_localize(tz[0]).tz_convert("UTC").tz_localize(None))

        return self._method("dt.to_utc", fun, dt.DATE_TIME_UTC, from_timezone)


def _convert_fmt(fmt: str) -> str:
    # pathway uses rust chrono-style %T etc.; python strptime shares most codes
    return fmt
