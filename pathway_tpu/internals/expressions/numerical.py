"""``.num`` expression namespace (parity: reference ``internals/expressions/numerical.py``)."""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr


class NumericalNamespace:
    def __init__(self, e: expr.ColumnExpression):
        self._e = e

    def _method(self, name: str, fun: Callable, ret: Any, *args: Any) -> expr.MethodCallExpression:
        return expr.MethodCallExpression(name, fun, ret, self._e, *args)

    def abs(self):
        return self._method(
            "num.abs",
            lambda a: np.abs(a) if a.dtype != object else np.frompyfunc(abs, 1, 1)(a),
            lambda dts: dts[0],
        )

    def round(self, decimals: Any = 0):
        def fun(a: np.ndarray, d: np.ndarray) -> np.ndarray:
            if a.dtype != object:
                out = np.round(a.astype(np.float64), int(d[0]) if len(d) else 0)
                return out
            return np.frompyfunc(lambda x, dd: round(x, dd), 2, 1)(a, d)

        return self._method("num.round", fun, lambda dts: dts[0], decimals)

    def fill_na(self, default_value: Any):
        def fun(a: np.ndarray, d: np.ndarray) -> np.ndarray:
            from pathway_tpu.engine.expression_evaluator import _tidy

            if a.dtype != object:
                if a.dtype.kind == "f":
                    return np.where(np.isnan(a), d.astype(np.float64), a)
                return a
            return _tidy(
                np.frompyfunc(
                    lambda x, dd: dd
                    if x is None or (isinstance(x, float) and np.isnan(x))
                    else x,
                    2,
                    1,
                )(a, d)
            )

        return self._method("num.fill_na", fun, lambda dts: dts[0].strip_optional(), default_value)
