"""``.str`` expression namespace (parity: reference ``internals/expressions/string.py``)."""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr


def _vec(fun: Callable, *arrays: np.ndarray) -> np.ndarray:
    from pathway_tpu.engine.columnar import ERROR, Error
    from pathway_tpu.engine.expression_evaluator import _tidy

    def wrapped(*vals: Any) -> Any:
        if any(isinstance(v, Error) for v in vals):
            return ERROR
        if vals and vals[0] is None:
            return None
        try:
            return fun(*vals)
        except Exception:
            return ERROR

    return _tidy(np.frompyfunc(wrapped, len(arrays), 1)(*arrays))


class StringNamespace:
    def __init__(self, e: expr.ColumnExpression):
        self._e = e

    def _method(self, name: str, fun: Callable, ret: dt.DType, *args: Any) -> expr.MethodCallExpression:
        return expr.MethodCallExpression(
            name, lambda *arrays: _vec(fun, *arrays), ret, self._e, *args
        )

    def lower(self):
        return self._method("str.lower", lambda s: s.lower(), dt.STR)

    def upper(self):
        return self._method("str.upper", lambda s: s.upper(), dt.STR)

    def reversed(self):
        return self._method("str.reversed", lambda s: s[::-1], dt.STR)

    def strip(self, chars: Any = None):
        return self._method("str.strip", lambda s, c: s.strip(c), dt.STR, chars)

    def lstrip(self, chars: Any = None):
        return self._method("str.lstrip", lambda s, c: s.lstrip(c), dt.STR, chars)

    def rstrip(self, chars: Any = None):
        return self._method("str.rstrip", lambda s, c: s.rstrip(c), dt.STR, chars)

    def len(self):
        return self._method("str.len", lambda s: len(s), dt.INT)

    def count(self, sub: Any, start: Any = None, end: Any = None):
        return self._method(
            "str.count", lambda s, su, st, en: s.count(su, st, en), dt.INT, sub, start, end
        )

    def find(self, sub: Any, start: Any = None, end: Any = None):
        return self._method(
            "str.find", lambda s, su, st, en: s.find(su, st, en), dt.INT, sub, start, end
        )

    def rfind(self, sub: Any, start: Any = None, end: Any = None):
        return self._method(
            "str.rfind", lambda s, su, st, en: s.rfind(su, st, en), dt.INT, sub, start, end
        )

    def startswith(self, prefix: Any):
        return self._method("str.startswith", lambda s, p: s.startswith(p), dt.BOOL, prefix)

    def endswith(self, suffix: Any):
        return self._method("str.endswith", lambda s, p: s.endswith(p), dt.BOOL, suffix)

    def swapcase(self):
        return self._method("str.swapcase", lambda s: s.swapcase(), dt.STR)

    def title(self):
        return self._method("str.title", lambda s: s.title(), dt.STR)

    def replace(self, old: Any, new: Any, count: Any = -1):
        return self._method(
            "str.replace", lambda s, o, n, c: s.replace(o, n, c), dt.STR, old, new, count
        )

    def split(self, sep: Any = None, maxsplit: Any = -1):
        return self._method(
            "str.split",
            lambda s, sp, m: tuple(s.split(sp, m)),
            dt.List_(dt.STR),
            sep,
            maxsplit,
        )

    def slice(self, start: Any, end: Any):
        return self._method("str.slice", lambda s, a, b: s[a:b], dt.STR, start, end)

    def parse_int(self, optional: bool = False):
        ret = dt.Optional_(dt.INT) if optional else dt.INT
        if optional:
            def parse(s: Any) -> Any:
                try:
                    return int(s)
                except (ValueError, TypeError):
                    return None
        else:
            parse = lambda s: int(s)  # noqa: E731
        return self._method("str.parse_int", parse, ret)

    def parse_float(self, optional: bool = False):
        ret = dt.Optional_(dt.FLOAT) if optional else dt.FLOAT
        if optional:
            def parse(s: Any) -> Any:
                try:
                    return float(s)
                except (ValueError, TypeError):
                    return None
        else:
            parse = lambda s: float(s)  # noqa: E731
        return self._method("str.parse_float", parse, ret)

    def parse_bool(self, true_values: Any = None, false_values: Any = None, optional: bool = False):
        trues = {v.lower() for v in (true_values or ["on", "true", "yes", "1"])}
        falses = {v.lower() for v in (false_values or ["off", "false", "no", "0"])}

        def parse(s: Any) -> Any:
            sl = s.lower()
            if sl in trues:
                return True
            if sl in falses:
                return False
            if optional:
                return None
            raise ValueError(s)

        ret = dt.Optional_(dt.BOOL) if optional else dt.BOOL
        return self._method("str.parse_bool", parse, ret)
