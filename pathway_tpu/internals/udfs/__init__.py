"""UDF system: ``pw.udf`` decorator, executors, caching, retries.

Parity: reference ``internals/udfs/`` (``class UDF`` ``__init__.py:68``, executors
``executors.py:36-132``, caches ``caches.py:35,120``, retries ``retries.py:58,107``).
UDF calls are batched column-wise by the engine; async UDFs gather per-batch with capacity
control, mirroring the reference's tokio-futures batching (``dataflow.rs:1442``).
"""

from __future__ import annotations

import asyncio
import functools
import hashlib
import inspect
import pickle
import time
from typing import Any, Callable, Optional

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr


# -- retries ----------------------------------------------------------------


class AsyncRetryStrategy:
    async def invoke(self, fun: Callable, /, *args: Any, **kwargs: Any) -> Any:
        return await fun(*args, **kwargs)


class NoRetryStrategy(AsyncRetryStrategy):
    pass


class ExponentialBackoffRetryStrategy(AsyncRetryStrategy):
    # NOTE: persistence.backends.RetryingObjectStore mirrors this schedule in a
    # sync loop (exact-type-gated); changing the retry behavior here means
    # changing it there too, or subclassing so the sync fast path is bypassed.
    def __init__(
        self,
        max_retries: int = 3,
        initial_delay: int = 1000,
        backoff_factor: float = 2,
        jitter_ms: int = 300,
    ):
        self.max_retries = max_retries
        self.initial_delay = initial_delay / 1000
        self.backoff_factor = backoff_factor
        self.jitter = jitter_ms / 1000

    async def invoke(self, fun: Callable, /, *args: Any, **kwargs: Any) -> Any:
        delay = self.initial_delay
        for attempt in range(self.max_retries + 1):
            try:
                return await fun(*args, **kwargs)
            except Exception:
                if attempt == self.max_retries:
                    raise
                import random

                await asyncio.sleep(delay + random.random() * self.jitter)
                delay *= self.backoff_factor
        raise RuntimeError("unreachable")


class FixedDelayRetryStrategy(ExponentialBackoffRetryStrategy):
    def __init__(self, max_retries: int = 3, delay_ms: int = 1000):
        super().__init__(max_retries=max_retries, initial_delay=delay_ms, backoff_factor=1, jitter_ms=0)


# -- caches -----------------------------------------------------------------


class CacheStrategy:
    def get(self, key: str) -> Any:
        raise KeyError(key)

    def set(self, key: str, value: Any) -> None:
        pass


class InMemoryCache(CacheStrategy):
    def __init__(self) -> None:
        self._data: dict[str, Any] = {}

    def get(self, key: str) -> Any:
        return self._data[key]

    def set(self, key: str, value: Any) -> None:
        self._data[key] = value


class DiskCache(CacheStrategy):
    """Sqlite-backed persistent cache (reference uses a disk KV store)."""

    def __init__(self, name: str | None = None, directory: str | None = None):
        import os
        import sqlite3

        directory = directory or os.environ.get("PATHWAY_PERSISTENT_STORAGE", "/tmp/pathway-cache")
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, f"udf-cache-{name or 'default'}.db")
        self._conn = sqlite3.connect(self._path, check_same_thread=False)
        self._conn.execute("CREATE TABLE IF NOT EXISTS cache (k TEXT PRIMARY KEY, v BLOB)")
        import threading

        self._lock = threading.Lock()

    def get(self, key: str) -> Any:
        with self._lock:
            row = self._conn.execute("SELECT v FROM cache WHERE k=?", (key,)).fetchone()
        if row is None:
            raise KeyError(key)
        return pickle.loads(row[0])

    def set(self, key: str, value: Any) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO cache VALUES (?, ?)", (key, pickle.dumps(value))
            )
            self._conn.commit()


DefaultCache = DiskCache


def wrap_async(
    fun: Callable,
    *,
    capacity: int | None = None,
    timeout: float | None = None,
    retry_strategy: "AsyncRetryStrategy | None" = None,
    cache_strategy: "CacheStrategy | None" = None,
    name: str = "async_fn",
) -> Callable:
    """Compose capacity/timeout/retries/caching around an async callable — the ONE
    wrapper both ``pw.udf`` async executors and ``AsyncTransformer.with_options``
    build on (``CacheStrategy.get`` raises ``KeyError`` on miss)."""
    import asyncio as _asyncio

    if timeout is not None:
        inner_t = fun

        async def with_timeout(*args: Any, **kwargs: Any) -> Any:
            return await _asyncio.wait_for(inner_t(*args, **kwargs), timeout=timeout)

        fun = with_timeout
    if retry_strategy is not None:
        inner_r = fun

        async def with_retries(*args: Any, **kwargs: Any) -> Any:
            return await retry_strategy.invoke(inner_r, *args, **kwargs)

        fun = with_retries
    if capacity:
        inner_c = fun
        semaphore = _asyncio.Semaphore(capacity)

        async def with_capacity(*args: Any, **kwargs: Any) -> Any:
            async with semaphore:
                return await inner_c(*args, **kwargs)

        fun = with_capacity
    if cache_strategy is not None:
        inner_k = fun

        async def cached(*args: Any, **kwargs: Any) -> Any:
            key = _cache_key(name, args, kwargs)
            try:
                return cache_strategy.get(key)
            except KeyError:
                value = await inner_k(*args, **kwargs)
                cache_strategy.set(key, value)
                return value

        fun = cached
    return fun


def _cache_key(name: str, args: tuple, kwargs: dict) -> str:
    payload = pickle.dumps((name, args, sorted(kwargs.items())))
    return hashlib.sha256(payload).hexdigest()


# -- executors --------------------------------------------------------------


class Executor:
    pass


class AutoExecutor(Executor):
    pass


class SyncExecutor(Executor):
    pass


class AsyncExecutor(Executor):
    def __init__(self, capacity: int | None = None, timeout: float | None = None):
        self.capacity = capacity
        self.timeout = timeout


class FullyAsyncExecutor(AsyncExecutor):
    def __init__(self, capacity: int | None = None, timeout: float | None = None, autocommit_duration_ms: int | None = 100):
        super().__init__(capacity, timeout)
        self.autocommit_duration_ms = autocommit_duration_ms


def auto_executor() -> AutoExecutor:
    return AutoExecutor()


def sync_executor() -> SyncExecutor:
    return SyncExecutor()


def async_executor(capacity: int | None = None, timeout: float | None = None, retry_strategy: AsyncRetryStrategy | None = None) -> AsyncExecutor:
    ex = AsyncExecutor(capacity, timeout)
    ex.retry_strategy = retry_strategy  # type: ignore[attr-defined]
    return ex


def fully_async_executor(capacity: int | None = None, timeout: float | None = None, autocommit_duration_ms: int | None = 100) -> FullyAsyncExecutor:
    return FullyAsyncExecutor(capacity, timeout, autocommit_duration_ms)


# -- the UDF class ----------------------------------------------------------


class UDF:
    """Base class for user-defined functions; also produced by the ``@pw.udf`` decorator.

    Subclasses implement ``__wrapped__`` (sync) or an async ``__wrapped__``.
    """

    def __init__(
        self,
        *,
        return_type: Any = None,
        propagate_none: bool = False,
        deterministic: bool = False,
        executor: Executor | None = None,
        cache_strategy: CacheStrategy | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        max_batch_size: int | None = None,
    ):
        self.return_type = return_type
        self.propagate_none = propagate_none
        self.deterministic = deterministic
        self.executor = executor or AutoExecutor()
        self.cache_strategy = cache_strategy
        self.retry_strategy = retry_strategy or getattr(executor, "retry_strategy", None)
        self.max_batch_size = max_batch_size
        self.func: Callable | None = getattr(self, "__wrapped__", None)

    def _resolved_return_type(self) -> Any:
        if self.return_type is not None:
            return self.return_type
        fun = self.func
        if fun is not None:
            hints = None
            try:
                import typing

                hints = typing.get_type_hints(fun)
            except Exception:
                hints = getattr(fun, "__annotations__", {})
            if hints and "return" in hints:
                return hints["return"]
        return Any

    def _wrapped_fun(self) -> tuple[Callable, bool]:
        fun = self.func
        assert fun is not None, "UDF must define __wrapped__"
        is_async = asyncio.iscoroutinefunction(fun)
        if isinstance(self.executor, (AsyncExecutor,)) and not is_async:
            # wrap sync fn as async for capacity control
            sync_fun = fun

            async def as_async(*args: Any, **kwargs: Any) -> Any:
                return sync_fun(*args, **kwargs)

            fun = as_async
            is_async = True
        if is_async and self.retry_strategy is not None:
            inner = fun

            async def with_retries(*args: Any, **kwargs: Any) -> Any:
                return await self.retry_strategy.invoke(inner, *args, **kwargs)

            fun = with_retries
        if is_async and isinstance(self.executor, AsyncExecutor) and self.executor.capacity:
            inner2 = fun
            semaphore = asyncio.Semaphore(self.executor.capacity)

            async def with_capacity(*args: Any, **kwargs: Any) -> Any:
                async with semaphore:
                    return await inner2(*args, **kwargs)

            fun = with_capacity
        if self.cache_strategy is not None:
            name = getattr(self.func, "__name__", "udf")
            cache = self.cache_strategy
            if is_async:
                inner3 = fun

                async def cached(*args: Any, **kwargs: Any) -> Any:
                    key = _cache_key(name, args, kwargs)
                    try:
                        return cache.get(key)
                    except KeyError:
                        value = await inner3(*args, **kwargs)
                        cache.set(key, value)
                        return value

                fun = cached
            else:
                inner4 = fun

                def cached_sync(*args: Any, **kwargs: Any) -> Any:
                    key = _cache_key(name, args, kwargs)
                    try:
                        return cache.get(key)
                    except KeyError:
                        value = inner4(*args, **kwargs)
                        cache.set(key, value)
                        return value

                fun = cached_sync
        return fun, is_async

    def __call__(self, *args: Any, **kwargs: Any) -> expr.ColumnExpression:
        fun, is_async = self._wrapped_fun()
        ret = self._resolved_return_type()
        if isinstance(self.executor, FullyAsyncExecutor):
            e: expr.ApplyExpression = expr.FullyAsyncApplyExpression(
                fun, ret, self.propagate_none, self.deterministic, args, kwargs, self.max_batch_size
            )
        elif is_async:
            e = expr.AsyncApplyExpression(
                fun, ret, self.propagate_none, self.deterministic, args, kwargs, self.max_batch_size
            )
        else:
            e = expr.ApplyExpression(
                fun, ret, self.propagate_none, self.deterministic, args, kwargs, self.max_batch_size
            )
        # the executor wrappers above hide the user function from bytecode
        # inspection; keep the raw callable reachable for the PWA001 graph-lint
        # determinism pass (pathway_tpu/analysis)
        e._source_fun = self.func
        return e


def udf(
    fun: Callable | None = None,
    /,
    *,
    return_type: Any = None,
    propagate_none: bool = False,
    deterministic: bool = False,
    executor: Executor | None = None,
    cache_strategy: CacheStrategy | None = None,
    retry_strategy: AsyncRetryStrategy | None = None,
    max_batch_size: int | None = None,
) -> Any:
    """Decorator turning a function into a column UDF (parity: ``pw.udf``)."""

    def wrapper(f: Callable) -> UDF:
        instance = UDF(
            return_type=return_type,
            propagate_none=propagate_none,
            deterministic=deterministic,
            executor=executor,
            cache_strategy=cache_strategy,
            retry_strategy=retry_strategy,
            max_batch_size=max_batch_size,
        )
        instance.func = f
        functools.update_wrapper(instance, f)  # type: ignore[arg-type]
        return instance

    if fun is not None:
        return wrapper(fun)
    return wrapper


udf_async = functools.partial(udf, executor=AsyncExecutor())
