"""Class-syntax row transformers — ``@pw.transformer``.

Parity: reference ``internals/row_transformer.py`` (``RowTransformer``/``ClassArg`` with
``input_attribute``/``attribute``/``output_attribute``/``method``) over the engine's
legacy ``complex_columns`` (``src/engine/dataflow/complex_columns.rs``): pointer-chasing
computations where a row's output may read other rows (``self.transformer.nodes[ptr]``).

Engine mechanism here: a batch evaluator materializes the class-arg tables, evaluates all
output attributes per commit with per-row memoization (cross-row references included), and
emits diffs against previously emitted outputs — recompute-and-diff rather than the
reference's dependency-tracked incremental columns, same results.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from pathway_tpu.engine.columnar import Delta, StateTable
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import Pointer, keys_to_pointers, pointer_from
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


class _Attr:
    kind = "input"

    def __init__(self, fn: Callable | None = None, *, output_name: str | None = None, dtype: Any = None):
        self.fn = fn
        self.output_name = output_name
        self.name: str | None = None
        self.dtype = dtype

    def __set_name__(self, owner: type, name: str) -> None:
        self.name = name
        if self.output_name is None:
            self.output_name = name


class _InputAttribute(_Attr):
    kind = "input"


class _Attribute(_Attr):
    kind = "attribute"


class _OutputAttribute(_Attr):
    kind = "output"


class _Method(_Attr):
    kind = "method"


class _InputMethod(_Attr):
    kind = "input_method"


def input_attribute(dtype: Any = None) -> _InputAttribute:
    return _InputAttribute(dtype=dtype)


def input_method(dtype: Any = None) -> _InputMethod:
    return _InputMethod(dtype=dtype)


def attribute(fn: Callable) -> _Attribute:
    return _Attribute(fn)


def output_attribute(fn: Callable | None = None, *, output_name: str | None = None):
    if fn is not None:
        return _OutputAttribute(fn)

    def wrap(f: Callable) -> _OutputAttribute:
        return _OutputAttribute(f, output_name=output_name)

    return wrap


def method(fn: Callable | None = None, **kwargs: Any):
    if fn is not None:
        return _Method(fn)

    def wrap(f: Callable) -> _Method:
        return _Method(f, **kwargs)

    return wrap


class ClassArg:
    """Base class for transformer inner classes (reference ``ClassArg``)."""

    def __init_subclass__(cls, input: Any = None, output: Any = None, **kw: Any) -> None:
        super().__init_subclass__(**kw)
        cls._pw_attrs = {}
        for klass in reversed(cls.__mro__):
            for name, value in vars(klass).items():
                if isinstance(value, _Attr):
                    cls._pw_attrs[name] = value
        cls._pw_output_schema_decl = output


class _RowReference:
    """One row of a class-arg table during evaluation: attribute access resolves
    inputs from state, computes (and memoizes) derived attributes, and follows
    pointers into sibling class-arg tables via ``self.transformer``."""

    __slots__ = ("_run", "_arg_name", "_ptr")

    def __init__(self, run: "_TransformerRun", arg_name: str, ptr: Pointer):
        self._run = run
        self._arg_name = arg_name
        self._ptr = ptr

    @property
    def id(self) -> Pointer:
        return self._ptr

    @property
    def transformer(self) -> "_TransformerNamespace":
        return _TransformerNamespace(self._run)

    def pointer_from(self, *args: Any, optional: bool = False) -> Pointer:
        return pointer_from(*args)

    def __getattr__(self, name: str) -> Any:
        run = object.__getattribute__(self, "_run")
        arg_name = object.__getattribute__(self, "_arg_name")
        ptr = object.__getattribute__(self, "_ptr")
        cls = run.transformer.class_args[arg_name]
        attr = cls._pw_attrs.get(name)
        if attr is None:
            # plain class helpers (constants, functions, staticmethods)
            value = getattr(cls, name)
            if callable(value) and not isinstance(value, staticmethod):
                import types

                if isinstance(inspect_getattr_static(cls, name), staticmethod):
                    return value
                return types.MethodType(value, self)
            return value
        if attr.kind == "input":
            return run.input_value(arg_name, ptr, name)
        if attr.kind == "input_method":
            return run.input_value(arg_name, ptr, name)
        # computed attribute/output/method: memoized per (arg, ptr, name)
        if attr.kind == "method":
            def call(*args: Any) -> Any:
                return attr.fn(self, *args)

            return call
        return run.computed_value(arg_name, ptr, name, attr.fn, self)


def inspect_getattr_static(cls: type, name: str) -> Any:
    import inspect

    try:
        return inspect.getattr_static(cls, name)
    except AttributeError:
        return None


class _TransformerNamespace:
    """``self.transformer.<class_arg>[ptr]`` resolution."""

    def __init__(self, run: "_TransformerRun"):
        self._run = run

    def __getattr__(self, arg_name: str) -> "_ClassArgIndexer":
        if arg_name.startswith("_"):
            raise AttributeError(arg_name)
        return _ClassArgIndexer(self._run, arg_name)


class _ClassArgIndexer:
    def __init__(self, run: "_TransformerRun", arg_name: str):
        self._run = run
        self._arg_name = arg_name

    def __getitem__(self, ptr: Pointer) -> _RowReference:
        return _RowReference(self._run, self._arg_name, ptr)

    def __call__(self, ref: _RowReference, ptr: Pointer) -> _RowReference:
        return _RowReference(self._run, self._arg_name, ptr)


class _TransformerRun:
    """One recompute pass: rows + memo caches for every class arg."""

    def __init__(self, transformer: "RowTransformer", rows: Dict[str, Dict[bytes, dict]]):
        self.transformer = transformer
        self.rows = rows  # arg name -> key bytes -> input row dict
        self.memo: Dict[tuple, Any] = {}
        self._computing: set[tuple] = set()

    def _row(self, arg_name: str, ptr: Pointer) -> dict:
        from pathway_tpu.internals.keys import pointers_to_keys

        kb = pointers_to_keys([ptr]).tobytes()
        row = self.rows.get(arg_name, {}).get(kb)
        if row is None:
            raise KeyError(f"transformer row {ptr!r} not found in {arg_name!r}")
        return row

    def input_value(self, arg_name: str, ptr: Pointer, name: str) -> Any:
        return self._row(arg_name, ptr)[name]

    def computed_value(
        self, arg_name: str, ptr: Pointer, name: str, fn: Callable, ref: _RowReference
    ) -> Any:
        key = (arg_name, ptr.hi, ptr.lo, name)
        if key in self.memo:
            return self.memo[key]
        if key in self._computing:
            raise RecursionError(f"cyclic attribute dependency at {arg_name}.{name}")
        self._computing.add(key)
        try:
            value = fn(ref)
        finally:
            self._computing.discard(key)
        self.memo[key] = value
        return value


class RowTransformer:
    def __init__(self, name: str, class_args: Dict[str, type]):
        self.name = name
        self.class_args = class_args

    def __call__(self, *tables: Table, **named: Table) -> Any:
        arg_names = list(self.class_args)
        matched: Dict[str, Table] = dict(zip(arg_names, tables))
        matched.update(named)
        if set(matched) != set(arg_names):
            raise ValueError(
                f"transformer {self.name} expects tables {arg_names}, got {sorted(matched)}"
            )

        node = G.add_node(
            pg.RowTransformerNode(
                inputs=[matched[n] for n in arg_names],
                transformer=self,
                arg_names=arg_names,
            )
        )
        out_tables: Dict[str, Table] = {}
        first = arg_names[0]
        for i, arg_name in enumerate(arg_names):
            schema = self._output_schema(arg_name)
            if i == 0:
                out_tables[arg_name] = Table(
                    node, schema, universe=matched[arg_name]._universe, name=f"{self.name}.{arg_name}"
                )
            else:
                reader = G.add_node(
                    pg.RowTransformerResultNode(
                        inputs=[out_tables[first]], parent=node, result_name=arg_name
                    )
                )
                out_tables[arg_name] = Table(
                    reader, schema, universe=matched[arg_name]._universe, name=f"{self.name}.{arg_name}"
                )

        class _Result:
            pass

        result = _Result()
        for arg_name, table in out_tables.items():
            setattr(result, arg_name, table)
        return result

    def _output_schema(self, arg_name: str) -> sch.SchemaMetaclass:
        cls = self.class_args[arg_name]
        declared = getattr(cls, "_pw_output_schema_decl", None)
        columns: Dict[str, sch.ColumnSchema] = {}
        for attr in cls._pw_attrs.values():
            if attr.kind == "output":
                dtype = dt.ANY
                if declared is not None and attr.output_name in declared.columns():
                    dtype = declared.columns()[attr.output_name].dtype
                columns[attr.output_name] = sch.ColumnSchema(attr.output_name, dtype)
        if declared is not None:
            missing = set(declared.columns()) - set(columns)
            if missing:
                raise RuntimeError(
                    f"output schema validation error: {arg_name} does not produce {sorted(missing)}"
                )
        return sch.schema_from_columns(columns, f"{self.name}.{arg_name}")


def transformer(cls: type) -> RowTransformer:
    """Decorator turning a class of ``ClassArg`` inner classes into a transformer."""
    class_args = {
        name: value
        for name, value in vars(cls).items()
        if isinstance(value, type) and issubclass(value, ClassArg)
    }
    if not class_args:
        raise ValueError("@transformer class must define ClassArg inner classes")
    t = RowTransformer(cls.__name__, class_args)
    # validate declared output schemas eagerly (reference validates at class creation)
    for arg_name in class_args:
        t._output_schema(arg_name)
    return t


class RowTransformerEvaluator:
    """Recompute-and-diff evaluator (see module docstring)."""

    _NON_STATE_ATTRS = ("node", "runner", "output_columns")
    state_dict = None  # wired to the engine implementation below
    load_state_dict = None

    def __init__(self, node: pg.Node, runner: Any):
        self.node = node
        self.runner = runner
        self.transformer: RowTransformer = node.config["transformer"]
        self.arg_names: List[str] = node.config["arg_names"]
        self.input_states = [StateTable(t.column_names()) for t in node.inputs]
        self.emitted: Dict[str, StateTable] = {
            name: StateTable(self.transformer._output_schema(name).column_names())
            for name in self.arg_names
        }
        self.pending: Dict[str, Delta] = {}
        self.output_columns = node.output.column_names() if node.output else []

    def process(self, input_deltas: List[Delta]) -> Delta:
        for state, delta in zip(self.input_states, input_deltas):
            state.apply(delta)
        if all(len(d) == 0 for d in input_deltas):
            return Delta.empty(self.output_columns)

        rows: Dict[str, Dict[bytes, dict]] = {}
        keys_of: Dict[str, list] = {}
        for arg_name, state in zip(self.arg_names, self.input_states):
            table_rows: Dict[bytes, dict] = {}
            keys = state.keys()
            pointers = keys_to_pointers(keys)
            for i in range(len(keys)):
                table_rows[keys[i].tobytes()] = state.get_row(keys[i].tobytes())
            rows[arg_name] = table_rows
            keys_of[arg_name] = list(zip(keys, pointers))

        run = _TransformerRun(self.transformer, rows)
        from pathway_tpu.engine.evaluators import _delta_from_rows
        from pathway_tpu.internals.iterate import _state_diff

        for arg_name in self.arg_names:
            cls = self.transformer.class_args[arg_name]
            out_names = self.transformer._output_schema(arg_name).column_names()
            out_keys = []
            out_rows = []
            for key, ptr in keys_of[arg_name]:
                ref = _RowReference(run, arg_name, ptr)
                out_row = {}
                for attr in cls._pw_attrs.values():
                    if attr.kind == "output":
                        out_row[attr.output_name] = run.computed_value(
                            arg_name, ptr, attr.name, attr.fn, ref
                        )
                out_keys.append(ptr)
                out_rows.append(out_row)
            full = _delta_from_rows(out_keys, [1] * len(out_rows), out_rows, out_names)
            target = StateTable(out_names)
            target.apply(full)
            delta = _state_diff(self.emitted[arg_name], target)
            self.emitted[arg_name].apply(delta)
            self.pending[arg_name] = delta
        return self.pending.pop(self.arg_names[0])

    def take_output(self, name: str) -> Delta:
        out_names = self.transformer._output_schema(name).column_names()
        return self.pending.pop(name, Delta.empty(out_names))


class RowTransformerResultEvaluator:
    _NON_STATE_ATTRS = ("node", "runner")
    state_dict = None
    load_state_dict = None

    def __init__(self, node: pg.Node, runner: Any):
        self.node = node
        self.runner = runner

    def has_pending(self) -> bool:
        parent = self.node.config["parent"]
        return self.node.config["result_name"] in self.runner.evaluators[parent.id].pending

    def process(self, input_deltas: List[Delta]) -> Delta:
        parent = self.node.config["parent"]
        return self.runner.evaluators[parent.id].take_output(self.node.config["result_name"])


def _register() -> None:
    from pathway_tpu.engine.evaluators import EVALUATORS, Evaluator

    from pathway_tpu.engine.evaluators import wire_cluster_defaults

    for cls in (RowTransformerEvaluator, RowTransformerResultEvaluator):
        cls.state_dict = Evaluator.state_dict
        cls.load_state_dict = Evaluator.load_state_dict
    # multi-process lane: row transformers chase pointers across ARBITRARY rows
    # (reference ``complex_columns.rs`` builds the same all-rows context), so
    # their input tables centralize on process 0 and outputs flow from there
    wire_cluster_defaults(RowTransformerEvaluator, "root")
    wire_cluster_defaults(RowTransformerResultEvaluator)
    EVALUATORS[pg.RowTransformerNode] = RowTransformerEvaluator
    EVALUATORS[pg.RowTransformerResultNode] = RowTransformerResultEvaluator


_register()
