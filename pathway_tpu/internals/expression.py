"""Lazy column-expression AST.

Parity with the reference's ``python/pathway/internals/expression.py`` (expression node taxonomy)
and ``src/engine/expression.rs`` (typed op inventory). Expressions are built by operator
overloading on column references, type-inferred statically, and compiled by the engine into
vectorized column kernels — numeric subtrees lower to a single jit'd JAX function on TPU.
"""

from __future__ import annotations

import operator
from abc import ABC
from typing import TYPE_CHECKING, Any, Callable, Iterable, Mapping, Tuple

from pathway_tpu.internals import dtype as dt

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table


class ColumnExpression(ABC):
    """Base class of all column expressions."""

    _dtype: dt.DType | None = None

    # -- arithmetic ---------------------------------------------------------
    def __add__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.add, self, other)

    def __radd__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.add, other, self)

    def __sub__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.sub, self, other)

    def __rsub__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.sub, other, self)

    def __mul__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.mul, self, other)

    def __rmul__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.mul, other, self)

    def __truediv__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.truediv, self, other)

    def __rtruediv__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.truediv, other, self)

    def __floordiv__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.floordiv, self, other)

    def __rfloordiv__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.floordiv, other, self)

    def __mod__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.mod, self, other)

    def __rmod__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.mod, other, self)

    def __pow__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.pow, self, other)

    def __rpow__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.pow, other, self)

    def __matmul__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.matmul, self, other)

    def __rmatmul__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.matmul, other, self)

    def __neg__(self) -> "ColumnUnaryOpExpression":
        return ColumnUnaryOpExpression(operator.neg, self)

    # -- comparisons --------------------------------------------------------
    def __eq__(self, other: Any) -> "ColumnBinaryOpExpression":  # type: ignore[override]
        return ColumnBinaryOpExpression(operator.eq, self, other)

    def __ne__(self, other: Any) -> "ColumnBinaryOpExpression":  # type: ignore[override]
        return ColumnBinaryOpExpression(operator.ne, self, other)

    def __lt__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.lt, self, other)

    def __le__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.le, self, other)

    def __gt__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.gt, self, other)

    def __ge__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.ge, self, other)

    # -- boolean ------------------------------------------------------------
    def __and__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.and_, self, other)

    def __rand__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.and_, other, self)

    def __or__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.or_, self, other)

    def __ror__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.or_, other, self)

    def __xor__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.xor, self, other)

    def __rxor__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.xor, other, self)

    def __lshift__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.lshift, self, other)

    def __rlshift__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.lshift, other, self)

    def __rshift__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.rshift, self, other)

    def __rrshift__(self, other: Any) -> "ColumnBinaryOpExpression":
        return ColumnBinaryOpExpression(operator.rshift, other, self)

    def __invert__(self) -> "ColumnUnaryOpExpression":
        return ColumnUnaryOpExpression(operator.not_, self)

    def __abs__(self) -> "ColumnUnaryOpExpression":
        return ColumnUnaryOpExpression(operator.abs, self)

    def __bool__(self) -> bool:
        raise RuntimeError(
            "ColumnExpression is lazy and cannot be used as a bool; "
            "use & | ~ instead of and/or/not"
        )

    def __hash__(self) -> int:
        return id(self)

    # -- access -------------------------------------------------------------
    def __getitem__(self, item: Any) -> "GetExpression":
        return GetExpression(self, item, check_if_exists=False)

    def get(self, item: Any, default: Any = None) -> "GetExpression":
        return GetExpression(self, item, default=default, check_if_exists=True)

    # -- type casts ---------------------------------------------------------
    def is_none(self) -> "IsNoneExpression":
        return IsNoneExpression(self)

    def is_not_none(self) -> "IsNotNoneExpression":
        return IsNotNoneExpression(self)

    def as_int(self, unwrap: bool = False) -> "ConvertExpression":
        return ConvertExpression(dt.INT, self, unwrap=unwrap)

    def as_float(self, unwrap: bool = False) -> "ConvertExpression":
        return ConvertExpression(dt.FLOAT, self, unwrap=unwrap)

    def as_str(self, unwrap: bool = False) -> "ConvertExpression":
        return ConvertExpression(dt.STR, self, unwrap=unwrap)

    def as_bool(self, unwrap: bool = False) -> "ConvertExpression":
        return ConvertExpression(dt.BOOL, self, unwrap=unwrap)

    def to_string(self) -> "ConvertExpression":
        return ConvertExpression(dt.STR, self)

    # -- namespaces ---------------------------------------------------------
    @property
    def dt(self):
        from pathway_tpu.internals.expressions.date_time import DateTimeNamespace

        return DateTimeNamespace(self)

    @property
    def str(self):
        from pathway_tpu.internals.expressions.string import StringNamespace

        return StringNamespace(self)

    @property
    def num(self):
        from pathway_tpu.internals.expressions.numerical import NumericalNamespace

        return NumericalNamespace(self)

    def _deps(self) -> Tuple["ColumnExpression", ...]:
        return ()

    @property
    def _column_refs(self) -> list["ColumnReference"]:
        out: list[ColumnReference] = []
        stack: list[ColumnExpression] = [self]
        while stack:
            node = stack.pop()
            if isinstance(node, ColumnReference):
                out.append(node)
            stack.extend(node._deps())
        return out


ColumnExpressionOrValue = Any


def smart_coerce(value: Any) -> ColumnExpression:
    if isinstance(value, ColumnExpression):
        return value
    return ColumnConstExpression(value)


class ColumnConstExpression(ColumnExpression):
    def __init__(self, value: Any):
        self._value = value

    def __repr__(self) -> str:
        return repr(self._value)


class ColumnReference(ColumnExpression):
    """``table.column_name`` / ``table['column_name']``."""

    def __init__(self, table: "Table", name: str):
        self._table = table
        self._name = name

    @property
    def table(self) -> "Table":
        return self._table

    @property
    def name(self) -> str:
        return self._name

    def __repr__(self) -> str:
        return f"<{self._table._name}>.{self._name}"

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        raise TypeError(f"column {self._name!r} is not callable")


class ColumnBinaryOpExpression(ColumnExpression):
    def __init__(self, op: Callable, left: Any, right: Any):
        self._operator = op
        self._left = smart_coerce(left)
        self._right = smart_coerce(right)

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return (self._left, self._right)

    def __repr__(self) -> str:
        return f"({self._left!r} {self._operator.__name__} {self._right!r})"


class ColumnUnaryOpExpression(ColumnExpression):
    def __init__(self, op: Callable, expr: Any):
        self._operator = op
        self._expr = smart_coerce(expr)

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return (self._expr,)


class ReducerExpression(ColumnExpression):
    """An aggregation over a grouped table column (reference ``ReducerExpression``)."""

    def __init__(self, reducer: Any, *args: Any, **kwargs: Any):
        self._reducer = reducer
        self._args = tuple(smart_coerce(a) for a in args)
        self._kwargs = kwargs

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return self._args

    def __repr__(self) -> str:
        return f"pw.reducers.{self._reducer.name}({', '.join(map(repr, self._args))})"


class ApplyExpression(ColumnExpression):
    def __init__(
        self,
        fun: Callable,
        return_type: Any,
        propagate_none: bool,
        deterministic: bool,
        args: tuple,
        kwargs: Mapping[str, Any],
        max_batch_size: int | None = None,
    ):
        self._fun = fun
        self._return_type = dt.wrap(return_type)
        self._propagate_none = propagate_none
        self._deterministic = deterministic
        self._args = tuple(smart_coerce(a) for a in args)
        self._kwargs = {k: smart_coerce(v) for k, v in kwargs.items()}
        self._max_batch_size = max_batch_size

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return self._args + tuple(self._kwargs.values())


class BatchApplyExpression(ApplyExpression):
    """fun receives whole columns (lists) and returns a list — the TPU-batched UDF path
    (reference batches UDFs through the engine; here one call per commit batch)."""


class AsyncApplyExpression(ApplyExpression):
    pass


class FullyAsyncApplyExpression(ApplyExpression):
    autocommit_duration_ms: int | None = 100


class CastExpression(ColumnExpression):
    def __init__(self, target: dt.DType, expr: Any):
        self._target = target
        self._expr = smart_coerce(expr)
        self._dtype = target

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return (self._expr,)


class ConvertExpression(ColumnExpression):
    def __init__(self, target: dt.DType, expr: Any, default: Any = None, unwrap: bool = False):
        self._target = target
        self._expr = smart_coerce(expr)
        self._default = smart_coerce(default)
        self._unwrap = unwrap

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return (self._expr, self._default)


class DeclareTypeExpression(ColumnExpression):
    def __init__(self, target: Any, expr: Any):
        self._target = dt.wrap(target)
        self._expr = smart_coerce(expr)

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return (self._expr,)


class CoalesceExpression(ColumnExpression):
    def __init__(self, *args: Any):
        self._args = tuple(smart_coerce(a) for a in args)

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return self._args


class RequireExpression(ColumnExpression):
    def __init__(self, val: Any, *args: Any):
        self._val = smart_coerce(val)
        self._args = tuple(smart_coerce(a) for a in args)

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return (self._val,) + self._args


class IfElseExpression(ColumnExpression):
    def __init__(self, _if: Any, _then: Any, _else: Any):
        self._if = smart_coerce(_if)
        self._then = smart_coerce(_then)
        self._else = smart_coerce(_else)

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return (self._if, self._then, self._else)


class IsNoneExpression(ColumnExpression):
    def __init__(self, expr: Any):
        self._expr = smart_coerce(expr)

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return (self._expr,)


class IsNotNoneExpression(ColumnExpression):
    def __init__(self, expr: Any):
        self._expr = smart_coerce(expr)

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return (self._expr,)


class PointerExpression(ColumnExpression):
    """``table.pointer_from(...)`` — key derivation expression."""

    def __init__(self, table: "Table", *args: Any, optional: bool = False, instance: Any = None):
        self._table = table
        self._args = tuple(smart_coerce(a) for a in args)
        self._optional = optional
        self._instance = smart_coerce(instance) if instance is not None else None

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        extra = (self._instance,) if self._instance is not None else ()
        return self._args + extra


class MakeTupleExpression(ColumnExpression):
    def __init__(self, *args: Any):
        self._args = tuple(smart_coerce(a) for a in args)

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return self._args


class GetExpression(ColumnExpression):
    def __init__(self, obj: Any, index: Any, default: Any = None, check_if_exists: bool = True):
        self._object = smart_coerce(obj)
        self._index = smart_coerce(index)
        self._default = smart_coerce(default)
        self._check_if_exists = check_if_exists

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return (self._object, self._index, self._default)


class MethodCallExpression(ColumnExpression):
    """A ``.dt`` / ``.str`` / ``.num`` namespace method call, dispatched by dtype."""

    def __init__(self, name: str, fun: Callable, return_mapper: Callable | Any, *args: Any):
        self._method_name = name
        self._fun = fun  # python callable over scalar/ndarray columns
        self._return_mapper = return_mapper  # DType or fn(arg dtypes)->DType
        self._args = tuple(smart_coerce(a) for a in args)

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return self._args


class UnwrapExpression(ColumnExpression):
    def __init__(self, expr: Any):
        self._expr = smart_coerce(expr)

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return (self._expr,)


class FillErrorExpression(ColumnExpression):
    def __init__(self, expr: Any, replacement: Any):
        self._expr = smart_coerce(expr)
        self._replacement = smart_coerce(replacement)

    def _deps(self) -> Tuple[ColumnExpression, ...]:
        return (self._expr, self._replacement)


# -- public helpers (exported as pw.if_else etc.) ---------------------------


def if_else(_if: Any, _then: Any, _else: Any) -> IfElseExpression:
    return IfElseExpression(_if, _then, _else)


def coalesce(*args: Any) -> CoalesceExpression:
    return CoalesceExpression(*args)


def require(val: Any, *args: Any) -> RequireExpression:
    return RequireExpression(val, *args)


def cast(target: Any, expr: Any) -> CastExpression:
    return CastExpression(dt.wrap(target), expr)


def declare_type(target: Any, expr: Any) -> DeclareTypeExpression:
    return DeclareTypeExpression(target, expr)


def unwrap(expr: Any) -> UnwrapExpression:
    return UnwrapExpression(expr)


def fill_error(expr: Any, replacement: Any) -> FillErrorExpression:
    return FillErrorExpression(expr, replacement)


def make_tuple(*args: Any) -> MakeTupleExpression:
    return MakeTupleExpression(*args)


def apply(fun: Callable, *args: Any, **kwargs: Any) -> ApplyExpression:
    import typing

    hints = typing.get_type_hints(fun) if callable(fun) and hasattr(fun, "__annotations__") else {}
    return_type = hints.get("return", Any)
    return ApplyExpression(fun, return_type, False, True, args, kwargs)


def apply_with_type(fun: Callable, ret_type: Any, *args: Any, **kwargs: Any) -> ApplyExpression:
    return ApplyExpression(fun, ret_type, False, True, args, kwargs)


def apply_async(fun: Callable, *args: Any, **kwargs: Any) -> AsyncApplyExpression:
    import typing

    hints = typing.get_type_hints(fun) if callable(fun) and hasattr(fun, "__annotations__") else {}
    return_type = hints.get("return", Any)
    return AsyncApplyExpression(fun, return_type, False, True, args, kwargs)
