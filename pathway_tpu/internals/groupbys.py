"""GroupedTable: the groupby → reduce surface.

Parity: reference ``internals/groupbys.py`` (``GroupedTable``, set_id logic).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.parse_graph import G


class GroupedTable:
    def __init__(
        self,
        table: Any,
        grouping: List[expr.ColumnExpression],
        grouping_names: List[str],
        set_id: bool = False,
        sort_by: expr.ColumnExpression | None = None,
    ):
        self._table = table
        self._grouping = grouping
        self._grouping_names = grouping_names
        self._set_id = set_id
        self._sort_by = sort_by

    def _resolve(self, e: Any) -> expr.ColumnExpression:
        e = thisclass.substitute(e, {thisclass.this: self._table})
        return expr.smart_coerce(e)

    def reduce(self, *args: Any, **kwargs: Any) -> Any:
        from pathway_tpu.internals.table import Table, _name_of
        from pathway_tpu.internals.type_interpreter import infer_dtype

        out_exprs: Dict[str, expr.ColumnExpression] = {}
        for arg in args:
            out_exprs[_name_of(arg)] = self._resolve(arg)
        for name, e in kwargs.items():
            out_exprs[name] = self._resolve(e)

        columns: Dict[str, sch.ColumnSchema] = {}
        for name, e in out_exprs.items():
            if isinstance(e, expr.ReducerExpression):
                arg_dtypes = [infer_dtype(a) for a in e._args]
                dtype = e._reducer.return_dtype(arg_dtypes)
            elif isinstance(e, expr.ColumnReference):
                # must be a grouping column
                dtype = infer_dtype(e)
            else:
                dtype = infer_dtype(e)
            columns[name] = sch.ColumnSchema(name, dtype)
        schema = sch.schema_from_columns(columns, "reduce")

        node = G.add_node(
            pg.GroupbyNode(
                inputs=[self._table],
                grouping=self._grouping,
                grouping_names=self._grouping_names,
                out_exprs=out_exprs,
                set_id=self._set_id,
                sort_by=self._sort_by,
            )
        )
        return Table(node, schema, name="reduce")
