"""Join builder & desugaring.

Parity: reference ``internals/joins.py`` (JoinResult, inner/left/right/outer, ``id==``
optimization). The engine executes joins as incremental symmetric hash joins
(``pathway_tpu/engine/evaluators.py``), the DD ``join_core`` replacement.
"""

from __future__ import annotations

import enum
from typing import Any, Dict, List, Optional

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.parse_graph import G


class JoinKind(enum.Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    OUTER = "outer"


# alias matching reference pw.JoinMode
JoinMode = JoinKind


class JoinResult:
    """Intermediate result of ``t1.join(t2, ...)``; call ``.select`` to materialize."""

    def __init__(
        self,
        left: Any,
        right: Any,
        on: tuple,
        kind: JoinKind,
        id: Any = None,
        left_instance: Any = None,
        right_instance: Any = None,
    ):
        self._left = left
        self._right = right
        self._kind = kind
        self._id = id
        self._left_on: List[expr.ColumnExpression] = []
        self._right_on: List[expr.ColumnExpression] = []
        for cond in on:
            l, r = self._split_condition(cond)
            self._left_on.append(l)
            self._right_on.append(r)
        if left_instance is not None or right_instance is not None:
            if left_instance is None or right_instance is None:
                raise ValueError("both left_instance and right_instance must be given")
            self._left_on.append(self._sub_left(left_instance))
            self._right_on.append(self._sub_right(right_instance))

    def _sub_left(self, e: Any) -> expr.ColumnExpression:
        e = thisclass.substitute(
            e, {thisclass.this: self._left, thisclass.left: self._left, thisclass.right: self._right}
        )
        return expr.smart_coerce(e)

    def _sub_right(self, e: Any) -> expr.ColumnExpression:
        e = thisclass.substitute(
            e, {thisclass.this: self._right, thisclass.left: self._left, thisclass.right: self._right}
        )
        return expr.smart_coerce(e)

    def _side_of(self, e: expr.ColumnExpression) -> str:
        refs = e._column_refs
        sides = set()
        for ref in refs:
            if ref.table is self._left:
                sides.add("left")
            elif ref.table is self._right:
                sides.add("right")
            else:
                raise ValueError(
                    f"join condition references table {ref.table._name!r} which is not a join side"
                )
        if len(sides) != 1:
            raise ValueError(f"join condition side is ambiguous: {e!r}")
        return sides.pop()

    def _split_condition(self, cond: Any) -> tuple:
        cond = thisclass.substitute(
            cond, {thisclass.left: self._left, thisclass.right: self._right}
        )
        if not isinstance(cond, expr.ColumnBinaryOpExpression):
            raise ValueError(f"join condition must be <left expr> == <right expr>, got {cond!r}")
        import operator

        if cond._operator is not operator.eq:
            raise ValueError("join conditions must use ==")
        a, b = cond._left, cond._right
        if self._side_of(a) == "left":
            return a, b
        return b, a

    def select(self, *args: Any, **kwargs: Any) -> Any:
        from pathway_tpu.internals.table import Table, _name_of
        from pathway_tpu.internals.type_interpreter import infer_dtype

        out: Dict[str, expr.ColumnExpression] = {}
        for arg in args:
            if isinstance(arg, thisclass.ThisWildcard):
                # *pw.left / *pw.right: that side's columns; *pw.this: both
                # sides' (left wins a name clash, as in the reference)
                sides = {
                    thisclass.left: [self._left],
                    thisclass.right: [self._right],
                    thisclass.this: [self._left, self._right],
                }[arg._kind]
                for side in sides:
                    for n in side.column_names():
                        if n not in arg._exclude and n not in out:
                            out[n] = expr.smart_coerce(side[n])
                continue
            resolved = thisclass.substitute(
                arg,
                {thisclass.this: _JoinThis(self), thisclass.left: self._left, thisclass.right: self._right},
            )
            out[_name_of(arg)] = expr.smart_coerce(resolved)
        for name, e in kwargs.items():
            resolved = thisclass.substitute(
                e,
                {thisclass.this: _JoinThis(self), thisclass.left: self._left, thisclass.right: self._right},
            )
            out[name] = expr.smart_coerce(resolved)

        id_expr = None
        if self._id is not None:
            id_expr = self._sub_left(self._id) if self._side_is_left_safe(self._id) else self._sub_right(self._id)

        columns = {}
        for name, e in out.items():
            dtype = infer_dtype(e)
            if self._kind in (JoinKind.LEFT, JoinKind.OUTER) and _references_side(e, self._right):
                dtype = dt.Optional_(dtype) if not dtype.is_optional() and dtype != dt.ANY else dtype
            if self._kind in (JoinKind.RIGHT, JoinKind.OUTER) and _references_side(e, self._left):
                dtype = dt.Optional_(dtype) if not dtype.is_optional() and dtype != dt.ANY else dtype
            columns[name] = sch.ColumnSchema(name, dtype)
        schema = sch.schema_from_columns(columns, "join")

        node = G.add_node(
            pg.JoinNode(
                inputs=[self._left, self._right],
                left_on=self._left_on,
                right_on=self._right_on,
                kind=self._kind,
                exprs=out,
                id_expr=id_expr,
            )
        )
        return Table(node, schema, name="join")

    def _side_is_left_safe(self, e: Any) -> bool:
        try:
            return self._side_of(expr.smart_coerce(e)) == "left"
        except ValueError:
            return False


class _JoinThis:
    """Resolution target for pw.this inside join select: prefers left, falls back right."""

    def __init__(self, jr: JoinResult):
        self._jr = jr

    def __getitem__(self, name: str) -> expr.ColumnReference:
        left, right = self._jr._left, self._jr._right
        in_left = name in left._schema.columns()
        in_right = name in right._schema.columns()
        if in_left and in_right:
            raise ValueError(f"column {name!r} exists on both join sides; use pw.left/pw.right")
        if in_left:
            return left[name]
        if in_right:
            return right[name]
        raise KeyError(name)

    @property
    def id(self) -> expr.ColumnReference:
        return self._jr._left.id


def _references_side(e: expr.ColumnExpression, table: Any) -> bool:
    return any(ref.table is table for ref in e._column_refs)
