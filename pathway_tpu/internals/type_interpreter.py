"""Static type inference over expressions.

Parity: reference ``internals/type_interpreter.py`` (lighter: infers output dtypes for schema
propagation; runtime values are the source of truth for dynamic columns).
"""

from __future__ import annotations

import operator
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr

_COMPARISONS = {operator.eq, operator.ne, operator.lt, operator.le, operator.gt, operator.ge}
_BOOL_OPS = {operator.and_, operator.or_, operator.xor}


def infer_dtype(e: expr.ColumnExpression) -> dt.DType:
    if isinstance(e, expr.ColumnConstExpression):
        return dt.wrap(type(e._value)) if e._value is not None else dt.NONE
    if isinstance(e, expr.ColumnReference):
        if e.name == "id":
            return dt.POINTER
        col = e.table._schema.columns().get(e.name)
        return col.dtype if col is not None else dt.ANY
    if isinstance(e, expr.ColumnBinaryOpExpression):
        left = infer_dtype(e._left)
        right = infer_dtype(e._right)
        op = e._operator
        if op in _COMPARISONS:
            return dt.BOOL
        if op in _BOOL_OPS and left == dt.BOOL and right == dt.BOOL:
            return dt.BOOL
        l, r = left.strip_optional(), right.strip_optional()
        if op is operator.truediv:
            base: dt.DType = dt.FLOAT if {l, r} <= {dt.INT, dt.FLOAT} else dt.ANY
        elif {l, r} <= {dt.INT, dt.FLOAT, dt.BOOL}:
            base = dt.FLOAT if dt.FLOAT in (l, r) else dt.INT
        elif l == dt.STR and r == dt.STR and op is operator.add:
            base = dt.STR
        elif l == dt.STR and r == dt.INT and op is operator.mul:
            base = dt.STR
        elif l == r:
            base = l
        elif {l, r} == {dt.DATE_TIME_NAIVE, dt.DURATION}:
            base = dt.DATE_TIME_NAIVE
        elif {l, r} == {dt.DATE_TIME_UTC, dt.DURATION}:
            base = dt.DATE_TIME_UTC
        elif l == dt.DATE_TIME_NAIVE and r == dt.DATE_TIME_NAIVE:
            base = dt.DURATION
        else:
            base = dt.ANY
        if (left.is_optional() or right.is_optional()) and base not in (dt.ANY,):
            return dt.Optional_(base)
        return base
    if isinstance(e, expr.ColumnUnaryOpExpression):
        inner = infer_dtype(e._expr)
        if e._operator is operator.not_:
            return dt.BOOL
        return inner
    if isinstance(e, expr.IfElseExpression):
        return dt.types_lca(infer_dtype(e._then), infer_dtype(e._else))
    if isinstance(e, expr.CoalesceExpression):
        result = infer_dtype(e._args[0]).strip_optional() if e._args else dt.ANY
        for a in e._args[1:]:
            result = dt.types_lca(result, infer_dtype(a).strip_optional())
        last = infer_dtype(e._args[-1]) if e._args else dt.ANY
        if last.is_optional() or last == dt.NONE:
            return dt.Optional_(result) if result != dt.ANY else result
        return result
    if isinstance(e, expr.RequireExpression):
        inner = infer_dtype(e._val)
        return inner if inner.is_optional() else dt.Optional_(inner)
    if isinstance(e, (expr.IsNoneExpression, expr.IsNotNoneExpression)):
        return dt.BOOL
    if isinstance(e, expr.CastExpression):
        return e._target
    if isinstance(e, expr.ConvertExpression):
        return e._target if e._unwrap else dt.Optional_(e._target)
    if isinstance(e, expr.DeclareTypeExpression):
        return e._target
    if isinstance(e, expr.UnwrapExpression):
        return infer_dtype(e._expr).strip_optional()
    if isinstance(e, expr.FillErrorExpression):
        return dt.types_lca(infer_dtype(e._expr), infer_dtype(e._replacement))
    if isinstance(e, expr.ApplyExpression):
        return e._return_type
    if isinstance(e, expr.PointerExpression):
        return dt.Optional_(dt.POINTER) if e._optional else dt.POINTER
    if isinstance(e, expr.MakeTupleExpression):
        return dt.Tuple_(*(infer_dtype(a) for a in e._args))
    if isinstance(e, expr.GetExpression):
        obj = infer_dtype(e._object).strip_optional()
        if obj == dt.JSON:
            return dt.JSON if not e._check_if_exists else dt.Optional_(dt.JSON)
        if isinstance(obj, dt.List_):
            return obj.wrapped
        if isinstance(obj, dt.Tuple_):
            idx = e._index
            if isinstance(idx, expr.ColumnConstExpression) and isinstance(idx._value, int):
                if 0 <= idx._value < len(obj.args):
                    return obj.args[idx._value]
            return dt.ANY
        if isinstance(obj, dt.Array):
            return obj.wrapped if obj.n_dim == 1 else dt.ANY
        return dt.ANY
    if isinstance(e, expr.MethodCallExpression):
        rm = e._return_mapper
        if isinstance(rm, dt.DType):
            return rm
        try:
            return rm([infer_dtype(a) for a in e._args])
        except Exception:
            return dt.ANY
    if isinstance(e, expr.ReducerExpression):
        return e._reducer.return_dtype([infer_dtype(a) for a in e._args])
    return dt.ANY


def eval_type(e: expr.ColumnExpression) -> dt.DType:
    return infer_dtype(e)
