"""Deterministic schedule exploration for the cluster protocols (loom-style).

The fence/quiesce/rejoin dance, the aligned checkpoint sequence, and the
coalescer's admission protocol are hand-written thread protocols whose bugs
live in *interleavings* — and until now the only interleavings ever tested
were whatever the OS scheduler produced (chaos testing). This module is a
loom/shuttle-style deterministic scheduler: protocol *models* (see
``internals/protocol_models.py``) run on real Python threads, but every
synchronization primitive is a controlled handoff point — exactly ONE model
thread runs at a time, and at every decision point the scheduler picks which
runnable thread proceeds. That makes a run a pure function of its decision
sequence, so schedules can be:

- **seeded** (``DeterministicScheduler(seed=N)``) — a random walk whose
  choices replay bit-identically from the same seed;
- **replayed** (``choices=[...]``) — the exact failing interleaving re-runs
  from the recorded choice list (``sched.choices_taken``);
- **explored** (:func:`explore`) — bounded-exhaustive DFS over the decision
  tree (the CHESS/stateless-model-checking shape): every schedule differs in
  at least one decision, so N schedules are N *distinct* interleavings.

Failure modes are typed and all carry the replayable schedule:
:class:`DeadlockError` (no thread can proceed — e.g. a lock-order inversion),
:class:`LivelockError` (step bound exceeded), :class:`InvariantViolation`
(a model assertion failed under this interleaving). Each failure also emits a
``modelcheck`` flight-recorder event naming the model, seed, and failing
choice sequence, and bumps the ``modelcheck.*`` stage counters — the same
PR-5 telemetry plane the chaos harness feeds.

Timeouts are modeled, not slept: a ``wait(timeout=...)`` is *always*
schedulable — the scheduler may deliver a spurious/timeout wakeup — while an
untimed ``wait()`` is only runnable after a notify. A protocol that deadlocks
under model checking unless its waits are timed is exactly the PWA102
finding, proven dynamically.

Seed resolution when neither ``seed`` nor ``choices`` is given:
``PATHWAY_SCHED_SEED`` env var, else the chaos plan's ``{"sched": {"seed": N}}``
entry (``internals/chaos.py``), else 0.
"""

from __future__ import annotations

import os
import random
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# one handoff must complete within this wall bound or the HOST (not the model)
# is considered wedged — model-level deadlocks are detected logically and
# never wait on wall time
_WALL_TIMEOUT_S = 20.0


class SchedulingError(RuntimeError):
    """Base of every model-check failure; carries the replayable schedule."""

    def __init__(
        self,
        message: str,
        *,
        schedule: "Sequence[int] | None" = None,
        seed: "int | None" = None,
        trace: "Sequence[str] | None" = None,
    ):
        super().__init__(message)
        self.schedule = list(schedule or [])
        self.seed = seed
        self.trace = list(trace or [])


class DeadlockError(SchedulingError):
    """No runnable thread remains while unfinished threads exist."""


class LivelockError(SchedulingError):
    """The step bound was exceeded (or a model thread stopped yielding)."""


class InvariantViolation(SchedulingError):
    """A model assertion failed under this interleaving."""


class _Killed(BaseException):
    """Internal: unwinds model threads when a run aborts. BaseException so
    model-level ``except Exception`` cannot swallow the teardown."""


def default_seed() -> int:
    """PATHWAY_SCHED_SEED, else the chaos plan's ``sched.seed``, else 0."""
    env = os.environ.get("PATHWAY_SCHED_SEED")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    try:
        from pathway_tpu.internals.chaos import get_chaos

        chaos = get_chaos()
        if chaos is not None:
            seed = chaos.sched_seed()
            if seed is not None:
                return seed
    except Exception:
        pass
    return 0


class _Thread:
    """One model thread under scheduler control."""

    __slots__ = (
        "name", "fn", "args", "go", "done", "started",
        "pred", "timed", "wake_reason", "op", "exception", "real",
    )

    def __init__(self, name: str, fn: Callable[..., Any], args: tuple):
        self.name = name
        self.fn = fn
        self.args = args
        self.go = threading.Event()
        self.done = False
        self.started = False
        self.pred: "Optional[Callable[[], bool]]" = None
        self.timed = False
        self.wake_reason = "signal"
        self.op = "spawn"
        self.exception: "Optional[BaseException]" = None
        self.real: "Optional[threading.Thread]" = None


class DeterministicScheduler:
    """Runs model threads one at a time under a controlled decision sequence.

    Use :meth:`lock`/:meth:`condition`/:meth:`event` to mint primitives,
    :meth:`spawn` to add threads, then :meth:`run` (from the owning thread) to
    drive the model to completion. ``choices`` replays a recorded schedule
    prefix; past its end the policy takes over (``"rng"`` = seeded random
    walk, ``"first"`` = lowest-index — what the DFS explorer uses)."""

    def __init__(
        self,
        *,
        seed: "Optional[int]" = None,
        choices: "Optional[Sequence[int]]" = None,
        policy: str = "rng",
        max_steps: int = 20_000,
        name: str = "model",
    ):
        if seed is None:
            seed = default_seed()
        self.seed = seed
        self.name = name
        self.policy = policy
        self.max_steps = max_steps
        self._rng = random.Random(seed)
        self._preset = list(choices or [])
        #: decision list of this run — replay it via ``choices=`` for an
        #: identical interleaving
        self.choices_taken: List[int] = []
        #: how many threads were enabled at each decision (DFS backtracking)
        self.enabled_counts: List[int] = []
        #: human-readable step log: "step thread op"
        self.trace: List[str] = []
        self._threads: List[_Thread] = []
        self._control = threading.Event()
        self._killed = False
        self._tls = threading.local()
        self._ran = False

    # -- primitives ----------------------------------------------------------

    def lock(self, name: str = "lock") -> "SchedLock":
        return SchedLock(self, name)

    def condition(self, lock: "Optional[SchedLock]" = None, name: str = "cond") -> "SchedCondition":
        return SchedCondition(self, lock, name)

    def event(self, name: str = "event") -> "SchedEvent":
        return SchedEvent(self, name)

    # -- threads -------------------------------------------------------------

    def spawn(self, fn: Callable[..., Any], *args: Any, name: "Optional[str]" = None) -> None:
        """Register (and start, parked) one model thread. Callable both before
        :meth:`run` and from inside a running model thread (a model of a
        supervisor relaunching a rank spawns mid-run)."""
        t = _Thread(name or f"t{len(self._threads)}", fn, args)
        self._threads.append(t)
        real = threading.Thread(
            target=self._wrapper, args=(t,), daemon=True,
            name=f"pathway:sched-{self.name}-{t.name}",
        )
        t.real = real
        real.start()

    def _wrapper(self, t: _Thread) -> None:
        self._tls.current = t
        try:
            # park until first scheduled
            while not t.go.wait(timeout=0.25):
                if self._killed:
                    return
            t.go.clear()
            if self._killed:
                return
            t.fn(*t.args)
        except _Killed:
            pass
        except BaseException as exc:
            t.exception = exc
        finally:
            t.done = True
            self._control.set()

    def current(self) -> _Thread:
        t = getattr(self._tls, "current", None)
        if t is None:
            raise RuntimeError("not inside a scheduler-managed thread")
        return t

    # -- handoff core --------------------------------------------------------

    def yield_point(
        self,
        op: str = "step",
        *,
        pred: "Optional[Callable[[], bool]]" = None,
        timed: bool = False,
    ) -> str:
        """Called from model threads: hand control back to the scheduler.
        With ``pred`` the thread blocks until the predicate holds (or, if
        ``timed``, until the scheduler delivers a timeout wakeup). Returns the
        wake reason: ``"signal"`` or ``"timeout"``."""
        t = self.current()
        t.op = op
        t.pred = pred
        t.timed = timed
        self._control.set()
        while not t.go.wait(timeout=0.25):
            if self._killed:
                raise _Killed()
        t.go.clear()
        if self._killed:
            raise _Killed()
        return t.wake_reason

    def _choose(self, n: int) -> int:
        i = len(self.choices_taken)
        if i < len(self._preset):
            idx = self._preset[i]
            if idx >= n:
                idx = n - 1  # model drifted shorter than the recorded prefix
        elif self.policy == "first":
            idx = 0
        else:
            idx = self._rng.randrange(n)
        self.choices_taken.append(idx)
        self.enabled_counts.append(n)
        return idx

    def _step_thread(self, t: _Thread) -> None:
        self._control.clear()
        t.go.set()
        if not self._control.wait(timeout=_WALL_TIMEOUT_S):
            self._abort()
            raise LivelockError(
                f"model thread {t.name!r} did not yield within "
                f"{_WALL_TIMEOUT_S:.0f}s wall time (op {t.op!r}) — a model "
                "thread used an uninstrumented blocking primitive",
                schedule=self.choices_taken, seed=self.seed, trace=self.trace,
            )

    def _abort(self) -> None:
        self._killed = True
        for t in self._threads:
            t.go.set()
        for t in self._threads:
            if t.real is not None:
                t.real.join(timeout=_WALL_TIMEOUT_S)

    # -- driver --------------------------------------------------------------

    def run(self, check: "Optional[Callable[[], None]]" = None) -> "DeterministicScheduler":
        """Drive the model to completion; raises a typed
        :class:`SchedulingError` carrying the replayable schedule on deadlock,
        livelock, or invariant violation. ``check`` (if given) runs after all
        threads finish — its ``AssertionError`` is an invariant violation
        too."""
        if self._ran:
            raise RuntimeError("a DeterministicScheduler drives one run; build a new one")
        self._ran = True
        try:
            self._loop()
            if check is not None:
                try:
                    check()
                except AssertionError as exc:
                    raise InvariantViolation(
                        f"model {self.name!r} post-condition failed: {exc}",
                        schedule=self.choices_taken, seed=self.seed,
                        trace=self.trace,
                    ) from exc
        except SchedulingError as exc:
            self._report(failed=type(exc).__name__)
            raise
        self._report(failed=None)
        return self

    def _loop(self) -> None:
        steps = 0
        while True:
            alive = [t for t in self._threads if not t.done]
            if not alive:
                break
            enabled: List[_Thread] = []
            for t in alive:
                if t.pred is None or t.timed or t.pred():
                    enabled.append(t)
            if not enabled:
                waiting = ", ".join(f"{t.name}@{t.op}" for t in alive)
                self._abort()
                raise DeadlockError(
                    f"model {self.name!r} deadlocked: no runnable thread "
                    f"(blocked: {waiting})",
                    schedule=self.choices_taken, seed=self.seed, trace=self.trace,
                )
            if steps >= self.max_steps:
                self._abort()
                raise LivelockError(
                    f"model {self.name!r} exceeded {self.max_steps} steps",
                    schedule=self.choices_taken, seed=self.seed, trace=self.trace,
                )
            t = enabled[self._choose(len(enabled))]
            if t.pred is not None:
                t.wake_reason = "signal" if t.pred() else "timeout"
                t.pred = None
                t.timed = False
            self.trace.append(f"{steps}:{t.name}:{t.op}")
            self._step_thread(t)
            steps += 1
            failed = next((x for x in self._threads if x.exception is not None), None)
            if failed is not None:
                exc = failed.exception
                self._abort()
                if isinstance(exc, AssertionError):
                    raise InvariantViolation(
                        f"model {self.name!r} invariant failed in thread "
                        f"{failed.name!r}: {exc}",
                        schedule=self.choices_taken, seed=self.seed,
                        trace=self.trace,
                    ) from exc
                raise SchedulingError(
                    f"model {self.name!r} thread {failed.name!r} crashed: "
                    f"{type(exc).__name__}: {exc}",
                    schedule=self.choices_taken, seed=self.seed, trace=self.trace,
                ) from exc
        for t in self._threads:
            if t.real is not None:
                t.real.join(timeout=_WALL_TIMEOUT_S)

    def _report(self, failed: "Optional[str]") -> None:
        """Model-check results ride the PR-5 telemetry plane: counters always,
        a ``modelcheck`` flight event naming the failing seed + schedule on
        failure (post-mortems can replay the exact interleaving)."""
        try:
            from pathway_tpu.engine.telemetry import stage_add_many

            updates = {"modelcheck.runs": 1.0, "modelcheck.steps": float(len(self.trace))}
            if failed is not None:
                updates["modelcheck.failures"] = 1.0
            stage_add_many(updates)
            if failed is not None:
                from pathway_tpu.engine.profile import get_flight_recorder

                get_flight_recorder().record_event(
                    "modelcheck",
                    model=self.name,
                    failure=failed,
                    seed=self.seed,
                    schedule=list(self.choices_taken),
                )
        except Exception:
            pass  # telemetry must never mask the model-check result


# ---------------------------------------------------------------------------
# instrumented primitives
# ---------------------------------------------------------------------------


class SchedLock:
    """Mutex under scheduler control (``with``-able, non-reentrant)."""

    def __init__(self, sched: DeterministicScheduler, name: str):
        self._sched = sched
        self.name = name
        self._owner: "Optional[_Thread]" = None

    def acquire(self) -> None:
        sched = self._sched
        t = sched.current()
        sched.yield_point(f"acquire({self.name})", pred=lambda: self._owner is None)
        self._owner = t

    def release(self) -> None:
        if self._owner is not self._sched.current():
            raise RuntimeError(f"release of {self.name} by non-owner")
        self._owner = None
        # a release is a decision point: who runs next decides who wins the lock
        self._sched.yield_point(f"release({self.name})")

    def held(self) -> bool:
        return self._owner is not None

    def __enter__(self) -> "SchedLock":
        self.acquire()
        return self

    def __exit__(self, *_exc: Any) -> None:
        self.release()


class SchedCondition:
    """Condition variable bound to a :class:`SchedLock` (notify-all model).

    ``wait(timeout=None)`` is only woken by a notify; ``wait(timeout=x)`` is
    additionally always schedulable as a timeout wakeup — the model-level
    meaning of an abortable wait. Returns True for a signal, False for a
    timeout (the stdlib contract)."""

    def __init__(self, sched: DeterministicScheduler, lock: "Optional[SchedLock]", name: str):
        self._sched = sched
        self.name = name
        self.lock = lock if lock is not None else sched.lock(f"{name}.lock")
        self._gen = 0

    def wait(self, timeout: "Optional[float]" = None) -> bool:
        sched = self._sched
        t = sched.current()
        if self.lock._owner is not t:
            raise RuntimeError(f"wait on {self.name} without holding {self.lock.name}")
        my_gen = self._gen
        self.lock._owner = None  # release; the wait itself is the yield
        reason = sched.yield_point(
            f"wait({self.name})",
            pred=lambda: self._gen > my_gen,
            timed=timeout is not None,
        )
        sched.yield_point(
            f"reacquire({self.lock.name})", pred=lambda: self.lock._owner is None
        )
        self.lock._owner = t
        return reason == "signal"

    def notify_all(self) -> None:
        self._gen += 1
        self._sched.yield_point(f"notify_all({self.name})")

    notify = notify_all  # model simplification: wakeups are re-checked anyway

    def __enter__(self) -> "SchedCondition":
        self.lock.acquire()
        return self

    def __exit__(self, *_exc: Any) -> None:
        self.lock.release()


class SchedEvent:
    """One-shot flag with modeled-timeout waits."""

    def __init__(self, sched: DeterministicScheduler, name: str):
        self._sched = sched
        self.name = name
        self._flag = False

    def set(self) -> None:
        self._flag = True
        self._sched.yield_point(f"set({self.name})")

    def clear(self) -> None:
        self._flag = False

    def is_set(self) -> bool:
        return self._flag

    def wait(self, timeout: "Optional[float]" = None) -> bool:
        self._sched.yield_point(
            f"wait({self.name})",
            pred=lambda: self._flag,
            timed=timeout is not None,
        )
        return self._flag


# ---------------------------------------------------------------------------
# exploration drivers
# ---------------------------------------------------------------------------

#: a model: receives a fresh scheduler, spawns its threads against fresh
#: state, and returns an optional post-condition callable
Model = Callable[[DeterministicScheduler], "Optional[Callable[[], None]]"]


@dataclass
class ExploreResult:
    """Outcome of a bounded-exhaustive or seeded sweep."""

    schedules_run: int
    distinct_schedules: int
    failure: "Optional[SchedulingError]" = None
    failing_schedule: "Optional[List[int]]" = None
    failing_seed: "Optional[int]" = None
    stats: Dict[str, Any] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return self.failure is None


def run_once(
    model: Model,
    *,
    seed: "Optional[int]" = None,
    choices: "Optional[Sequence[int]]" = None,
    policy: "Optional[str]" = None,
    max_steps: int = 20_000,
    name: str = "model",
) -> DeterministicScheduler:
    """One schedule: seeded random walk, or exact replay via ``choices``."""
    sched = DeterministicScheduler(
        seed=seed,
        choices=choices,
        policy=policy or ("first" if choices is not None else "rng"),
        max_steps=max_steps,
        name=name,
    )
    check = model(sched)
    sched.run(check=check)
    return sched


def explore(
    model: Model,
    *,
    max_schedules: int = 500,
    max_steps: int = 20_000,
    name: str = "model",
) -> ExploreResult:
    """Bounded-exhaustive DFS over the decision tree (stateless model
    checking): re-run the model with a growing choice prefix, backtracking at
    the deepest decision with an untried branch. Every schedule differs in at
    least one decision. Stops at the first failure (replayable via
    ``failing_schedule``) or after ``max_schedules``."""
    prefix: List[int] = []
    distinct: "set[Tuple[int, ...]]" = set()
    runs = 0
    while runs < max_schedules:
        sched = DeterministicScheduler(
            choices=prefix, policy="first", max_steps=max_steps, name=name
        )
        try:
            check = model(sched)
            sched.run(check=check)
        except SchedulingError as exc:
            distinct.add(tuple(sched.choices_taken))
            return ExploreResult(
                schedules_run=runs + 1,
                distinct_schedules=len(distinct),
                failure=exc,
                failing_schedule=list(exc.schedule),
                failing_seed=sched.seed,
            )
        runs += 1
        distinct.add(tuple(sched.choices_taken))
        taken, counts = sched.choices_taken, sched.enabled_counts
        i = len(taken) - 1
        while i >= 0 and taken[i] + 1 >= counts[i]:
            i -= 1
        if i < 0:
            break  # decision tree exhausted below the bound
        prefix = taken[:i] + [taken[i] + 1]
    return ExploreResult(schedules_run=runs, distinct_schedules=len(distinct))


def sweep_seeds(
    model: Model,
    *,
    seeds: "Optional[Sequence[int]]" = None,
    n_seeds: int = 200,
    base_seed: "Optional[int]" = None,
    max_steps: int = 20_000,
    name: str = "model",
) -> ExploreResult:
    """Seeded random-walk sweep: ``n_seeds`` independent walks (base_seed +
    i). Complements :func:`explore` — DFS is systematic near the root, seeded
    walks spread over the whole depth. Stops at the first failure with its
    seed recorded for replay."""
    if seeds is None:
        base = default_seed() if base_seed is None else base_seed
        seeds = [base + i for i in range(n_seeds)]
    distinct: "set[Tuple[int, ...]]" = set()
    runs = 0
    for seed in seeds:
        sched = DeterministicScheduler(seed=seed, policy="rng", max_steps=max_steps, name=name)
        try:
            check = model(sched)
            sched.run(check=check)
        except SchedulingError as exc:
            distinct.add(tuple(sched.choices_taken))
            return ExploreResult(
                schedules_run=runs + 1,
                distinct_schedules=len(distinct),
                failure=exc,
                failing_schedule=list(exc.schedule),
                failing_seed=seed,
            )
        runs += 1
        distinct.add(tuple(sched.choices_taken))
    return ExploreResult(schedules_run=runs, distinct_schedules=len(distinct))
