"""``pw.sql`` — SQL queries over tables.

Parity: reference ``internals/sql.py`` (sqlglot-based). sqlglot is not in this image, so a
compact recursive-descent parser covers the supported subset: SELECT (exprs, aliases), FROM,
WHERE, GROUP BY, HAVING, and the reducers COUNT/SUM/MIN/MAX/AVG. Unsupported syntax raises.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table

_TOKEN = re.compile(
    r"\s*(?:(?P<num>\d+\.\d+|\d+)|(?P<id>[A-Za-z_][A-Za-z_0-9.]*)|(?P<str>'[^']*')"
    r"|(?P<op><=|>=|<>|!=|=|<|>|\+|-|\*|/|%|\(|\)|,))"
)

_AGGS = {"count", "sum", "min", "max", "avg"}


class _Parser:
    def __init__(self, text: str, tables: Dict[str, Table]):
        self.tokens = self._tokenize(text)
        self.pos = 0
        self.tables = tables
        self.table: Table | None = None

    @staticmethod
    def _tokenize(text: str) -> List[str]:
        out = []
        pos = 0
        while pos < len(text):
            m = _TOKEN.match(text, pos)
            if m is None:
                if text[pos:].strip() == "":
                    break
                raise ValueError(f"cannot tokenize SQL near {text[pos:pos+20]!r}")
            out.append(m.group().strip())
            pos = m.end()
        return out

    def peek(self) -> str | None:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> str:
        tok = self.tokens[self.pos]
        self.pos += 1
        return tok

    def expect(self, word: str) -> None:
        tok = self.next()
        if tok.lower() != word.lower():
            raise ValueError(f"expected {word!r}, got {tok!r}")

    def at_keyword(self, *words: str) -> bool:
        tok = self.peek()
        return tok is not None and tok.lower() in words

    # expression grammar: comparison > additive > multiplicative > atom
    def parse_expr(self) -> Any:
        left = self.parse_add()
        if self.peek() in ("=", "<>", "!=", "<", "<=", ">", ">="):
            op = self.next()
            right = self.parse_add()
            import operator as _op

            mapping = {
                "=": _op.eq,
                "<>": _op.ne,
                "!=": _op.ne,
                "<": _op.lt,
                "<=": _op.le,
                ">": _op.gt,
                ">=": _op.ge,
            }
            return expr.ColumnBinaryOpExpression(mapping[op], left, right)
        return left

    def parse_condition(self) -> Any:
        left = self.parse_expr()
        while self.at_keyword("and", "or"):
            kw = self.next().lower()
            right = self.parse_expr()
            import operator as _op

            left = expr.ColumnBinaryOpExpression(
                _op.and_ if kw == "and" else _op.or_, left, right
            )
        return left

    def parse_add(self) -> Any:
        left = self.parse_mul()
        while self.peek() in ("+", "-"):
            op = self.next()
            right = self.parse_mul()
            import operator as _op

            left = expr.ColumnBinaryOpExpression(_op.add if op == "+" else _op.sub, left, right)
        return left

    def parse_mul(self) -> Any:
        left = self.parse_atom()
        while self.peek() in ("*", "/", "%"):
            op = self.next()
            right = self.parse_atom()
            import operator as _op

            mapping = {"*": _op.mul, "/": _op.truediv, "%": _op.mod}
            left = expr.ColumnBinaryOpExpression(mapping[op], left, right)
        return left

    def parse_atom(self) -> Any:
        tok = self.peek()
        if tok is None:
            raise ValueError("unexpected end of SQL")
        if tok == "(":
            self.next()
            e = self.parse_condition()
            self.expect(")")
            return e
        if re.fullmatch(r"\d+", tok):
            self.next()
            return expr.ColumnConstExpression(int(tok))
        if re.fullmatch(r"\d+\.\d+", tok):
            self.next()
            return expr.ColumnConstExpression(float(tok))
        if tok.startswith("'"):
            self.next()
            return expr.ColumnConstExpression(tok[1:-1])
        # identifier / function call
        self.next()
        if self.peek() == "(":
            fn = tok.lower()
            self.next()
            if fn == "count" and self.peek() == "*":
                self.next()
                self.expect(")")
                return reducers.count()
            args = []
            if self.peek() != ")":
                args.append(self.parse_condition())
                while self.peek() == ",":
                    self.next()
                    args.append(self.parse_condition())
            self.expect(")")
            if fn in _AGGS:
                return getattr(reducers, fn)(*args)
            raise ValueError(f"unsupported SQL function {fn!r}")
        name = tok.split(".")[-1]
        assert self.table is not None
        return self.table[name]


def sql(query: str, **tables: Table) -> Table:
    """Run a SQL SELECT over the given tables (supported: WHERE/GROUP BY/HAVING + aggs)."""
    p = _Parser(query, tables)
    p.expect("select")
    select_items: List[tuple] = []  # (alias, token-slice start) — parse later once FROM known
    start = p.pos
    depth = 0
    while not (p.at_keyword("from") and depth == 0):
        tok = p.next()
        if tok == "(":
            depth += 1
        elif tok == ")":
            depth -= 1
        if p.peek() is None:
            raise ValueError("SELECT without FROM")
    select_tokens = p.tokens[start : p.pos]
    p.expect("from")
    table_name = p.next()
    if table_name not in tables:
        raise ValueError(f"unknown table {table_name!r}")
    table = tables[table_name]
    p.table = table

    # re-parse the select list with the table bound
    sel = _Parser("", tables)
    sel.tokens = select_tokens
    sel.table = table
    exprs: Dict[str, Any] = {}
    idx = 0
    while sel.peek() is not None:
        if sel.peek() == "*":
            sel.next()
            for name in table.column_names():
                exprs[name] = table[name]
        else:
            e = sel.parse_condition()
            alias = None
            if sel.at_keyword("as"):
                sel.next()
                alias = sel.next()
            if alias is None:
                if isinstance(e, expr.ColumnReference):
                    alias = e.name
                else:
                    alias = f"col_{idx}"
            exprs[alias] = e
        idx += 1
        if sel.peek() == ",":
            sel.next()

    where_e = None
    if p.at_keyword("where"):
        p.next()
        where_e = p.parse_condition()
    group_cols: List[Any] = []
    if p.at_keyword("group"):
        p.next()
        p.expect("by")
        group_cols.append(p.parse_expr())
        while p.peek() == ",":
            p.next()
            group_cols.append(p.parse_expr())
    having_e = None
    if p.at_keyword("having"):
        p.next()
        having_e = p.parse_condition()

    result = table
    if where_e is not None:
        result = result.filter(_rebind(where_e, table, result))
        p.table = result
        exprs = {k: _rebind(v, table, result) for k, v in exprs.items()}
        group_cols = [_rebind(g, table, result) for g in group_cols]
        if having_e is not None:
            having_e = _rebind(having_e, table, result)

    has_aggs = any(_contains_reducer(e) for e in exprs.values())
    if group_cols or has_aggs:
        grouped = result.groupby(*group_cols) if group_cols else result.groupby()
        if having_e is not None:
            exprs["_pw_having"] = having_e
        out = grouped.reduce(**exprs)
        if having_e is not None:
            out = out.filter(out._pw_having).without("_pw_having")
        return out
    return result.select(**exprs)


def _rebind(e: Any, old: Table, new: Table) -> Any:
    if isinstance(e, expr.ColumnReference):
        return new[e.name] if e.table is old else e
    if isinstance(e, expr.ReducerExpression):
        clone = expr.ReducerExpression(e._reducer)
        clone._args = tuple(_rebind(a, old, new) for a in e._args)
        clone._kwargs = e._kwargs
        return clone
    if isinstance(e, expr.ColumnExpression):
        import copy

        clone = copy.copy(e)
        for attr, value in list(vars(e).items()):
            if isinstance(value, expr.ColumnExpression):
                setattr(clone, attr, _rebind(value, old, new))
            elif isinstance(value, tuple) and any(isinstance(v, expr.ColumnExpression) for v in value):
                setattr(
                    clone,
                    attr,
                    tuple(
                        _rebind(v, old, new) if isinstance(v, expr.ColumnExpression) else v
                        for v in value
                    ),
                )
        return clone
    return e


def _contains_reducer(e: Any) -> bool:
    if isinstance(e, expr.ReducerExpression):
        return True
    if isinstance(e, expr.ColumnExpression):
        return any(_contains_reducer(d) for d in e._deps())
    return False
