"""``pw.sql`` — SQL queries over tables.

Parity: reference ``internals/sql.py`` (sqlglot AST -> Table ops). sqlglot is not in
this image, so this module carries its own SQL front end: a tokenizer + recursive-
descent parser building a query AST (SELECT/DISTINCT, FROM with table aliases and
subqueries, INNER/LEFT/RIGHT/FULL JOIN ... ON, WHERE, GROUP BY, HAVING, UNION [ALL]),
and a planner lowering the AST onto the Table algebra: equi-conditions in ON become
join conditions, residual ON predicates post-filter, subqueries plan recursively,
UNION maps to concat_reindex (+ distinct), and qualified/unqualified column names
resolve against the FROM scope with ambiguity errors. Predicates support AND/OR/NOT,
comparisons, IS [NOT] NULL, [NOT] IN (...), [NOT] BETWEEN, and [NOT] LIKE.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table

# -- tokenizer --------------------------------------------------------------------

_TOKEN = re.compile(
    r"\s*(?:(?P<num>\d+\.\d+|\d+)|(?P<str>'(?:''|[^'])*')"
    r"|(?P<id>[A-Za-z_][A-Za-z_0-9]*)|(?P<op><=|>=|<>|!=|=|<|>|\+|-|\*|/|%|\(|\)|,|\.))"
)

_KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having", "union", "all",
    "join", "inner", "left", "right", "full", "outer", "on", "as", "and", "or",
    "not", "is", "null", "in", "between", "like", "asc", "desc", "order",
}

_AGGS = {"count", "sum", "min", "max", "avg"}


class _Tokens:
    def __init__(self, text: str):
        self.toks: List[str] = []
        pos = 0
        while pos < len(text):
            m = _TOKEN.match(text, pos)
            if m is None:
                if text[pos:].strip() == "":
                    break
                raise ValueError(f"cannot tokenize SQL near {text[pos:pos+20]!r}")
            self.toks.append(m.group().strip())
            pos = m.end()
        self.pos = 0

    def peek(self, ahead: int = 0) -> Optional[str]:
        i = self.pos + ahead
        return self.toks[i] if i < len(self.toks) else None

    def peek_kw(self, *words: str) -> bool:
        tok = self.peek()
        return tok is not None and tok.lower() in words

    def next(self) -> str:
        tok = self.toks[self.pos]
        self.pos += 1
        return tok

    def accept_kw(self, *words: str) -> Optional[str]:
        if self.peek_kw(*words):
            return self.next().lower()
        return None

    def expect(self, word: str) -> None:
        tok = self.next() if self.pos < len(self.toks) else None
        if tok is None or tok.lower() != word.lower():
            raise ValueError(f"expected {word!r}, got {tok!r}")


# -- AST ---------------------------------------------------------------------------


@dataclass
class Ident:
    qualifier: Optional[str]
    name: str


@dataclass
class Literal:
    value: Any


@dataclass
class Star:
    qualifier: Optional[str] = None


@dataclass
class Unary:
    op: str  # "not" | "neg"
    operand: Any


@dataclass
class Binary:
    op: str
    left: Any
    right: Any


@dataclass
class Func:
    name: str
    args: List[Any]
    star: bool = False


@dataclass
class InList:
    operand: Any
    items: List[Any]
    negated: bool


@dataclass
class Between:
    operand: Any
    low: Any
    high: Any
    negated: bool


@dataclass
class Like:
    operand: Any
    pattern: str
    negated: bool


@dataclass
class IsNull:
    operand: Any
    negated: bool


@dataclass
class SelectItem:
    expression: Any
    alias: Optional[str]


@dataclass
class TableRef:
    name: Optional[str]  # None for subqueries
    subquery: Optional["Query"]
    alias: str


@dataclass
class Join:
    kind: str  # inner/left/right/outer
    table: TableRef
    on: Any


@dataclass
class Select:
    items: List[Any]  # SelectItem | Star
    distinct: bool
    base: TableRef
    joins: List[Join]
    where: Any
    group_by: List[Any]
    having: Any


@dataclass
class Query:
    selects: List[Select]  # UNION chain
    union_all: List[bool] = field(default_factory=list)  # per junction


# -- parser ------------------------------------------------------------------------


class _Parser:
    def __init__(self, text: str):
        self.t = _Tokens(text)

    def parse_query(self) -> Query:
        query = self.parse_subquery()
        if self.t.peek() is not None:
            raise ValueError(f"unexpected trailing SQL at {self.t.peek()!r}")
        return query

    def parse_select(self) -> Select:
        self.t.expect("select")
        distinct = self.t.accept_kw("distinct") is not None
        items: List[Any] = [self.parse_select_item()]
        while self.t.peek() == ",":
            self.t.next()
            items.append(self.parse_select_item())
        self.t.expect("from")
        base = self.parse_table_ref()
        joins: List[Join] = []
        while self.t.peek_kw("join", "inner", "left", "right", "full"):
            joins.append(self.parse_join())
        where = None
        if self.t.accept_kw("where"):
            where = self.parse_condition()
        group_by: List[Any] = []
        if self.t.accept_kw("group"):
            self.t.expect("by")
            group_by.append(self.parse_condition())
            while self.t.peek() == ",":
                self.t.next()
                group_by.append(self.parse_condition())
        having = None
        if self.t.accept_kw("having"):
            having = self.parse_condition()
        if self.t.peek_kw("order"):
            raise NotImplementedError(
                "ORDER BY has no meaning on an incremental table; use pw.Table.sort"
            )
        return Select(items, distinct, base, joins, where, group_by, having)

    def parse_select_item(self) -> Any:
        if self.t.peek() == "*":
            self.t.next()
            return Star()
        # qualified star: alias.*
        if (
            self.t.peek(1) == "."
            and self.t.peek(2) == "*"
            and self.t.peek() is not None
            and self.t.peek().lower() not in _KEYWORDS
        ):
            qualifier = self.t.next()
            self.t.next()
            self.t.next()
            return Star(qualifier)
        e = self.parse_condition()
        alias = None
        if self.t.accept_kw("as"):
            alias = self.t.next()
        elif (
            self.t.peek() is not None
            and re.fullmatch(r"[A-Za-z_]\w*", self.t.peek() or "")
            and (self.t.peek() or "").lower() not in _KEYWORDS
        ):
            alias = self.t.next()  # bare alias: SELECT a b
        return SelectItem(e, alias)

    def parse_table_ref(self) -> TableRef:
        if self.t.peek() == "(":
            self.t.next()
            sub = self.parse_subquery()
            self.t.expect(")")
            self.t.accept_kw("as")
            alias = self.t.next()
            return TableRef(None, sub, alias)
        name = self.t.next()
        alias = name
        if self.t.accept_kw("as"):
            alias = self.t.next()
        elif (
            self.t.peek() is not None
            and re.fullmatch(r"[A-Za-z_]\w*", self.t.peek() or "")
            and (self.t.peek() or "").lower() not in _KEYWORDS
        ):
            alias = self.t.next()
        return TableRef(name, None, alias)

    def parse_subquery(self) -> Query:
        selects = [self.parse_select()]
        union_all: List[bool] = []
        while self.t.accept_kw("union"):
            union_all.append(self.t.accept_kw("all") is not None)
            selects.append(self.parse_select())
        return Query(selects, union_all)

    def parse_join(self) -> Join:
        kind = "inner"
        kw = self.t.accept_kw("inner", "left", "right", "full")
        if kw in ("left", "right", "full"):
            kind = "outer" if kw == "full" else kw
            self.t.accept_kw("outer")
        self.t.expect("join")
        table = self.parse_table_ref()
        self.t.expect("on")
        on = self.parse_condition()
        return Join(kind, table, on)

    # expressions: or > and > not > comparison > add > mul > unary > atom
    def parse_condition(self) -> Any:
        left = self.parse_and()
        while self.t.accept_kw("or"):
            left = Binary("or", left, self.parse_and())
        return left

    def parse_and(self) -> Any:
        left = self.parse_not()
        while self.t.accept_kw("and"):
            left = Binary("and", left, self.parse_not())
        return left

    def parse_not(self) -> Any:
        if self.t.accept_kw("not"):
            return Unary("not", self.parse_not())
        return self.parse_cmp()

    def parse_cmp(self) -> Any:
        left = self.parse_add()
        if self.t.peek() in ("=", "<>", "!=", "<", "<=", ">", ">="):
            op = self.t.next()
            return Binary(op, left, self.parse_add())
        if self.t.peek_kw("is"):
            self.t.next()
            negated = self.t.accept_kw("not") is not None
            self.t.expect("null")
            return IsNull(left, negated)
        negated = False
        if self.t.peek_kw("not") and (self.t.peek(1) or "").lower() in ("in", "between", "like"):
            self.t.next()
            negated = True
        if self.t.accept_kw("in"):
            self.t.expect("(")
            items = [self.parse_add()]
            while self.t.peek() == ",":
                self.t.next()
                items.append(self.parse_add())
            self.t.expect(")")
            return InList(left, items, negated)
        if self.t.accept_kw("between"):
            low = self.parse_add()
            self.t.expect("and")
            high = self.parse_add()
            return Between(left, low, high, negated)
        if self.t.accept_kw("like"):
            pattern = self.t.next()
            if not pattern.startswith("'"):
                raise ValueError("LIKE requires a string literal pattern")
            return Like(left, pattern[1:-1].replace("''", "'"), negated)
        return left

    def parse_add(self) -> Any:
        left = self.parse_mul()
        while self.t.peek() in ("+", "-"):
            left = Binary(self.t.next(), left, self.parse_mul())
        return left

    def parse_mul(self) -> Any:
        left = self.parse_unary()
        while self.t.peek() in ("*", "/", "%"):
            left = Binary(self.t.next(), left, self.parse_unary())
        return left

    def parse_unary(self) -> Any:
        if self.t.peek() == "-":
            self.t.next()
            return Unary("neg", self.parse_unary())
        return self.parse_atom()

    def parse_atom(self) -> Any:
        tok = self.t.peek()
        if tok is None:
            raise ValueError("unexpected end of SQL")
        if tok == "(":
            self.t.next()
            e = self.parse_condition()
            self.t.expect(")")
            return e
        if re.fullmatch(r"\d+", tok):
            self.t.next()
            return Literal(int(tok))
        if re.fullmatch(r"\d+\.\d+", tok):
            self.t.next()
            return Literal(float(tok))
        if tok.startswith("'"):
            self.t.next()
            return Literal(tok[1:-1].replace("''", "'"))
        if tok.lower() == "null":
            self.t.next()
            return Literal(None)
        if tok.lower() in ("true", "false"):
            self.t.next()
            return Literal(tok.lower() == "true")
        # identifier / qualified identifier / function call
        name = self.t.next()
        if self.t.peek() == "(":
            self.t.next()
            if self.t.peek() == "*":
                self.t.next()
                self.t.expect(")")
                return Func(name.lower(), [], star=True)
            args = []
            if self.t.peek() != ")":
                args.append(self.parse_condition())
                while self.t.peek() == ",":
                    self.t.next()
                    args.append(self.parse_condition())
            self.t.expect(")")
            return Func(name.lower(), args)
        if self.t.peek() == ".":
            self.t.next()
            col = self.t.next()
            return Ident(name, col)
        return Ident(None, name)


# -- planner -----------------------------------------------------------------------


class _Scope:
    """FROM-clause name resolution: alias -> (Table, its column names)."""

    def __init__(self) -> None:
        self.order: List[str] = []
        self.tables: Dict[str, Table] = {}

    def add(self, alias: str, table: Table) -> None:
        if alias in self.tables:
            raise ValueError(f"duplicate table alias {alias!r}")
        self.order.append(alias)
        self.tables[alias] = table

    def resolve(self, ident: Ident) -> expr.ColumnReference:
        if ident.qualifier is not None:
            table = self.tables.get(ident.qualifier)
            if table is None:
                raise ValueError(f"unknown table alias {ident.qualifier!r}")
            return table[ident.name]
        hits = [
            alias
            for alias in self.order
            if ident.name in self.tables[alias].column_names()
        ]
        if not hits:
            raise ValueError(f"unknown column {ident.name!r}")
        if len(hits) > 1:
            raise ValueError(
                f"ambiguous column {ident.name!r} (in tables {hits}); qualify it"
            )
        return self.tables[hits[0]][ident.name]

    def all_columns(self, qualifier: Optional[str] = None) -> List[Tuple[str, Any]]:
        out: List[Tuple[str, Any]] = []
        aliases = [qualifier] if qualifier else self.order
        for alias in aliases:
            table = self.tables.get(alias)
            if table is None:
                raise ValueError(f"unknown table alias {alias!r}")
            for name in table.column_names():
                out.append((name, table[name]))
        return out


def _bind(node: Any, scope: _Scope) -> Any:
    """AST -> ColumnExpression against the scope."""
    import operator as _op

    if isinstance(node, Literal):
        return expr.ColumnConstExpression(node.value)
    if isinstance(node, Ident):
        return scope.resolve(node)
    if isinstance(node, Unary):
        operand = _bind(node.operand, scope)
        if node.op == "not":
            return expr.ColumnUnaryOpExpression(_op.not_, operand)
        return expr.ColumnBinaryOpExpression(
            _op.sub, expr.ColumnConstExpression(0), operand
        )
    if isinstance(node, Binary):
        mapping = {
            "=": _op.eq, "<>": _op.ne, "!=": _op.ne, "<": _op.lt, "<=": _op.le,
            ">": _op.gt, ">=": _op.ge, "+": _op.add, "-": _op.sub, "*": _op.mul,
            "/": _op.truediv, "%": _op.mod, "and": _op.and_, "or": _op.or_,
        }
        return expr.ColumnBinaryOpExpression(
            mapping[node.op], _bind(node.left, scope), _bind(node.right, scope)
        )
    if isinstance(node, Func):
        if node.name == "count" and node.star:
            return reducers.count()
        args = [_bind(a, scope) for a in node.args]
        if node.name in _AGGS:
            return getattr(reducers, node.name)(*args)
        if node.name == "coalesce":
            return expr.coalesce(*args)
        if node.name == "abs":
            return expr.apply_with_type(abs, float, *args)
        raise ValueError(f"unsupported SQL function {node.name!r}")
    if isinstance(node, InList):
        import functools
        import operator as _o

        operand = _bind(node.operand, scope)
        comparisons = [
            expr.ColumnBinaryOpExpression(_o.eq, operand, _bind(i, scope))
            for i in node.items
        ]
        out = functools.reduce(
            lambda a, b: expr.ColumnBinaryOpExpression(_o.or_, a, b), comparisons
        )
        if node.negated:
            out = expr.ColumnUnaryOpExpression(_o.not_, out)
        # NULL [NOT] IN (...) is NULL in SQL: the row is filtered either way
        return expr.ColumnBinaryOpExpression(_o.and_, operand.is_not_none(), out)
    if isinstance(node, Between):
        import operator as _o

        operand = _bind(node.operand, scope)
        out = expr.ColumnBinaryOpExpression(
            _o.and_,
            expr.ColumnBinaryOpExpression(_o.ge, operand, _bind(node.low, scope)),
            expr.ColumnBinaryOpExpression(_o.le, operand, _bind(node.high, scope)),
        )
        if node.negated:
            out = expr.ColumnUnaryOpExpression(_o.not_, out)
        return out
    if isinstance(node, Like):
        operand = _bind(node.operand, scope)
        # % -> .* and _ -> ., everything else literal (SQL LIKE, not glob)
        regex = re.compile(
            "^"
            + "".join(
                ".*" if ch == "%" else "." if ch == "_" else re.escape(ch)
                for ch in node.pattern
            )
            + "$",
            re.DOTALL,
        )
        negated = node.negated

        def like(v: Any) -> bool:
            if v is None:
                return False  # NULL [NOT] LIKE is NULL -> row filtered (SQL semantics)
            ok = regex.match(str(v)) is not None
            return (not ok) if negated else ok

        return expr.apply_with_type(like, bool, operand)
    if isinstance(node, IsNull):
        e = _bind(node.operand, scope)
        return e.is_not_none() if node.negated else e.is_none()
    raise ValueError(f"cannot bind SQL node {node!r}")


def _split_on_condition(on: Any) -> List[Any]:
    """Flatten an ON condition's top-level AND conjuncts."""
    if isinstance(on, Binary) and on.op == "and":
        return _split_on_condition(on.left) + _split_on_condition(on.right)
    return [on]


def _plan_table_ref(ref: TableRef, tables: Dict[str, Table]) -> Table:
    if ref.subquery is not None:
        return _plan_query(ref.subquery, tables)
    if ref.name not in tables:
        raise ValueError(f"unknown table {ref.name!r}")
    return tables[ref.name]


def _flatten_join(scope: _Scope) -> Tuple[Table, Dict[str, str]]:
    """Materialize a multi-table scope into ONE table carrying every column,
    disambiguating clashes as alias_column."""
    taken: Dict[str, int] = {}
    exprs: Dict[str, Any] = {}
    rename: Dict[str, str] = {}  # "alias.col" -> flattened name
    for alias in scope.order:
        for col in scope.tables[alias].column_names():
            name = col if col not in taken else f"{alias}_{col}"
            while name in exprs:
                name = f"{name}_"
            taken[col] = taken.get(col, 0) + 1
            exprs[name] = scope.tables[alias][col]
            rename[f"{alias}.{col}"] = name
    return exprs, rename  # type: ignore[return-value]


def _plan_select(sel: Select, tables: Dict[str, Table]) -> Table:
    scope = _Scope()
    base = _plan_table_ref(sel.base, tables)
    scope.add(sel.base.alias, base)

    result = base
    for join in sel.joins:
        right = _plan_table_ref(join.table, tables)
        right_alias = join.table.alias
        join_scope = _Scope()
        for alias in scope.order:
            join_scope.add(alias, scope.tables[alias])
        join_scope.add(right_alias, right)
        # split ON into cross-side equi-conditions (join keys) and residual filters
        equi: List[Any] = []
        residual: List[Any] = []
        for conj in _split_on_condition(join.on):
            bound = None
            if isinstance(conj, Binary) and conj.op == "=":
                left_e = _bind(conj.left, join_scope)
                right_e = _bind(conj.right, join_scope)
                tabs_l = {id(r.table) for r in left_e._column_refs}
                tabs_r = {id(r.table) for r in right_e._column_refs}
                right_id = id(right)
                # a join key needs one side referencing ONLY the joined table and
                # the other referencing ONLY earlier tables; anything mixed is a
                # residual predicate
                l_only_right = tabs_l == {right_id}
                r_only_right = tabs_r == {right_id}
                l_no_right = bool(tabs_l) and right_id not in tabs_l
                r_no_right = bool(tabs_r) and right_id not in tabs_r
                if (l_only_right and r_no_right) or (r_only_right and l_no_right):
                    bound = expr.ColumnBinaryOpExpression(
                        __import__("operator").eq, left_e, right_e
                    )
            if bound is not None:
                equi.append(bound)
            else:
                residual.append(conj)
        if not equi:
            raise ValueError(
                "JOIN ... ON needs at least one cross-table equality condition"
            )
        if residual and join.kind != "inner":
            raise NotImplementedError(
                "non-equality ON conditions are only supported for INNER JOIN"
            )
        from pathway_tpu.internals.joins import JoinKind

        kinds = {
            "inner": JoinKind.INNER, "left": JoinKind.LEFT,
            "right": JoinKind.RIGHT, "outer": JoinKind.OUTER,
        }
        jr = result.join(right, *equi, how=kinds[join.kind])
        # flatten: the joined table carries every visible column
        flat_scope = join_scope
        exprs, rename = _flatten_join(flat_scope)
        joined = jr.select(**exprs)
        if residual:
            res_scope = _AliasedScope(joined, rename, flat_scope)
            cond = None
            for conj in residual:
                bound = _bind(conj, res_scope)
                cond = bound if cond is None else expr.ColumnBinaryOpExpression(
                    __import__("operator").and_, cond, bound
                )
            joined = joined.filter(cond)
        # the new scope: every original alias maps onto the flattened table through
        # per-alias column views
        new_scope = _Scope()
        new_scope.order = list(flat_scope.order)
        new_scope.tables = {
            alias: _AliasView(joined, {
                col: rename[f"{alias}.{col}"]
                for col in flat_scope.tables[alias].column_names()
            })
            for alias in flat_scope.order
        }
        scope = new_scope
        result = joined

    # WHERE
    if sel.where is not None:
        cond = _bind(sel.where, scope)
        filtered = result.filter(cond)
        scope = _rebased_scope(scope, result, filtered)
        result = filtered

    # SELECT list
    exprs: Dict[str, Any] = {}
    idx = 0
    for item in sel.items:
        if isinstance(item, Star):
            for name, e in scope.all_columns(item.qualifier):
                out_name = name
                while out_name in exprs:
                    out_name = out_name + "_"
                exprs[out_name] = e
            continue
        e = _bind(item.expression, scope)
        alias = item.alias
        if alias is None:
            if isinstance(item.expression, Ident):
                alias = item.expression.name
            else:
                alias = f"col_{idx}"
        exprs[alias] = e
        idx += 1

    group_exprs = [_bind(g, scope) for g in sel.group_by]
    having_e = _bind(sel.having, scope) if sel.having is not None else None

    has_aggs = any(_contains_reducer(e) for e in exprs.values()) or (
        having_e is not None and _contains_reducer(having_e)
    )
    if group_exprs or has_aggs:
        grouped = result.groupby(*group_exprs) if group_exprs else result.groupby()
        if having_e is not None:
            exprs["_pw_having"] = having_e
        out = grouped.reduce(**exprs)
        if having_e is not None:
            out = out.filter(out._pw_having).without("_pw_having")
    elif having_e is not None:
        raise ValueError("HAVING without aggregation; use WHERE")
    else:
        out = result.select(**exprs)

    if sel.distinct:
        out = _distinct(out)
    return out


class _AliasView:
    """A per-alias column view over a flattened join table (quacks like Table for
    scope resolution)."""

    def __init__(self, table: Table, mapping: Dict[str, str]):
        self._table = table
        self._mapping = mapping

    def column_names(self) -> List[str]:
        return list(self._mapping)

    def __getitem__(self, name: str) -> Any:
        return self._table[self._mapping[name]]


class _AliasedScope(_Scope):
    """Resolution over a flattened join for residual ON predicates."""

    def __init__(self, joined: Table, rename: Dict[str, str], base_scope: _Scope):
        super().__init__()
        for alias in base_scope.order:
            self.add(
                alias,
                _AliasView(joined, {
                    col: rename[f"{alias}.{col}"]
                    for col in base_scope.tables[alias].column_names()
                }),
            )


def _rebased_scope(scope: _Scope, old: Table, new: Table) -> _Scope:
    out = _Scope()
    out.order = list(scope.order)
    for alias in scope.order:
        t = scope.tables[alias]
        if isinstance(t, _AliasView):
            out.tables[alias] = _AliasView(
                new if t._table is old else t._table, t._mapping
            )
        else:
            out.tables[alias] = new if t is old else t
    return out


def _distinct(table: Table) -> Table:
    cols = [table[c] for c in table.column_names()]
    return table.groupby(*cols).reduce(
        **{c: table[c] for c in table.column_names()}
    )


def _plan_query(query: Query, tables: Dict[str, Table]) -> Table:
    parts = [_plan_select(s, tables) for s in query.selects]
    out = parts[0]
    for i, part in enumerate(parts[1:]):
        if len(part.column_names()) != len(out.column_names()):
            raise ValueError(
                "UNION requires the same number of columns "
                f"({len(out.column_names())} vs {len(part.column_names())})"
            )
        if out.column_names() != part.column_names():
            # UNION aligns by position (SQL semantics)
            mapping = dict(zip(part.column_names(), out.column_names()))
            part = part.select(**{mapping[c]: part[c] for c in part.column_names()})
        out = out.concat_reindex(part)
        if not query.union_all[i]:
            out = _distinct(out)
    return out


def sql(query: str, **tables: Table) -> Table:
    """Run a SQL query over the given tables (reference ``pw.sql``): SELECT
    [DISTINCT], table aliases, subqueries in FROM, INNER/LEFT/RIGHT/FULL JOIN ... ON,
    WHERE, GROUP BY, HAVING, UNION [ALL], and COUNT/SUM/MIN/MAX/AVG."""
    ast = _Parser(query).parse_query()
    return _plan_query(ast, tables)


def _contains_reducer(e: Any) -> bool:
    if isinstance(e, expr.ReducerExpression):
        return True
    if isinstance(e, expr.ColumnExpression):
        return any(_contains_reducer(d) for d in e._deps())
    return False
