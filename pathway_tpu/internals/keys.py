"""128-bit row keys.

Parity with the reference's ``src/engine/value.rs:41`` (``Key`` = 128-bit fingerprint via xxh3)
and ``src/engine/dataflow/shard.rs`` (shard = low bits of the key). Keys are represented
columnar-first: a batch of keys is a structured numpy array with ``hi``/``lo`` uint64 fields so
sort/unique/equality are vectorized; a scalar key is a ``Pointer`` (the user-visible value, as in
``pw.Pointer``).
"""

from __future__ import annotations

from typing import Any, Iterable, Sequence

import numpy as np
import xxhash

KEY_DTYPE = np.dtype([("hi", "<u8"), ("lo", "<u8")])

_SALT = b"pathway-tpu-v1"


class Pointer:
    """User-visible 128-bit row reference (reference ``api.Pointer``)."""

    __slots__ = ("hi", "lo")

    def __init__(self, hi: int, lo: int):
        object.__setattr__(self, "hi", int(hi) & 0xFFFFFFFFFFFFFFFF)
        object.__setattr__(self, "lo", int(lo) & 0xFFFFFFFFFFFFFFFF)

    def __setattr__(self, *a: Any) -> None:
        raise AttributeError("Pointer is immutable")

    def __reduce__(self):
        # slots + frozen breaks pickle's default (it loads via __setattr__);
        # pointers cross process boundaries in cluster exchanges and journals
        return (Pointer, (self.hi, self.lo))

    def as_int(self) -> int:
        return (self.hi << 64) | self.lo

    def __repr__(self) -> str:
        return f"^{self.as_int():032X}"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Pointer) and other.hi == self.hi and other.lo == self.lo

    def __lt__(self, other: "Pointer") -> bool:
        return (self.hi, self.lo) < (other.hi, other.lo)

    def __le__(self, other: "Pointer") -> bool:
        return (self.hi, self.lo) <= (other.hi, other.lo)

    def __gt__(self, other: "Pointer") -> bool:
        return (self.hi, self.lo) > (other.hi, other.lo)

    def __ge__(self, other: "Pointer") -> bool:
        return (self.hi, self.lo) >= (other.hi, other.lo)

    def __hash__(self) -> int:
        return hash((self.hi, self.lo))


def _fingerprint_bytes(data: bytes) -> tuple[int, int]:
    digest = xxhash.xxh3_128_digest(data)
    return int.from_bytes(digest[:8], "little"), int.from_bytes(digest[8:], "little")


def _serialize_value(value: Any, out: list[bytes]) -> None:
    """Deterministic serialization of an engine value for fingerprinting."""
    if value is None:
        out.append(b"\x00")
    elif isinstance(value, Pointer):
        out.append(b"\x01" + value.hi.to_bytes(8, "little") + value.lo.to_bytes(8, "little"))
    elif isinstance(value, (bool, np.bool_)):
        out.append(b"\x02\x01" if value else b"\x02\x00")
    elif isinstance(value, (int, np.integer)):
        out.append(b"\x03" + int(value).to_bytes(16, "little", signed=True))
    elif isinstance(value, (float, np.floating)):
        out.append(b"\x04" + np.float64(value).tobytes())
    elif isinstance(value, str):
        encoded = value.encode()
        out.append(b"\x05" + len(encoded).to_bytes(8, "little") + encoded)
    elif isinstance(value, bytes):
        out.append(b"\x06" + len(value).to_bytes(8, "little") + value)
    elif isinstance(value, (tuple, list)):
        out.append(b"\x07" + len(value).to_bytes(8, "little"))
        for item in value:
            _serialize_value(item, out)
    elif isinstance(value, np.void) and value.dtype == KEY_DTYPE:
        # a raw KEY_DTYPE cell serializes exactly like the Pointer it denotes
        out.append(
            b"\x01"
            + int(value["hi"]).to_bytes(8, "little")
            + int(value["lo"]).to_bytes(8, "little")
        )
    elif isinstance(value, np.ndarray):
        out.append(b"\x08" + str(value.dtype).encode() + str(value.shape).encode() + value.tobytes())
    else:
        from pathway_tpu.internals.json import Json

        if isinstance(value, Json):
            encoded = value.dumps().encode()
            out.append(b"\x09" + len(encoded).to_bytes(8, "little") + encoded)
        elif isinstance(value, dict):
            # order-insensitive: equal dicts built in different insertion orders
            # must fingerprint identically (consolidation relies on it)
            items = sorted(
                ((repr(k), k, v) for k, v in value.items()), key=lambda kv: kv[0]
            )
            out.append(b"\x0b" + len(items).to_bytes(8, "little"))
            for _, k, v in items:
                _serialize_value(k, out)
                _serialize_value(v, out)
        elif isinstance(value, (set, frozenset)):
            parts: list[list[bytes]] = []
            for item in value:
                chunk: list[bytes] = []
                _serialize_value(item, chunk)
                parts.append(chunk)
            out.append(b"\x0c" + len(parts).to_bytes(8, "little"))
            for chunk in sorted(parts, key=b"".join):
                out.extend(chunk)
        else:
            encoded = repr(value).encode()
            out.append(b"\x0a" + len(encoded).to_bytes(8, "little") + encoded)


# -- single-int identity-mix keys --------------------------------------------
# A row whose key derives from EXACTLY ONE int value uses a splitmix-style
# 128-bit mix instead of salted xxh3 over its serialization (reference key
# derivation from Value, ``value.rs`` — the single-int join/groupby key is the
# hottest derivation; the mix keeps full 64->128 avalanche at ~10x less cost).
# ``csrc/pathway_native.cc::pw_intkey_mix64`` is the exact native twin — every
# derivation site must produce identical bits for equal values. Changing this
# function invalidates persisted journals (keys are stored in frames) — bump
# KEY_DERIVATION_VERSION so the persistence layer refuses to resume them.

# v1: salted xxh3 for every value kind. v2: splitmix identity mix for single-int
# keys. Recorded in every journal/checkpoint header; persistence/engine.py
# refuses to resume stores written under a different version.
KEY_DERIVATION_VERSION = 2
_INTKEY_LO = 0x9E3779B97F4A7C15
_INTKEY_HI = 0xD6E8FEB86659FD93
_MIX_M1 = 0xBF58476D1CE4E5B9
_MIX_M2 = 0x94D049BB133111EB
_U64 = (1 << 64) - 1
_INT64_MIN, _INT64_MAX = -(1 << 63), (1 << 63) - 1


def _mix64(x: int) -> int:
    x ^= x >> 30
    x = (x * _MIX_M1) & _U64
    x ^= x >> 27
    x = (x * _MIX_M2) & _U64
    x ^= x >> 31
    return x


def _is_plain_int(value: Any) -> bool:
    return (
        isinstance(value, (int, np.integer))
        and not isinstance(value, (bool, np.bool_))
        and _INT64_MIN <= int(value) <= _INT64_MAX
    )


def _int_key(value: int) -> tuple[int, int]:
    u = value & _U64
    return _mix64(u ^ _INTKEY_HI), _mix64((u + _INTKEY_LO) & _U64)


def _int_keys_array(col: np.ndarray) -> np.ndarray:
    """Vectorized mix for an int64 column — bit-identical to the scalar/native."""
    u = np.ascontiguousarray(col, dtype=np.int64).view(np.uint64)
    out = np.empty(len(col), dtype=KEY_DTYPE)
    def mix(x: np.ndarray) -> np.ndarray:
        x = x ^ (x >> np.uint64(30))
        x = x * np.uint64(_MIX_M1)
        x = x ^ (x >> np.uint64(27))
        x = x * np.uint64(_MIX_M2)
        x = x ^ (x >> np.uint64(31))
        return x
    out["hi"] = mix(u ^ np.uint64(_INTKEY_HI))
    out["lo"] = mix(u + np.uint64(_INTKEY_LO))
    return out


def pointer_from(*parts: Any) -> Pointer:
    """Fingerprint values into a key (reference ``Key::for_values``, ``value.rs:73``)."""
    if len(parts) == 1 and _is_plain_int(parts[0]):
        return Pointer(*_int_key(int(parts[0])))
    chunks: list[bytes] = [_SALT]
    for part in parts:
        _serialize_value(part, chunks)
    hi, lo = _fingerprint_bytes(b"".join(chunks))
    return Pointer(hi, lo)


def _classify_column(col: np.ndarray):
    """Describe a column for the native hasher; None for unsupported array dtypes.

    Returns (kind, data_array) with the array kept alive by the caller. Kinds mirror
    ``csrc/pathway_native.cc``: 1=int64 2=float64 3=bool 5=pyobject 6=key128. Object
    columns go straight to the pyobject kind — type dispatch happens natively per value.
    """
    if col.dtype == KEY_DTYPE:
        return (6, np.ascontiguousarray(col))
    if col.dtype == object:
        return (5, np.ascontiguousarray(col))
    if col.dtype == np.bool_:
        return (3, np.ascontiguousarray(col, dtype=np.uint8))
    if np.issubdtype(col.dtype, np.integer):
        if col.dtype == np.uint64 and len(col) and col.max() > np.uint64(2**63 - 1):
            # int64 cast would wrap; the Python serializer encodes the true value
            return None
        return (1, np.ascontiguousarray(col, dtype=np.int64))
    if np.issubdtype(col.dtype, np.floating):
        # widening matches the Python serializer (_serialize_value casts to float64)
        return (2, np.ascontiguousarray(col, dtype=np.float64))
    return None


def _marshal_cols(
    columns: Sequence[np.ndarray],
    masks: Sequence[np.ndarray | None] | None,
) -> "tuple[Any, list] | None":
    """(PwCol array, keepalive list) for the native hashers; None when any
    column's dtype has no native kind. ONE home for the marshalling so the
    plain and fused hash paths can never diverge."""
    from pathway_tpu import native as _native

    descs = []
    for col in columns:
        desc = _classify_column(np.asarray(col))
        if desc is None:
            return None
        descs.append(desc)
    import ctypes

    keepalive: list = [data for _kind, data in descs]
    cols = (_native.PwCol * len(descs))()
    for i, (kind, data) in enumerate(descs):
        cols[i].kind = kind
        cols[i].data = data.ctypes.data_as(ctypes.c_void_p)
        cols[i].offsets = None
        mask = masks[i] if masks is not None else None
        if mask is None:
            cols[i].mask = None
        else:
            m = np.ascontiguousarray(mask, dtype=np.uint8)
            keepalive.append(m)
            cols[i].mask = m.ctypes.data_as(ctypes.c_void_p)
    return cols, keepalive


def _native_keys(
    columns: Sequence[np.ndarray],
    n: int,
    masks: Sequence[np.ndarray | None] | None = None,
) -> np.ndarray | None:
    from pathway_tpu import native as _native

    lib = _native.get_lib()
    if lib is None:
        return None
    marshalled = _marshal_cols(columns, masks)
    if marshalled is None:
        return None
    cols, _keepalive = marshalled
    import ctypes

    hi = np.empty(n, dtype=np.uint64)
    lo = np.empty(n, dtype=np.uint64)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    status = lib.pwtpu_hash_typed(
        ctypes.cast(cols, ctypes.c_void_p),
        len(columns),
        n,
        _SALT,
        len(_SALT),
        np.bool_,
        np.integer,
        hi.ctypes.data_as(u64p),
        lo.ctypes.data_as(u64p),
    )
    if status != -1:
        return None  # unsupported value encountered: Python path handles the batch
    out = np.empty(n, dtype=KEY_DTYPE)
    out["hi"], out["lo"] = hi, lo
    return out


def _python_keys(
    columns: Sequence[np.ndarray],
    n: int,
    masks: Sequence[np.ndarray | None] | None = None,
) -> np.ndarray:
    """Reference Python serializer path (the native hashers are byte-identical)."""
    out = np.empty(n, dtype=KEY_DTYPE)
    single = len(columns) == 1
    mask0 = masks[0] if (single and masks is not None) else None
    for i in range(n):
        if single and (mask0 is None or mask0[i]):
            v = columns[0][i]
            if _is_plain_int(v):
                out["hi"][i], out["lo"][i] = _int_key(int(v))
                continue
        chunks: list[bytes] = [_SALT]
        for j, col in enumerate(columns):
            if masks is not None and masks[j] is not None and not masks[j][i]:
                chunks.append(b"\x00")
            else:
                _serialize_value(col[i], chunks)
        out["hi"][i], out["lo"][i] = _fingerprint_bytes(b"".join(chunks))
    return out


def keys_from_values(
    columns: Sequence[np.ndarray],
    masks: Sequence[np.ndarray | None] | None = None,
) -> np.ndarray:
    """Vectorized key derivation for a batch of rows, one key per row.

    ``masks[j]``, when given, marks present rows of column ``j`` (False serializes as
    None — used for outer-join null sides). Simple-typed batches route through the
    native hasher (``csrc/pathway_native.cc``, byte-identical serialization); anything
    else falls back to the Python serializer.
    """
    n = len(columns[0]) if columns else 0
    if (
        len(columns) == 1
        and columns[0].dtype == np.int64
        and (masks is None or masks[0] is None)
    ):
        # single-int64 column: the vectorized mix beats even the native hasher
        return _int_keys_array(columns[0])
    if n >= 64:
        native_out = _native_keys(columns, n, masks)
        if native_out is not None:
            return native_out
    return _python_keys(columns, n, masks)


def hash_upsert(
    index: Any,
    columns: Sequence[np.ndarray],
    masks: Sequence[np.ndarray | None] | None = None,
) -> "tuple[np.ndarray, np.ndarray, np.ndarray]":
    """Fused ``keys_from_values`` + ``KeyIndex.upsert`` (the groupby hot pair):
    one native pass, one Python↔C crossing. Returns (keys, slots, is_new);
    falls back for unsupported cell types or when either the native lib or a
    native index is unavailable — and a native-hash failure goes STRAIGHT to the
    Python serializer (the native attempt is already known to fail; no retry)."""
    from pathway_tpu import native as _native
    from pathway_tpu.engine.index import _NativeKeyIndex

    lib = _native.get_lib()
    n = len(columns[0]) if columns else 0
    fused = getattr(lib, "pwtpu_hash_upsert", None) if lib is not None else None
    if fused is not None and isinstance(index, _NativeKeyIndex) and n >= 64:
        marshalled = _marshal_cols(columns, masks)
        if marshalled is not None:
            import ctypes

            cols, _keepalive = marshalled
            hi = np.empty(n, dtype=np.uint64)
            lo = np.empty(n, dtype=np.uint64)
            slots = np.empty(n, dtype=np.int64)
            is_new = np.empty(n, dtype=np.uint8)
            u64p = ctypes.POINTER(ctypes.c_uint64)
            status = fused(
                ctypes.cast(cols, ctypes.c_void_p),
                len(columns),
                n,
                _SALT,
                len(_SALT),
                np.bool_,
                np.integer,
                index._h,
                hi.ctypes.data_as(u64p),
                lo.ctypes.data_as(u64p),
                slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                is_new.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            )
            if status == -1:
                keys = np.empty(n, dtype=KEY_DTYPE)
                keys["hi"], keys["lo"] = hi, lo
                return keys, slots, is_new.astype(bool)
            # unsupported value mid-batch: the index is untouched (the native
            # function hashes fully before any upsert); don't re-try native
            keys = _python_keys(columns, n, masks)
            slots, is_new_b = index.upsert(keys)
            return keys, slots, is_new_b
    keys = keys_from_values(columns, masks)
    slots, is_new = index.upsert(keys)
    return keys, slots, is_new


def combine_keys(
    lkeys: np.ndarray,
    rkeys: np.ndarray,
    lmask: np.ndarray,
    rmask: np.ndarray,
    salt: int = 0x6A6F696E,  # "join"
) -> np.ndarray:
    """Derive output keys from two (maskable) key columns by arithmetic mixing.

    Join/concat output rows are identified by their constituent row keys; since those
    are already xxh3-128 fingerprints, a splitmix-style combine preserves uniformity
    without re-serializing and re-hashing row bytes (the reference hashes the pair
    through ``Key::for_values`` — same contract, cheaper mechanism). Null sides
    (``mask`` False) fold in distinct constants so (k, null) != (null, k).
    """
    from pathway_tpu import native as _native

    lib = _native.get_lib()
    if lib is not None and len(lkeys) >= 64:
        import ctypes

        n = len(lkeys)
        lk = np.ascontiguousarray(lkeys)
        rk = np.ascontiguousarray(rkeys)
        lm = np.ascontiguousarray(lmask, dtype=np.uint8)
        rm = np.ascontiguousarray(rmask, dtype=np.uint8)
        out = np.empty(n, dtype=KEY_DTYPE)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.pwtpu_combine_keys(
            lk.ctypes.data_as(u64p), rk.ctypes.data_as(u64p),
            lm.ctypes.data_as(u8p), rm.ctypes.data_as(u8p),
            n, salt, out.ctypes.data_as(u64p),
        )
        return out
    C1 = np.uint64(0x9E3779B97F4A7C15)
    C2 = np.uint64(0xC2B2AE3D27D4EB4F)
    C3 = np.uint64(0x165667B19E3779F9)
    z = np.uint64(0x27D4EB2F165667C5)
    with np.errstate(over="ignore"):
        lh = np.where(lmask, lkeys["hi"], np.uint64(0x6C6E756C6C))
        ll = np.where(lmask, lkeys["lo"], np.uint64(0x1B873593))
        rh = np.where(rmask, rkeys["hi"], np.uint64(0x726E756C6C))
        rl = np.where(rmask, rkeys["lo"], np.uint64(0x85EBCA77))
        s = np.uint64(salt)
        hi = (lh * C1) ^ (rh * C2) ^ ((rl >> np.uint64(31)) + s * C3)
        lo = (ll * C2) ^ (rl * C1) ^ ((lh << np.uint64(17)) | (lh >> np.uint64(47)))
        hi ^= hi >> np.uint64(29)
        hi *= z
        hi ^= hi >> np.uint64(32)
        lo ^= lo >> np.uint64(29)
        lo *= C3
        lo ^= lo >> np.uint64(32)
        # cross-fold so each output word depends on every input word
        lo ^= hi * C1
        lo ^= lo >> np.uint64(31)
    out = np.empty(len(lkeys), dtype=KEY_DTYPE)
    out["hi"], out["lo"] = hi, lo
    return out


def sequential_keys(start: int, count: int) -> np.ndarray:
    """Keys for autogenerated row ids (dense ints hashed for uniform sharding)."""
    out = np.empty(count, dtype=KEY_DTYPE)
    if count >= 64:
        from pathway_tpu import native as _native

        lib = _native.get_lib()
        if lib is not None:
            import ctypes

            hi = np.empty(count, dtype=np.uint64)
            lo = np.empty(count, dtype=np.uint64)
            u64p = ctypes.POINTER(ctypes.c_uint64)
            lib.pwtpu_sequential_keys(
                _SALT,
                len(_SALT),
                start,
                count,
                hi.ctypes.data_as(u64p),
                lo.ctypes.data_as(u64p),
            )
            out["hi"], out["lo"] = hi, lo
            return out
    for i in range(count):
        hi, lo = _fingerprint_bytes(_SALT + b"seq" + (start + i).to_bytes(16, "little", signed=True))
        out["hi"][i], out["lo"][i] = hi, lo
    return out


def keys_to_pointers(keys: np.ndarray) -> list[Pointer]:
    # .tolist() converts to python ints in one C pass (values already in range,
    # so Pointer's masking is a no-op)
    return [Pointer(h, l) for h, l in zip(keys["hi"].tolist(), keys["lo"].tolist())]


def pointers_to_keys(pointers: Iterable[Pointer]) -> np.ndarray:
    pointers = list(pointers)
    out = np.empty(len(pointers), dtype=KEY_DTYPE)
    for i, p in enumerate(pointers):
        out["hi"][i], out["lo"][i] = p.hi, p.lo
    return out


def broadcast_key(p: Pointer, n: int) -> np.ndarray:
    """A KEY_DTYPE column with every row set to ``p`` (constant-key buckets)."""
    out = np.empty(n, dtype=KEY_DTYPE)
    out["hi"], out["lo"] = p.hi, p.lo
    return out


def key_bytes(keys: np.ndarray) -> list[bytes]:
    """Per-row 16-byte representations, usable as dict keys (one C-level tobytes
    plus slicing, instead of a per-row ``np.void.tobytes`` call)."""
    blob = np.ascontiguousarray(keys).tobytes()
    return [blob[i : i + 16] for i in range(0, len(blob), 16)]


def shard_of(keys: np.ndarray, n_shards: int) -> np.ndarray:
    """Row -> shard routing: low bits of the key (reference ``shard.rs:15-20``)."""
    return (keys["lo"] % np.uint64(n_shards)).astype(np.int64)
