"""Native (C++) runtime kernels, loaded via ctypes.

The reference engine keeps its host-side hot loops native (Rust: ``src/engine/value.rs``
key fingerprinting, ``src/connectors/data_format.rs`` parsers). This package builds the
TPU-native counterparts from ``csrc/pathway_native.cc`` with g++ on first import (cached
as a shared object next to this file) and exposes them behind the same contracts as the
pure-Python fallbacks in ``internals/keys.py`` / ``io/fs.py``. When no toolchain is
available everything degrades to the Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "..", "..", "csrc", "pathway_native.cc")
_SO = os.path.join(_HERE, "_pathway_native.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _xxhash_include_dir() -> Optional[str]:
    """xxhash ships header-only inside pyarrow's vendored tree in this image."""
    try:
        import pyarrow

        cand = os.path.join(
            os.path.dirname(pyarrow.__file__), "include", "arrow", "vendored", "xxhash"
        )
        if os.path.exists(os.path.join(cand, "xxhash.h")):
            return cand
    except Exception:
        pass
    for cand in ("/usr/include", "/usr/local/include"):
        if os.path.exists(os.path.join(cand, "xxhash.h")):
            return cand
    return None


def _build(force: bool = False) -> Optional[str]:
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return None
    if not force and os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(src):
        return _SO
    include = _xxhash_include_dir()
    if include is None:
        return None
    import sysconfig

    py_include = sysconfig.get_paths()["include"]
    tmp = f"{_SO}.{os.getpid()}.tmp"  # per-pid: concurrent spawned processes may race
    cmd = [
        "g++",
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-march=native",
        f"-I{include}",
        f"-I{py_include}",
        src,
        "-o",
        tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return _SO
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None when unavailable."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("PATHWAY_TPU_DISABLE_NATIVE"):
        return None
    path = _build()
    if path is None:
        return None
    try:
        # PyDLL: calls keep the GIL — required for the pyobject column kind, which
        # walks PyObject* arrays with CPython C-API calls
        lib = ctypes.PyDLL(path)
    except OSError:
        return None
    if not hasattr(lib, "pwtpu_hash_upsert"):
        # stale prebuilt .so from older source (mtime comparisons can lie across
        # archive extraction / layer caching): force one rebuild — compiled to a
        # temp path and swapped in only on success, so a failed compile (e.g. no
        # toolchain on the deployment host) leaves the existing library intact.
        # The reload must use a FRESH path — glibc dedupes dlopen by pathname, so
        # reloading the replaced file at the same path returns the stale handle.
        path = _build(force=True)
        if path is None:
            return None
        import shutil

        fresh = f"{_SO}.reload.{os.getpid()}"
        try:
            shutil.copyfile(path, fresh)
            lib = ctypes.PyDLL(fresh)
        except OSError:
            return None
        finally:
            try:
                os.unlink(fresh)  # the mapping survives the unlink on Linux
            except OSError:
                pass
        if not hasattr(lib, "pwtpu_hash_upsert"):
            return None

    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.pwtpu_hash_typed.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_uint64,
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.py_object,
        ctypes.py_object,
        u64p,
        u64p,
    ]
    lib.pwtpu_hash_typed.restype = ctypes.c_int64
    lib.pwtpu_hash_upsert.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_uint64,
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.py_object,
        ctypes.py_object,
        ctypes.c_void_p,
        u64p,
        u64p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.pwtpu_hash_upsert.restype = ctypes.c_int64
    lib.pwtpu_hash_serialized.argtypes = [
        ctypes.c_char_p,
        u64p,
        ctypes.c_uint64,
        u64p,
        u64p,
    ]
    lib.pwtpu_hash_serialized.restype = None
    lib.pwtpu_sequential_keys.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_int64,
        ctypes.c_uint64,
        u64p,
        u64p,
    ]
    lib.pwtpu_sequential_keys.restype = None
    lib.pwtpu_split_dsv.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_char,
        ctypes.c_char_p,
        u64p,
        u64p,
        ctypes.POINTER(ctypes.c_uint8),
        u64p,
        u64p,
    ]
    lib.pwtpu_split_dsv.restype = ctypes.c_uint64
    lib.pwtpu_parse_dsv_rows.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_char,
        ctypes.py_object,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
        ctypes.py_object,
    ]
    lib.pwtpu_parse_dsv_rows.restype = ctypes.py_object
    i64p = ctypes.POINTER(ctypes.c_int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.pwtpu_combine_keys.argtypes = [
        u64p, u64p,
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int64, ctypes.c_uint64, u64p,
    ]
    lib.pwtpu_combine_keys.restype = None
    lib.pwtpu_idx_new.argtypes = [ctypes.c_uint64]
    lib.pwtpu_idx_new.restype = ctypes.c_void_p
    lib.pwtpu_idx_free.argtypes = [ctypes.c_void_p]
    lib.pwtpu_idx_free.restype = None
    lib.pwtpu_idx_len.argtypes = [ctypes.c_void_p]
    lib.pwtpu_idx_len.restype = ctypes.c_int64
    lib.pwtpu_idx_slot_bound.argtypes = [ctypes.c_void_p]
    lib.pwtpu_idx_slot_bound.restype = ctypes.c_int64
    lib.pwtpu_idx_upsert.argtypes = [ctypes.c_void_p, u64p, ctypes.c_int64, i64p, u8p]
    lib.pwtpu_idx_upsert.restype = None
    lib.pwtpu_idx_lookup.argtypes = [ctypes.c_void_p, u64p, ctypes.c_int64, i64p]
    lib.pwtpu_idx_lookup.restype = None
    lib.pwtpu_idx_remove.argtypes = [ctypes.c_void_p, u64p, ctypes.c_int64, i64p]
    lib.pwtpu_idx_remove.restype = None
    lib.pwtpu_idx_items.argtypes = [ctypes.c_void_p, u64p, i64p]
    lib.pwtpu_idx_items.restype = None
    lib.pwtpu_idx_restore.argtypes = [
        ctypes.c_void_p, u64p, i64p, ctypes.c_int64, ctypes.c_int64,
    ]
    lib.pwtpu_idx_restore.restype = None
    lib.pwtpu_mm_new.argtypes = []
    lib.pwtpu_mm_new.restype = ctypes.c_void_p
    lib.pwtpu_mm_free.argtypes = [ctypes.c_void_p]
    lib.pwtpu_mm_free.restype = None
    lib.pwtpu_mm_total.argtypes = [ctypes.c_void_p]
    lib.pwtpu_mm_total.restype = ctypes.c_int64
    lib.pwtpu_mm_insert.argtypes = [ctypes.c_void_p, u64p, i64p, ctypes.c_int64]
    lib.pwtpu_mm_insert.restype = None
    lib.pwtpu_mm_remove.argtypes = [ctypes.c_void_p, u64p, i64p, ctypes.c_int64, u8p]
    lib.pwtpu_mm_remove.restype = None
    lib.pwtpu_mm_count.argtypes = [ctypes.c_void_p, u64p, ctypes.c_int64, i64p]
    lib.pwtpu_mm_count.restype = ctypes.c_int64
    lib.pwtpu_mm_fill.argtypes = [ctypes.c_void_p, u64p, ctypes.c_int64, i64p]
    lib.pwtpu_mm_fill.restype = None
    lib.pwtpu_mm_items.argtypes = [ctypes.c_void_p, u64p, i64p]
    lib.pwtpu_mm_items.restype = None
    lib.pwtpu_side_insert.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, u64p, u64p, ctypes.c_int64,
        u64p, u64p, i64p,
    ]
    lib.pwtpu_side_insert.restype = None
    lib.pwtpu_side_remove.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, u64p, ctypes.c_int64, u64p, i64p,
    ]
    lib.pwtpu_side_remove.restype = None
    _lib = lib
    return _lib


class PwCol(ctypes.Structure):
    _fields_ = [
        ("kind", ctypes.c_int32),
        ("data", ctypes.c_void_p),
        ("offsets", ctypes.c_void_p),
        ("mask", ctypes.c_void_p),
    ]


def split_dsv(data: bytes, delimiter: str = ",") -> "list[list[str]] | None":
    """Split DSV content into rows of string fields natively; None if unavailable.

    Handles double-quote quoting with "" escapes and CRLF, mirroring the reference's
    Dsv parser (src/connectors/data_format.rs:500).
    """
    lib = get_lib()
    if lib is None:
        return None
    import numpy as np

    n = len(data)
    needed_bytes = ctypes.c_uint64()
    needed_fields = ctypes.c_uint64()
    delim = delimiter.encode()[:1]
    nrows = lib.pwtpu_split_dsv(
        data, n, delim, None, None, None, None,
        ctypes.byref(needed_bytes), ctypes.byref(needed_fields),
    )
    if nrows == 0:
        return []
    field_buf = ctypes.create_string_buffer(max(needed_bytes.value, 1))
    offsets = np.zeros(needed_fields.value + 1, dtype=np.uint64)
    counts = np.zeros(nrows, dtype=np.uint64)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.pwtpu_split_dsv(
        data, n, delim, field_buf,
        offsets.ctypes.data_as(u64p), counts.ctypes.data_as(u64p),
        None, None, None,
    )
    raw = field_buf.raw
    rows: list[list[str]] = []
    f = 0
    for r in range(nrows):
        k = int(counts[r])
        row = [
            raw[int(offsets[f + j]) : int(offsets[f + j + 1])].decode("utf-8", "replace")
            for j in range(k)
        ]
        f += k
        rows.append(row)
    return rows


def parse_dsv_rows(
    data: bytes,
    selected: "list[tuple[str, int]]",
    delimiter: str,
    error_obj: object,
) -> "list[dict] | None":
    """Fused native DSV parse → list of row dicts; None when unavailable.

    ``selected``: (column_name, tag) pairs; tag 0=str 1=int 2=float 3=bool. Name→column
    resolution happens natively against the file's (properly split) header row; wanted
    columns absent from the header are omitted from the rows, like DictReader.
    Malformed typed fields yield ``error_obj``.
    """
    lib = get_lib()
    if lib is None or len(delimiter.encode()) != 1:
        return None
    tags = (ctypes.c_int32 * len(selected))(*[tag for _name, tag in selected])
    names = tuple(name for name, _tag in selected)
    return lib.pwtpu_parse_dsv_rows(
        data, len(data), delimiter.encode(), names, tags, len(selected), error_obj
    )
