"""Graph lint: build-time static analysis over the operator DAG.

Three surfaces share this one analyzer:

- ``pathway_tpu.cli analyze program.py`` — builds the program's graph without
  running it (``PATHWAY_LINT_CAPTURE``) and reports diagnostics, with
  ``--format json`` + the 0/1/2 exit-code contract for CI gating;
- an automatic check at graph-run time, gated by ``PATHWAY_LINT=off|warn|error``
  (default ``warn``; ``error`` refuses to run a graph carrying error-severity
  diagnostics);
- telemetry mirroring: diagnostic counts ride the PR-5 stage counters
  (``lint.*``) and a ``lint`` flight-recorder event, so post-mortems can say
  "this graph ran with 2 known lint errors".

Diagnostic codes: PWA001 determinism, PWA002 rewind-safety, PWA003 unbounded
state, PWA004 device placement, PWA005 checkpoint compatibility.

A second pass family (``analysis/concurrency.py``) lints the RUNTIME's own
threaded source instead of user graphs: PWA101 lock-order cycles, PWA102
unbounded waits, PWA103 unlocked shared writes, PWA104 thread-lifecycle
hygiene — surfaced as ``cli analyze --runtime`` (same exit-code contract) and
the ``PATHWAY_RUNTIME_LINT`` gate.

A third family (``analysis/resources.py``) proves resource lifecycles and
exception contracts over the same substrate: PWA201 acquire/release pairing,
PWA202 typed-error swallowing, PWA203 write-only state, PWA204 exception-
masking ``finally`` blocks, PWA205 telemetry-contract drift — folded into
``cli analyze --runtime`` alongside PWA10x and gated independently by
``PATHWAY_RESOURCE_LINT``.
"""

from __future__ import annotations

import os
import sys
from typing import Any, List, Optional, Tuple

from pathway_tpu.analysis.framework import (
    AnalysisContext,
    AnalysisPass,
    AnalysisReport,
    Diagnostic,
    GraphCaptureInterrupt,
    GraphLintError,
    PassManager,
    Severity,
)
from pathway_tpu.analysis.fusion import (
    ChainSpec,
    FusedRegion,
    FusionPlan,
    FusionPlanner,
    plan_fusion,
)
from pathway_tpu.analysis.concurrency import (
    LockOrderPass,
    RUNTIME_MODULES,
    ThreadLifecyclePass,
    UnboundedWaitPass,
    UnlockedSharedWritePass,
    analyze_runtime,
    analyze_source,
    default_concurrency_passes,
    runtime_gate,
)
from pathway_tpu.analysis.resources import (
    RESOURCE_MODULES,
    AcquireReleasePass,
    DeadStatePass,
    FinallyMaskPass,
    ResourceAnalysisContext,
    ResourcePass,
    TelemetryContractPass,
    TypedErrorSwallowPass,
    analyze_resource_source,
    analyze_resources,
    analyze_runtime_full,
    build_resource_context,
    default_resource_passes,
    resource_gate,
)
from pathway_tpu.analysis.passes import (
    CheckpointCompatibilityPass,
    DeterminismPass,
    DevicePlacementPass,
    RewindSafetyPass,
    UnboundedStatePass,
    default_passes,
)

__all__ = [
    "AnalysisContext",
    "AnalysisPass",
    "AnalysisReport",
    "Diagnostic",
    "GraphCaptureInterrupt",
    "GraphLintError",
    "PassManager",
    "Severity",
    "analyze_graph",
    "capture_program_graph",
    "default_passes",
    "ChainSpec",
    "FusedRegion",
    "FusionPlan",
    "FusionPlanner",
    "plan_fusion",
    "CheckpointCompatibilityPass",
    "DeterminismPass",
    "DevicePlacementPass",
    "RewindSafetyPass",
    "UnboundedStatePass",
    "LockOrderPass",
    "RUNTIME_MODULES",
    "ThreadLifecyclePass",
    "UnboundedWaitPass",
    "UnlockedSharedWritePass",
    "analyze_runtime",
    "analyze_source",
    "default_concurrency_passes",
    "runtime_gate",
    "RESOURCE_MODULES",
    "AcquireReleasePass",
    "DeadStatePass",
    "FinallyMaskPass",
    "ResourceAnalysisContext",
    "ResourcePass",
    "TelemetryContractPass",
    "TypedErrorSwallowPass",
    "analyze_resource_source",
    "analyze_resources",
    "analyze_runtime_full",
    "build_resource_context",
    "default_resource_passes",
    "resource_gate",
]

_CAPTURE_ENV = "PATHWAY_LINT_CAPTURE"


def analyze_graph(
    graph: Any = None,
    *,
    persistence: bool = False,
    passes: "Optional[List[AnalysisPass]]" = None,
    ctx: "Optional[AnalysisContext]" = None,
) -> AnalysisReport:
    """Run the lint pipeline over ``graph`` (default: the global parse graph).
    ``ctx`` lets callers that already hold an :class:`AnalysisContext` (the
    GraphRunner shares one with the fusion planner) skip a second DAG walk."""
    return PassManager(passes).run(graph, persistence=persistence, ctx=ctx)


def capture_program_graph(
    program: str, arguments: "Tuple[str, ...]" = ()
) -> Tuple[Any, bool]:
    """Execute ``program`` up to its first ``pw.run`` and return
    ``(parse graph, persistence enabled)`` without running the dataflow.

    ``PATHWAY_LINT_CAPTURE`` makes ``GraphRunner.run`` raise
    :class:`GraphCaptureInterrupt` before any commit; code after the first
    ``pw.run`` (result assertions, cleanup) does not execute. A program that
    never calls ``pw.run`` still leaves its operators in the global graph."""
    import runpy

    from pathway_tpu.internals import parse_graph as pg

    prev_env = os.environ.get(_CAPTURE_ENV)
    prev_argv = sys.argv
    os.environ[_CAPTURE_ENV] = "1"
    sys.argv = [program, *arguments]
    try:
        runpy.run_path(program, run_name="__main__")
    except GraphCaptureInterrupt as interrupt:
        return interrupt.graph, interrupt.persistence
    finally:
        sys.argv = prev_argv
        if prev_env is None:
            os.environ.pop(_CAPTURE_ENV, None)
        else:
            os.environ[_CAPTURE_ENV] = prev_env
    return pg.G._current, False
