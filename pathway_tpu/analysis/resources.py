"""Resource-lifecycle & exception-contract lint over the runtime (PWA201–205).

The reference engine leans on Rust ownership and typed-error discipline to stay
leak-free under failure; this Python runtime reproduces that discipline only by
convention — and the review-hardening history shows the recurring bug class: a
cancelled REST client permanently leaking its admission slot (PR 6), parked
leaver continuations that were write-only state (PR 11), broad ``except``
blocks one refactor away from swallowing ``PeerShutdownError`` and wedging the
fence ladder. These passes mechanize that audit over the same parsed-module
substrate the concurrency lint (PWA101–104) built:

- **PWA201 — acquire/release pairing.** Registered resource acquisitions
  (socket/file/tempfile/process constructors, admission-slot container stores)
  must have their release dominate every exit: a ``with``, a ``finally``, a
  provably-exception-free tail, or an ownership transfer (returned, stored on
  ``self``/a container, passed onward). Class-attribute resources are checked
  interprocedurally: SOME method of the class (a teardown helper called from a
  ``finally`` qualifies) must release the attribute. Error.
- **PWA202 — typed-error swallowing.** A ``try`` whose body can raise a typed
  protocol error (``PeerShutdownError``/``PeerTimeoutError``/
  ``ClusterFenceError``/``MembershipMismatchError``/``AutoscaleRefusedError``/
  ``EmbedOverloadError``…, discovered from the analyzed modules; raise sets
  propagate interprocedurally through resolvable calls) guarded by a bare or
  ``except Exception`` handler that neither re-raises nor isinstance-triages
  swallows the failure model's control flow. Any non-re-raising
  ``except BaseException`` is flagged unconditionally — it can eat
  ``GraphCaptureInterrupt`` (and ``KeyboardInterrupt``). Error.
- **PWA203 — write-only / dead attribute state.** An attribute of a runtime
  class that is written outside constructor-only code but never read anywhere
  (any analyzed module, plus the tests/bench read index in tree mode) is the
  parked-continuation bug class: state that silently stops meaning anything.
  Constructor-reachability and the ``# noqa: PWA2xx (<why>)`` escape reuse the
  PWA103 machinery. Warning.
- **PWA204 — exception-masking cleanup.** A ``raise``, ``return``/``break``/
  ``continue``, or an unguarded call that can raise a typed error inside a
  ``finally`` block replaces the in-flight (typed) exception with a generic
  one — recovery then routes on the wrong type. Error.
- **PWA205 — telemetry-contract drift.** Every ``stage_add``/``stage_timer``/
  ``stage_add_many``/``record_event`` string literal must parse against the
  registered namespace prefixes (``engine/telemetry.py:STAGE_NAMESPACES``) and
  flight-event kinds (``FLIGHT_EVENT_KINDS``), so counters cannot silently
  fork from ``/metrics`` dashboards. Error.

Surfaces mirror PWA10x exactly: folded into ``cli analyze --runtime`` (same
0/1/2 exit-code contract and JSON format, per-pass ``checked`` flags), a
``PATHWAY_RESOURCE_LINT=off|warn|error`` gate on ``pw.run`` (default ``off`` —
CI carries the clean-tree gate), ``lint.diag.PWA20x`` stage counters + the
``lint`` flight event, and ``# noqa: PWA20x (<reason>)`` suppression through
the shared noqa machinery.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from pathway_tpu.analysis.concurrency import (
    _REPO_ROOT,
    RUNTIME_MODULES,
    ConcurrencyPass,
    _ModuleInfo,
    _ModuleParser,
    _load_modules,
    _self_attr,
)
from pathway_tpu.analysis.framework import (
    AnalysisReport,
    Diagnostic,
    Severity,
)

#: the modules the resource/exception passes police: the threaded runtime set
#: plus the engine commit loop, persistence, the REST plane, and chaos — the
#: layers that hold slots, sockets, file handles, and typed-error contracts.
RESOURCE_MODULES: Tuple[str, ...] = RUNTIME_MODULES + (
    "pathway_tpu/engine/runner.py",
    "pathway_tpu/engine/profile.py",
    "pathway_tpu/engine/fusion.py",
    "pathway_tpu/persistence/engine.py",
    "pathway_tpu/persistence/backends.py",
    "pathway_tpu/persistence/replica_feed.py",
    "pathway_tpu/io/http/_server.py",
    "pathway_tpu/internals/chaos.py",
)

#: files scanned (regex, not AST) for attribute reads in tree mode: an attr
#: consumed only by tests/bench/examples is observability state, not dead
_EXTERNAL_READ_GLOBS: Tuple[str, ...] = ("tests", "examples", "bench.py")

# -- PWA201 resource registry -------------------------------------------------

#: terminal constructor name -> (resource kind, release-method names). The
#: Attribute form (``socket.socket``/``tempfile.NamedTemporaryFile``) only
#: matches when the receiver is an imported-module alias, so a method merely
#: NAMED ``open`` on some object never reads as a file constructor.
_RESOURCE_CTORS: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    "socket": ("socket", ("close", "detach")),
    "create_connection": ("socket", ("close", "detach")),
    "socketpair": ("socket", ("close", "detach")),
    "open": ("file", ("close",)),
    "fdopen": ("file", ("close",)),
    "NamedTemporaryFile": ("file", ("close",)),
    "TemporaryFile": ("file", ("close",)),
    "TemporaryDirectory": ("tempdir", ("cleanup",)),
    "Popen": ("process", ("wait", "communicate", "kill", "terminate")),
}

#: ``self.<attr>[key] = value`` admission-slot containers: a function that both
#: stores AND pops a slot must pop on the ``finally`` path (the PR-6 cancelled-
#: client wedge). Release method names that undo a slot store.
_SLOT_CONTAINERS: Set[str] = {"futures"}
_SLOT_RELEASES: Set[str] = {"pop", "discard", "remove"}

#: mutator methods whose receiver is a WRITE, not a read, for PWA203: only the
#: grow-a-collection family — ``.add(1)`` on an OTel counter or ``.pop()`` on
#: a queue consumes the object, a bare ``.append`` into a never-read list does
#: not (the parked-continuation shape)
_WRITE_ONLY_MUTATORS: Set[str] = {
    "append", "extend", "insert", "appendleft", "extendleft", "setdefault",
}

#: typed protocol errors every tree carries even when the defining module is
#: not in the analyzed set (framework.py defines the capture interrupt)
_SEED_TYPED_ERRORS: Dict[str, Tuple[str, ...]] = {
    "GraphCaptureInterrupt": ("BaseException",),
    "GraphLintError": ("Exception",),
}

_BROAD = {"Exception"}
_BROADEST = {"BaseException"}

#: builtin exception hierarchy the name-level subclass test walks through
#: (typed errors derive from these; ast gives us names, not classes)
_BUILTIN_BASES: Dict[str, Tuple[str, ...]] = {
    "Exception": ("BaseException",),
    "ArithmeticError": ("Exception",),
    "AssertionError": ("Exception",),
    "AttributeError": ("Exception",),
    "LookupError": ("Exception",),
    "KeyError": ("LookupError",),
    "IndexError": ("LookupError",),
    "OSError": ("Exception",),
    "IOError": ("OSError",),
    "ConnectionError": ("OSError",),
    "TimeoutError": ("OSError",),
    "RuntimeError": ("Exception",),
    "NotImplementedError": ("RuntimeError",),
    "TypeError": ("Exception",),
    "ValueError": ("Exception",),
    "StopIteration": ("Exception",),
    "SystemExit": ("BaseException",),
    "KeyboardInterrupt": ("BaseException",),
}


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _exc_names(node: "ast.expr | None") -> List[str]:
    """The exception class names an ``except <type>`` clause matches."""
    if node is None:
        return []
    if isinstance(node, ast.Tuple):
        out: List[str] = []
        for el in node.elts:
            out.extend(_exc_names(el))
        return out
    name = _terminal_name(node)
    return [name] if name else []


def _walk_skip_nested(node: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` that does not descend into nested function/class defs —
    their statements execute on a different activation (or not at all)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        sub = stack.pop()
        yield sub
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(sub))


def _cannot_raise(stmt: ast.stmt) -> bool:
    """True only for statements that provably cannot raise: simple assignments
    of names/constants (the "exception-free tail" a release may ride)."""
    if isinstance(stmt, ast.Pass):
        return True
    if isinstance(stmt, (ast.Assign, ast.AugAssign)):
        value = stmt.value
        simple = (ast.Name, ast.Constant)
        if isinstance(value, ast.Tuple):
            ok = all(isinstance(el, simple) for el in value.elts)
        else:
            ok = isinstance(value, simple)
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        return ok and all(isinstance(t, ast.Name) for t in targets)
    return False


# ---------------------------------------------------------------------------
# context
# ---------------------------------------------------------------------------


class _FuncRef:
    """One function/method with its AST node and resolution coordinates."""

    __slots__ = ("module", "cls", "name", "node")

    def __init__(self, module: _ModuleInfo, cls: Optional[str], name: str, node: ast.AST):
        self.module = module
        self.cls = cls
        self.name = name
        self.node = node

    @property
    def qual(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


class ResourceAnalysisContext:
    """Parsed view of the resource modules shared by all five passes: function
    AST index, typed-error hierarchy, interprocedural raise closures, and the
    external attribute-read index (tree mode)."""

    def __init__(self, modules: List[_ModuleInfo], *, external_reads: "Optional[Set[str]]" = None):
        self.modules = modules
        self.funcs: List[_FuncRef] = []
        self.class_defs: Dict[str, Tuple[_ModuleInfo, ast.ClassDef]] = {}
        self.class_methods: Dict[str, Dict[str, _FuncRef]] = {}
        self.module_funcs: Dict[Tuple[str, str], _FuncRef] = {}
        self.method_index: Dict[str, List[_FuncRef]] = {}
        for mod in modules:
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    self.class_defs.setdefault(node.name, (mod, node))
                    methods = self.class_methods.setdefault(node.name, {})
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            ref = _FuncRef(mod, node.name, item.name, item)
                            methods[item.name] = ref
                            self.funcs.append(ref)
                            self.method_index.setdefault(item.name, []).append(ref)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ref = _FuncRef(mod, None, node.name, node)
                    self.module_funcs[(mod.short, node.name)] = ref
                    self.funcs.append(ref)
        # nested defs (closures, thread bodies, async handlers) are analyzed as
        # their own functions — the REST handler's slot store and the acceptor
        # thread's except live in closures, not methods
        for ref in list(self.funcs):
            seen_nodes: Set[int] = {id(ref.node)}
            for sub in ast.walk(ref.node):
                if (
                    isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and id(sub) not in seen_nodes
                ):
                    seen_nodes.add(id(sub))
                    self.funcs.append(
                        _FuncRef(
                            ref.module, ref.cls,
                            f"{ref.name}.<locals>.{sub.name}", sub,
                        )
                    )
        # typed-error hierarchy: ClassDef names ending in Error/Interrupt whose
        # bases chain to builtin exceptions or other typed errors
        self.error_bases: Dict[str, Tuple[str, ...]] = {
            **_BUILTIN_BASES,
            **_SEED_TYPED_ERRORS,
        }
        self.typed_errors: Set[str] = set(_SEED_TYPED_ERRORS)
        changed = True
        while changed:
            changed = False
            for name, (mod, node) in self.class_defs.items():
                if name in self.typed_errors:
                    continue
                if not (name.endswith("Error") or name.endswith("Interrupt")):
                    continue
                bases = tuple(b for b in (_terminal_name(x) for x in node.bases) if b)
                if any(b in self.error_bases or b.endswith("Error") for b in bases):
                    self.error_bases[name] = bases
                    self.typed_errors.add(name)
                    changed = True
        self.external_reads: Set[str] = external_reads if external_reads is not None else set()
        self._raise_cache: Dict[Tuple[str, str, str], Set[str]] = {}

    # -- resolution ----------------------------------------------------------

    def resolve_method(self, cls_name: str, method: str) -> Optional[_FuncRef]:
        seen: Set[str] = set()
        stack = [cls_name]
        while stack:
            name = stack.pop()
            if name in seen:
                continue
            seen.add(name)
            got = self.class_methods.get(name, {}).get(method)
            if got is not None:
                return got
            entry = self.class_defs.get(name)
            if entry is not None:
                stack.extend(
                    b for b in (_terminal_name(x) for x in entry[1].bases) if b
                )
        return None

    def resolve_call(self, call: ast.Call, mod: _ModuleInfo, cls: Optional[str]) -> Optional[_FuncRef]:
        """Resolve a call to an analyzed function: local/imported functions,
        ``self.m()`` methods (through analyzed bases), ``module.f()`` through
        import aliases, and — for ``other.m()`` receivers — the terminal-
        attribute heuristic when exactly one analyzed class defines ``m``."""
        fn = call.func
        if isinstance(fn, ast.Name):
            imported = mod.import_funcs.get(fn.id)
            if imported is not None:
                return self.module_funcs.get(imported)
            return self.module_funcs.get((mod.short, fn.id))
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            if isinstance(recv, ast.Name):
                if recv.id in ("self", "cls") and cls is not None:
                    return self.resolve_method(cls, fn.attr)
                target_mod = mod.import_modules.get(recv.id)
                if target_mod is not None:
                    return self.module_funcs.get((target_mod, fn.attr))
            cands = self.method_index.get(fn.attr, [])
            if len(cands) == 1:
                return cands[0]
        return None

    # -- interprocedural raise closure ---------------------------------------

    def raise_closure(self, ref: _FuncRef, _depth: int = 0) -> Set[str]:
        """Typed-error names ``ref`` may raise, directly or through resolvable
        calls (depth-bounded, cycle-guarded)."""
        key = (ref.module.short, ref.cls or "", ref.name)
        got = self._raise_cache.get(key)
        if got is not None:
            return got
        self._raise_cache[key] = set()  # cycle guard
        out: Set[str] = set()
        for sub in _walk_skip_nested(ref.node):
            if isinstance(sub, ast.Raise) and sub.exc is not None:
                target = sub.exc
                if isinstance(target, ast.Call):
                    target = target.func
                name = _terminal_name(target)
                if name in self.typed_errors:
                    out.add(name)
            elif isinstance(sub, ast.Call) and _depth < 8:
                callee = self.resolve_call(sub, ref.module, ref.cls)
                if callee is not None and callee.node is not ref.node:
                    out |= self.raise_closure(callee, _depth + 1)
        self._raise_cache[key] = out
        return out

    def stmt_raises(self, stmts: List[ast.stmt], mod: _ModuleInfo, cls: Optional[str]) -> Set[str]:
        """Typed errors the statement list may raise (direct + call closure)."""
        out: Set[str] = set()
        for stmt in stmts:
            for sub in [stmt, *_walk_skip_nested(stmt)]:
                if isinstance(sub, ast.Raise) and sub.exc is not None:
                    target = sub.exc
                    if isinstance(target, ast.Call):
                        target = target.func
                    name = _terminal_name(target)
                    if name in self.typed_errors:
                        out.add(name)
                elif isinstance(sub, ast.Call):
                    callee = self.resolve_call(sub, mod, cls)
                    if callee is not None:
                        out |= self.raise_closure(callee)
        return out

    def is_subclass(self, name: str, ancestor: str) -> bool:
        """Name-level subclass test over the discovered hierarchy (plus the
        builtin bases recorded for each typed error)."""
        seen: Set[str] = set()
        stack = [name]
        while stack:
            cur = stack.pop()
            if cur == ancestor:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self.error_bases.get(cur, ()))
        return False


def _scan_external_reads(root: str) -> Set[str]:
    """Attribute names read by tests/bench/examples (regex scan: ``.name``
    loads plus getattr/hasattr string literals). Coarse on purpose — an over-
    wide read index only makes PWA203 quieter, never noisier."""
    attr_re = re.compile(r"\.\s*([A-Za-z_]\w*)")
    getattr_re = re.compile(r"(?:getattr|hasattr|setattr)\(\s*[^,]+,\s*['\"](\w+)['\"]")
    out: Set[str] = set()
    for rel in _EXTERNAL_READ_GLOBS:
        path = os.path.join(root, rel)
        files: List[str] = []
        if os.path.isfile(path):
            files = [path]
        elif os.path.isdir(path):
            for base, _dirs, names in os.walk(path):
                files.extend(
                    os.path.join(base, n) for n in names if n.endswith(".py")
                )
        for fpath in files:
            try:
                with open(fpath, "r", encoding="utf-8") as f:
                    source = f.read()
            except OSError:
                continue
            out.update(attr_re.findall(source))
            out.update(getattr_re.findall(source))
    return out


def build_resource_context(
    paths: "Optional[List[str]]" = None, *, with_external_reads: bool = True
) -> ResourceAnalysisContext:
    modules = _load_modules(paths if paths is not None else list(RESOURCE_MODULES))
    external = _scan_external_reads(_REPO_ROOT) if with_external_reads else set()
    return ResourceAnalysisContext(modules, external_reads=external)


# ---------------------------------------------------------------------------
# pass base
# ---------------------------------------------------------------------------


class ResourcePass(ConcurrencyPass):
    """One resource/exception-contract pass. Shares the Diagnostic + noqa
    machinery with the concurrency passes (different context type)."""

    code = "PWA200"

    def run(self, ctx: ResourceAnalysisContext) -> List[Diagnostic]:  # type: ignore[override]
        raise NotImplementedError


def _iter_funcs(ctx: ResourceAnalysisContext) -> Iterator[_FuncRef]:
    yield from ctx.funcs


# ---------------------------------------------------------------------------
# PWA201 — acquire/release pairing
# ---------------------------------------------------------------------------


class _Acquire:
    __slots__ = ("var", "kind", "releases", "lineno", "stmt")

    def __init__(self, var: str, kind: str, releases: Tuple[str, ...], lineno: int, stmt: ast.stmt):
        self.var = var
        self.kind = kind
        self.releases = releases
        self.lineno = lineno
        self.stmt = stmt


class AcquireReleasePass(ResourcePass):
    """PWA201: a registered resource acquisition whose release does not
    dominate every exit — not in a ``with``, not in a ``finally``, not in a
    provably-exception-free tail, and never transferred to another owner.

    Known precision limit: escape analysis is flow-INsensitive — a ``return s``
    (or store/call-arg) on ANY path blesses the variable on every path, so a
    conditional ownership transfer followed by raising statements on the other
    branch is not caught. Full dominance analysis over the CFG would close
    this; the pass trades it for zero false positives on ownership-transfer
    idioms (dial → tune → store) that pervade the mesh wiring."""

    code = "PWA201"
    title = "resource release does not dominate every exit"

    def run(self, ctx: ResourceAnalysisContext) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for ref in _iter_funcs(ctx):
            out.extend(self._check_function(ctx, ref))
        out.extend(self._check_class_attrs(ctx))
        return out

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _ctor_of(call: ast.AST, mod: _ModuleInfo) -> Optional[Tuple[str, Tuple[str, ...]]]:
        if not isinstance(call, ast.Call):
            return None
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id == "open":
                return _RESOURCE_CTORS["open"]
            if fn.id in _RESOURCE_CTORS and fn.id != "open":
                # `from socket import socket` / `from subprocess import Popen`
                if fn.id in mod.import_funcs:
                    return _RESOURCE_CTORS[fn.id]
            return None
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            # module-alias receivers only: `store.open()` is a method, not a fd
            if fn.value.id in mod.import_modules and fn.attr in _RESOURCE_CTORS:
                return _RESOURCE_CTORS[fn.attr]
        return None

    def _check_function(self, ctx: ResourceAnalysisContext, ref: _FuncRef) -> List[Diagnostic]:
        mod, node = ref.module, ref.node
        acquires: List[_Acquire] = []
        attr_acquires: List[Tuple[str, int]] = []  # (attr, lineno) — checked class-wide
        local_to_attr: Dict[str, str] = {}

        # withitem context expressions and attribute receivers never count as
        # escapes; collect their Name ids up front (AST has no parent links)
        non_escape: Set[int] = set()
        with_managed: Set[str] = set()
        for sub in _walk_skip_nested(node):
            if isinstance(sub, ast.With) or isinstance(sub, ast.AsyncWith):
                for item in sub.items:
                    for inner in ast.walk(item.context_expr):
                        if isinstance(inner, ast.Name):
                            non_escape.add(id(inner))
                    if isinstance(item.context_expr, ast.Name):
                        with_managed.add(item.context_expr.id)
            elif isinstance(sub, ast.Attribute) and isinstance(sub.value, ast.Name):
                non_escape.add(id(sub.value))
            elif isinstance(sub, ast.Subscript) and isinstance(sub.value, ast.Name):
                non_escape.add(id(sub.value))
            elif isinstance(sub, ast.Compare):
                for inner in ast.walk(sub):
                    if isinstance(inner, ast.Name):
                        non_escape.add(id(inner))

        # acquisitions
        for sub in _walk_skip_nested(node):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                continue  # `with open(...) as f` is release-by-construction
            if isinstance(sub, ast.Assign):
                got = self._ctor_of(sub.value, mod)
                if got is None:
                    continue
                kind, releases = got
                for target in sub.targets:
                    if isinstance(target, ast.Name):
                        acquires.append(
                            _Acquire(target.id, kind, releases, sub.lineno, sub)
                        )
                    else:
                        attr = _self_attr(target)
                        if attr is not None:
                            attr_acquires.append((attr, sub.lineno))
        if not acquires and not attr_acquires:
            slot = self._check_slot_stores(ctx, ref)
            return slot
        # the `with ctor()` case: the ctor Call sits in a withitem — drop
        # acquisitions whose ctor call is managed (detected above by walking
        # With items first; Assign-in-with is not a python shape, so only
        # plain `x = ctor()` reaches here)

        # escapes + releases
        escaped: Set[str] = set(with_managed)
        released_finally: Set[str] = set()
        released_lines: Dict[str, List[ast.Call]] = {}
        for name in [a.var for a in acquires]:
            released_lines.setdefault(name, [])

        def note_escapes(expr: "ast.expr | None") -> None:
            if expr is None:
                return
            for inner in ast.walk(expr):
                if isinstance(inner, ast.Name) and id(inner) not in non_escape:
                    escaped.add(inner.id)

        acquire_ids = {id(a.stmt) for a in acquires}
        for sub in _walk_skip_nested(node):
            if isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)):
                note_escapes(sub.value)
            elif isinstance(sub, ast.Assign) and id(sub) not in acquire_ids:
                note_escapes(sub.value)
                for target in sub.targets:
                    attr = _self_attr(target)
                    if attr is not None and isinstance(sub.value, ast.Name):
                        local_to_attr[sub.value.id] = attr
            elif isinstance(sub, ast.Call):
                for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                    note_escapes(arg)
                fn = sub.func
                if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
                    for a in acquires:
                        if fn.value.id == a.var and fn.attr in a.releases:
                            released_lines[a.var].append(sub)

        # which release calls sit under a finally?
        finally_calls: Set[int] = set()
        for sub in _walk_skip_nested(node):
            if isinstance(sub, ast.Try) and sub.finalbody:
                for stmt in sub.finalbody:
                    for inner in [stmt, *ast.walk(stmt)]:
                        if isinstance(inner, ast.Call):
                            finally_calls.add(id(inner))
        for a in acquires:
            if any(id(c) in finally_calls for c in released_lines[a.var]):
                released_finally.add(a.var)

        out: List[Diagnostic] = []
        for a in acquires:
            if a.var in escaped or a.var in released_finally:
                continue
            if a.var in local_to_attr:
                continue  # ownership moved to the object; class-wide check below
            if self._released_in_safe_tail(node, a):
                continue
            d = self.diag(
                Severity.ERROR,
                f"{a.kind} acquired into {a.var!r} in {ref.qual} is not "
                "released on every exit: no `with`, no `finally`-path "
                f"{'/'.join(a.releases)}(), and no ownership transfer — an "
                "exception between acquire and release leaks the "
                f"{a.kind} (wrap in `with`, or release in `finally`)",
                module=mod, lineno=a.lineno, function=ref.qual,
                resource=a.kind, variable=a.var,
            )
            if d is not None:
                out.append(d)
        out.extend(self._check_slot_stores(ctx, ref))
        return out

    @staticmethod
    def _released_in_safe_tail(fn_node: ast.AST, acq: _Acquire) -> bool:
        """Release follows the acquire in the same statement block with only
        provably-exception-free statements between them."""

        def block_check(body: List[ast.stmt]) -> bool:
            for i, stmt in enumerate(body):
                if stmt is not acq.stmt:
                    continue
                for later in body[i + 1:]:
                    if (
                        isinstance(later, ast.Expr)
                        and isinstance(later.value, ast.Call)
                        and isinstance(later.value.func, ast.Attribute)
                        and isinstance(later.value.func.value, ast.Name)
                        and later.value.func.value.id == acq.var
                        and later.value.func.attr in acq.releases
                    ):
                        return True
                    if not _cannot_raise(later):
                        return False
                return False
            return False

        for sub in [fn_node, *_walk_skip_nested(fn_node)]:
            for field in ("body", "orelse", "finalbody"):
                body = getattr(sub, field, None)
                if isinstance(body, list) and block_check(body):
                    return True
        return False

    def _check_slot_stores(self, ctx: ResourceAnalysisContext, ref: _FuncRef) -> List[Diagnostic]:
        """Admission-slot containers: a function that stores AND pops a slot
        must pop on the finally path — a success-only pop is the PR-6
        cancelled-client wedge."""
        mod, node = ref.module, ref.node
        stores: List[Tuple[str, int]] = []
        pops: List[ast.Call] = []
        for sub in _walk_skip_nested(node):
            if isinstance(sub, ast.Assign):
                for target in sub.targets:
                    if isinstance(target, ast.Subscript):
                        attr = _self_attr(target.value)
                        if attr in _SLOT_CONTAINERS:
                            stores.append((attr, sub.lineno))
            elif isinstance(sub, ast.Call):
                fn = sub.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in _SLOT_RELEASES
                    and _self_attr(fn.value) in _SLOT_CONTAINERS
                ):
                    pops.append(sub)
        if not stores or not pops:
            return []
        finally_calls: Set[int] = set()
        for sub in _walk_skip_nested(node):
            if isinstance(sub, ast.Try) and sub.finalbody:
                for stmt in sub.finalbody:
                    for inner in [stmt, *ast.walk(stmt)]:
                        if isinstance(inner, ast.Call):
                            finally_calls.add(id(inner))
        if any(id(p) in finally_calls for p in pops):
            return []
        attr, lineno = stores[0]
        d = self.diag(
            Severity.ERROR,
            f"admission slot stored into self.{attr}[...] in {ref.qual} is "
            "released only on the success path: a cancelled/raising request "
            "leaks its slot and wedges the admission cap — pop it in a "
            "`finally`",
            module=mod, lineno=lineno, function=ref.qual, container=attr,
        )
        return [d] if d is not None else []

    def _check_class_attrs(self, ctx: ResourceAnalysisContext) -> List[Diagnostic]:
        """Class-attribute resources: SOME method of the class must release the
        attribute (``self.a.close()``, or through a local alias — the teardown
        helper called from a ``finally`` is the interprocedural corner)."""
        out: List[Diagnostic] = []
        for cls_name, (mod, cls_node) in ctx.class_defs.items():
            resource_attrs: Dict[str, Tuple[str, Tuple[str, ...], int, str]] = {}
            for method in ctx.class_methods.get(cls_name, {}).values():
                for sub in _walk_skip_nested(method.node):
                    if not isinstance(sub, ast.Assign):
                        continue
                    got = self._ctor_of(sub.value, mod)
                    direct_attr = None
                    for target in sub.targets:
                        a = _self_attr(target)
                        if a is not None:
                            direct_attr = a
                    if got is not None and direct_attr is not None:
                        resource_attrs.setdefault(
                            direct_attr, (got[0], got[1], sub.lineno, method.qual)
                        )
                    elif direct_attr is not None and isinstance(sub.value, ast.Name):
                        # `self.attr = local` where local held a resource
                        for inner in _walk_skip_nested(method.node):
                            if (
                                isinstance(inner, ast.Assign)
                                and any(
                                    isinstance(t, ast.Name) and t.id == sub.value.id
                                    for t in inner.targets
                                )
                            ):
                                got2 = self._ctor_of(inner.value, mod)
                                if got2 is not None:
                                    resource_attrs.setdefault(
                                        direct_attr,
                                        (got2[0], got2[1], sub.lineno, method.qual),
                                    )
            if not resource_attrs:
                continue
            for attr, (kind, releases, lineno, qual) in sorted(resource_attrs.items()):
                if self._class_releases_attr(ctx, cls_name, attr, releases):
                    continue
                d = self.diag(
                    Severity.ERROR,
                    f"{cls_name}.{attr} holds a {kind} but no method of the "
                    f"class ever calls {'/'.join(releases)}() on it: the "
                    "object's teardown path cannot release the resource",
                    module=mod, lineno=lineno, function=qual,
                    cls=cls_name, attr=attr, resource=kind,
                )
                if d is not None:
                    out.append(d)
        return out

    @staticmethod
    def _class_releases_attr(
        ctx: ResourceAnalysisContext, cls_name: str, attr: str, releases: Tuple[str, ...]
    ) -> bool:
        seen: Set[str] = set()
        stack = [cls_name]
        while stack:
            name = stack.pop()
            if name in seen:
                continue
            seen.add(name)
            for method in ctx.class_methods.get(name, {}).values():
                aliases: Set[str] = set()
                for sub in _walk_skip_nested(method.node):
                    if isinstance(sub, ast.Assign):
                        # x = self.attr  /  x, self.attr = self.attr, None
                        values = (
                            list(sub.value.elts)
                            if isinstance(sub.value, ast.Tuple)
                            else [sub.value]
                        )
                        targets = sub.targets
                        if (
                            len(targets) == 1
                            and isinstance(targets[0], ast.Tuple)
                            and len(targets[0].elts) == len(values)
                        ):
                            pairs = list(zip(targets[0].elts, values))
                        elif len(values) == 1:
                            pairs = [(t, values[0]) for t in targets]
                        else:
                            pairs = []
                        for tgt, val in pairs:
                            if (
                                isinstance(tgt, ast.Name)
                                and _self_attr(val) == attr
                            ):
                                aliases.add(tgt.id)
                for sub in _walk_skip_nested(method.node):
                    if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
                        if sub.func.attr not in releases:
                            continue
                        recv = sub.func.value
                        if _self_attr(recv) == attr:
                            return True
                        if isinstance(recv, ast.Name) and recv.id in aliases:
                            return True
            entry = ctx.class_defs.get(name)
            if entry is not None:
                stack.extend(
                    b for b in (_terminal_name(x) for x in entry[1].bases) if b
                )
        return False


# ---------------------------------------------------------------------------
# PWA202 — typed-error swallowing
# ---------------------------------------------------------------------------


class TypedErrorSwallowPass(ResourcePass):
    """PWA202: broad handlers that can eat the failure model's typed errors.
    ``except BaseException`` without re-raise is flagged unconditionally (it
    can eat ``GraphCaptureInterrupt``); bare/``except Exception`` is flagged
    when the try body's interprocedural raise set carries a typed protocol
    error the handler neither re-raises nor isinstance-triages."""

    code = "PWA202"
    title = "broad except swallows typed protocol errors"

    def run(self, ctx: ResourceAnalysisContext) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for ref in _iter_funcs(ctx):
            for sub in _walk_skip_nested(ref.node):
                if isinstance(sub, ast.Try):
                    out.extend(self._check_try(ctx, ref, sub))
        return out

    #: methods that STORE their argument for another consumer — shipping the
    #: exception object onward, not discarding it. Deliberately narrow: a
    #: ``log.warning("...", exc)`` is log-and-continue, i.e. exactly the
    #: swallow this pass exists to catch.
    _TRANSFER_METHODS = frozenset({
        "append", "add", "put", "put_nowait", "set_exception", "set_result",
        "send", "extend",
    })

    @classmethod
    def _handler_triages(cls, handler: ast.ExceptHandler) -> bool:
        """Re-raise, isinstance triage, or capture-for-transfer: a handler that
        STORES the bound exception somewhere another thread reads it
        (``t.exception = exc``, ``errors.append(exc)``, ``fut.set_exception(exc)``)
        is shipping the failure, not swallowing it. Storing means an attribute/
        subscript assignment target or a transfer-method call — a plain local
        (``msg = str(exc)``) or a logging call does NOT count."""
        exc_name = handler.name
        for sub in ast.walk(handler):
            if isinstance(sub, ast.Raise):
                return True
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == "isinstance"
            ):
                return True
            if exc_name is None:
                continue
            stored: "List[ast.expr]" = []
            if isinstance(sub, ast.Assign) and any(
                isinstance(t, (ast.Attribute, ast.Subscript)) for t in sub.targets
            ):
                stored = [sub.value]
            elif (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in cls._TRANSFER_METHODS
            ):
                stored = list(sub.args)
            for value in stored:
                if any(
                    isinstance(inner, ast.Name) and inner.id == exc_name
                    for inner in ast.walk(value)
                ):
                    return True
        return False

    def _check_try(
        self, ctx: ResourceAnalysisContext, ref: _FuncRef, node: ast.Try
    ) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        body_raises: "Optional[Set[str]]" = None  # computed lazily (closure walk)
        caught_before: List[str] = []
        for handler in node.handlers:
            names = _exc_names(handler.type)
            broadest = handler.type is None or any(n in _BROADEST for n in names)
            broad = broadest or any(n in _BROAD for n in names)
            if not broad:
                caught_before.extend(names)
                continue
            if self._handler_triages(handler):
                caught_before.extend(names)
                continue
            if broadest:
                d = self.diag(
                    Severity.ERROR,
                    f"{'bare except' if handler.type is None else 'except BaseException'} "
                    f"in {ref.qual} neither re-raises nor triages: it can eat "
                    "GraphCaptureInterrupt (and KeyboardInterrupt), so the "
                    "capture/abort protocol silently dies here — catch "
                    "Exception, or re-raise after cleanup",
                    module=ref.module, lineno=handler.lineno, function=ref.qual,
                )
                if d is not None:
                    out.append(d)
                caught_before.extend(names)
                continue
            if body_raises is None:
                body_raises = ctx.stmt_raises(node.body, ref.module, ref.cls)
            # Exception-derived only: BaseException-derived typed errors
            # (GraphCaptureInterrupt) fly PAST an `except Exception` anyway
            residual = {
                e
                for e in body_raises
                if ctx.is_subclass(e, "Exception")
                and not any(ctx.is_subclass(e, c) for c in caught_before)
            }
            if residual:
                listed = ", ".join(sorted(residual))
                d = self.diag(
                    Severity.ERROR,
                    f"broad except in {ref.qual} can swallow typed protocol "
                    f"error(s) {listed} raised in the try body: the failure "
                    "model routes recovery on these types — triage with "
                    "isinstance/a narrower except, or re-raise",
                    module=ref.module, lineno=handler.lineno, function=ref.qual,
                    swallows=sorted(residual),
                )
                if d is not None:
                    out.append(d)
            caught_before.extend(names)
        return out


# ---------------------------------------------------------------------------
# PWA203 — write-only / dead attribute state
# ---------------------------------------------------------------------------


class DeadStatePass(ResourcePass):
    """PWA203: runtime-class attributes written outside constructor-only code
    but read nowhere (any analyzed module + the external read index): the
    parked-continuation bug class — state that no longer means anything."""

    code = "PWA203"
    title = "write-only attribute state"

    def run(self, ctx: ResourceAnalysisContext) -> List[Diagnostic]:
        # global read index: any `x.attr` load in the analyzed modules
        reads: Set[str] = set(ctx.external_reads)
        not_read_nodes: Set[int] = set()
        for mod in ctx.modules:
            for sub in ast.walk(mod.tree):
                if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
                    if sub.func.attr in _WRITE_ONLY_MUTATORS:
                        # `self.x.append(v)`: the self.x load is the WRITE's
                        # receiver, not a read of the value
                        not_read_nodes.add(id(sub.func.value))
                elif isinstance(sub, ast.Subscript) and isinstance(sub.ctx, (ast.Store, ast.Del)):
                    not_read_nodes.add(id(sub.value))
                elif (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id in ("getattr", "hasattr")
                    and len(sub.args) >= 2
                    and isinstance(sub.args[1], ast.Constant)
                    and isinstance(sub.args[1].value, str)
                ):
                    reads.add(sub.args[1].value)
        for mod in ctx.modules:
            for sub in ast.walk(mod.tree):
                if (
                    isinstance(sub, ast.Attribute)
                    and isinstance(sub.ctx, ast.Load)
                    and id(sub) not in not_read_nodes
                ):
                    reads.add(sub.attr)

        out: List[Diagnostic] = []
        for cls_name, (mod, cls_node) in ctx.class_defs.items():
            cls_info = mod.classes.get(cls_name)
            if cls_info is None:
                continue
            from pathway_tpu.analysis.concurrency import UnlockedSharedWritePass

            exempt = UnlockedSharedWritePass._constructor_only(cls_info)
            writes: Dict[str, Tuple[str, int]] = {}
            for method in ctx.class_methods.get(cls_name, {}).values():
                if method.name.split(".")[0] in exempt:
                    continue
                for sub in _walk_skip_nested(method.node):
                    attr: Optional[str] = None
                    if isinstance(sub, (ast.Assign, ast.AugAssign)):
                        targets = (
                            sub.targets if isinstance(sub, ast.Assign) else [sub.target]
                        )
                        for t in targets:
                            a = _self_attr(t)
                            if a is None and isinstance(t, ast.Subscript):
                                a = _self_attr(t.value)
                            if a is not None:
                                attr = a
                    elif isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
                        if sub.func.attr in _WRITE_ONLY_MUTATORS:
                            attr = _self_attr(sub.func.value)
                    if attr is None or attr.startswith("__"):
                        continue
                    writes.setdefault(attr, (method.qual, sub.lineno))
            for attr, (qual, lineno) in sorted(writes.items()):
                if attr in reads:
                    continue
                d = self.diag(
                    Severity.WARNING,
                    f"{cls_name}.{attr} is written in {qual} but never read "
                    "anywhere (analyzed modules + tests/bench): write-only "
                    "state is the parked-continuation bug class — delete it, "
                    "or wire the consumer it was meant for (`# noqa: PWA203 "
                    "(<why>)` if it is intentionally export-only)",
                    module=mod, lineno=lineno, function=qual,
                    cls=cls_name, attr=attr,
                )
                if d is not None:
                    out.append(d)
        return out


# ---------------------------------------------------------------------------
# PWA204 — exception-masking finally/cleanup
# ---------------------------------------------------------------------------


class FinallyMaskPass(ResourcePass):
    """PWA204: a ``raise``/``return``/``break``/``continue`` or an unguarded
    typed-error-raising call inside ``finally`` replaces the in-flight
    exception — the fence ladder then routes recovery on the wrong type."""

    code = "PWA204"
    title = "finally block can mask the in-flight exception"

    def run(self, ctx: ResourceAnalysisContext) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for ref in _iter_funcs(ctx):
            for sub in _walk_skip_nested(ref.node):
                if isinstance(sub, ast.Try) and sub.finalbody:
                    out.extend(self._check_finally(ctx, ref, sub.finalbody))
        return out

    def _check_finally(
        self, ctx: ResourceAnalysisContext, ref: _FuncRef, finalbody: List[ast.stmt]
    ) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        guarded: Set[int] = set()  # nodes under a try/except INSIDE the finally

        def scan(stmts: List[ast.stmt]) -> None:
            for stmt in stmts:
                for sub in [stmt, *_walk_skip_nested(stmt)]:
                    if isinstance(sub, ast.Try) and sub.handlers:
                        for inner_stmt in sub.body:
                            for inner in [inner_stmt, *ast.walk(inner_stmt)]:
                                guarded.add(id(inner))

        scan(finalbody)
        for stmt in finalbody:
            for sub in [stmt, *_walk_skip_nested(stmt)]:
                if id(sub) in guarded:
                    continue
                if isinstance(sub, ast.Raise):
                    d = self.diag(
                        Severity.ERROR,
                        f"raise inside finally in {ref.qual} replaces the "
                        "in-flight exception: a typed protocol error unwinding "
                        "through here becomes this one — re-raise outside the "
                        "finally, or guard the cleanup",
                        module=ref.module, lineno=sub.lineno, function=ref.qual,
                    )
                    if d is not None:
                        out.append(d)
                elif isinstance(sub, (ast.Return, ast.Break, ast.Continue)):
                    kind = type(sub).__name__.lower()
                    d = self.diag(
                        Severity.ERROR,
                        f"{kind} inside finally in {ref.qual} silently "
                        "swallows any in-flight exception (including typed "
                        "protocol errors) — move it out of the finally",
                        module=ref.module, lineno=sub.lineno, function=ref.qual,
                    )
                    if d is not None:
                        out.append(d)
                elif isinstance(sub, ast.Call):
                    callee = ctx.resolve_call(sub, ref.module, ref.cls)
                    if callee is None:
                        continue
                    raised = ctx.raise_closure(callee)
                    if raised:
                        listed = ", ".join(sorted(raised))
                        d = self.diag(
                            Severity.ERROR,
                            f"call to {callee.qual} inside finally in "
                            f"{ref.qual} can raise {listed}: an error thrown "
                            "from cleanup masks the in-flight exception — "
                            "guard the call with its own try/except",
                            module=ref.module, lineno=sub.lineno,
                            function=ref.qual, raises=sorted(raised),
                        )
                        if d is not None:
                            out.append(d)
        return out


# ---------------------------------------------------------------------------
# PWA205 — telemetry-contract drift
# ---------------------------------------------------------------------------


class TelemetryContractPass(ResourcePass):
    """PWA205: stage-counter and flight-event string literals must parse
    against the registered namespaces (``telemetry.STAGE_NAMESPACES`` /
    ``telemetry.FLIGHT_EVENT_KINDS``) so counters can't silently fork from the
    ``/metrics`` dashboards built on them."""

    code = "PWA205"
    title = "unregistered telemetry namespace"

    def run(self, ctx: ResourceAnalysisContext) -> List[Diagnostic]:
        from pathway_tpu.engine.telemetry import (
            FLIGHT_EVENT_KINDS,
            STAGE_NAMESPACES,
            TRACE_SPAN_KINDS,
        )

        out: List[Diagnostic] = []
        for ref in _iter_funcs(ctx):
            out.extend(
                self._check_function(
                    ref, STAGE_NAMESPACES, FLIGHT_EVENT_KINDS, TRACE_SPAN_KINDS
                )
            )
        # module-level calls (rare) ride the module "function"
        return out

    @staticmethod
    def _literal_head(node: ast.AST) -> "Optional[Tuple[str, bool]]":
        """``(name, is_partial)``: a literal stage name, or the literal head of
        an f-string (partial — the tail is dynamic)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value, False
        if isinstance(node, ast.JoinedStr) and node.values:
            head = node.values[0]
            if isinstance(head, ast.Constant) and isinstance(head.value, str):
                return head.value, True
        return None

    def _check_name(
        self,
        ref: _FuncRef,
        node: ast.AST,
        name: str,
        namespaces: Tuple[str, ...],
        *,
        partial: bool,
    ) -> Optional[Diagnostic]:
        # a COMPLETE literal must carry a full registered prefix; only an
        # f-string head may be shorter than its namespace (f"embed{x}")
        ok = any(
            name.startswith(ns) or (partial and ns.startswith(name))
            for ns in namespaces
        )
        if ok:
            return None
        return self.diag(
            Severity.ERROR,
            f"stage counter {name!r} in {ref.qual} is outside every "
            "registered namespace "
            f"({', '.join(n.rstrip('.') for n in namespaces)}): it would fork "
            "from /metrics silently — register the prefix in "
            "telemetry.STAGE_NAMESPACES or fix the name",
            module=ref.module, lineno=node.lineno, function=ref.qual,
            stage=name,
        )

    def _check_function(
        self,
        ref: _FuncRef,
        namespaces: Tuple[str, ...],
        event_kinds: "frozenset[str]",
        trace_kinds: "frozenset[str]" = frozenset(),
    ) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        many_vars: Set[str] = set()
        for sub in _walk_skip_nested(ref.node):
            if isinstance(sub, ast.Call):
                callee = _terminal_name(sub.func)
                if callee == "stage_add_many" and sub.args:
                    if isinstance(sub.args[0], ast.Name):
                        many_vars.add(sub.args[0].id)
        for sub in _walk_skip_nested(ref.node):
            if isinstance(sub, ast.Call):
                callee = _terminal_name(sub.func)
                if callee in ("stage_add", "stage_timer") and sub.args:
                    got = self._literal_head(sub.args[0])
                    if got is not None:
                        d = self._check_name(
                            ref, sub.args[0], got[0], namespaces, partial=got[1]
                        )
                        if d is not None:
                            out.append(d)
                elif callee == "stage_add_many" and sub.args:
                    if isinstance(sub.args[0], ast.Dict):
                        for key in sub.args[0].keys:
                            got = self._literal_head(key) if key is not None else None
                            if got is not None:
                                d = self._check_name(
                                    ref, key, got[0], namespaces, partial=got[1]
                                )
                                if d is not None:
                                    out.append(d)
                elif callee == "record_event" and sub.args:
                    got = self._literal_head(sub.args[0])
                    head = got[0] if got is not None else None
                    if (
                        head is not None
                        and isinstance(sub.args[0], ast.Constant)
                        and head not in event_kinds
                    ):
                        d = self.diag(
                            Severity.ERROR,
                            f"flight event kind {head!r} in {ref.qual} is not "
                            "in telemetry.FLIGHT_EVENT_KINDS: post-mortem "
                            "tooling keyed on registered kinds will not see "
                            "it — register the kind or fix the name",
                            module=ref.module, lineno=sub.lineno,
                            function=ref.qual, event=head,
                        )
                        if d is not None:
                            out.append(d)
                elif (
                    callee in ("trace_span", "record_span", "start")
                    and trace_kinds
                    and sub.args
                    and isinstance(sub.args[0], ast.Constant)
                    and isinstance(sub.args[0].value, str)
                ):
                    # span kinds are closed-set literals: the merger and the
                    # critical-path walk key on them. ``.start`` is scoped to
                    # literal-string first args, so Thread.start() (no args)
                    # never matches
                    kind_lit = sub.args[0].value
                    if kind_lit not in trace_kinds:
                        d = self.diag(
                            Severity.ERROR,
                            f"trace span kind {kind_lit!r} in {ref.qual} is "
                            "not in telemetry.TRACE_SPAN_KINDS: the trace "
                            "merger and critical-path analysis key on "
                            "registered kinds — register the kind or fix "
                            "the name",
                            module=ref.module, lineno=sub.lineno,
                            function=ref.qual, span_kind=kind_lit,
                        )
                        if d is not None:
                            out.append(d)
            elif isinstance(sub, ast.Assign):
                # updates["exchange.x"] = 1 on a dict later fed to
                # stage_add_many: literal keys checked too
                for target in sub.targets:
                    if (
                        isinstance(target, ast.Subscript)
                        and isinstance(target.value, ast.Name)
                        and target.value.id in many_vars
                    ):
                        got = self._literal_head(target.slice)
                        if got is not None:
                            d = self._check_name(
                                ref, target, got[0], namespaces, partial=got[1]
                            )
                            if d is not None:
                                out.append(d)
        return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def default_resource_passes() -> List[ResourcePass]:
    return [
        AcquireReleasePass(),
        TypedErrorSwallowPass(),
        DeadStatePass(),
        FinallyMaskPass(),
        TelemetryContractPass(),
    ]


def analyze_resources(
    paths: "Optional[List[str]]" = None,
    *,
    passes: "Optional[List[ResourcePass]]" = None,
    ctx: "Optional[ResourceAnalysisContext]" = None,
) -> AnalysisReport:
    """Run the PWA201–205 pipeline over the resource modules (or ``paths``).
    Same report type and exit-code contract as the other lint families."""
    from pathway_tpu.analysis.framework import run_runtime_passes

    if ctx is None:
        ctx = build_resource_context(paths)
    if passes is None:
        passes = default_resource_passes()
    return run_runtime_passes(
        passes, ctx, family="resource", node_count=len(ctx.funcs)
    )


def analyze_resource_source(source: str, name: str = "planted") -> AnalysisReport:
    """Lint one in-memory module (tests plant violations this way). No
    external read index: the planted module is the whole world."""
    info = _ModuleParser(name, f"<{name}>", source).parse()
    return analyze_resources(ctx=ResourceAnalysisContext([info]))


def analyze_runtime_full(paths: "Optional[List[str]]" = None) -> AnalysisReport:
    """The full runtime lint: PWA101–104 (concurrency) + PWA201–205 (resource/
    exception contracts) folded into ONE report — what ``cli analyze
    --runtime`` surfaces. The modules are parsed ONCE and shared: the
    concurrency context is built over the RUNTIME_MODULES subset of the same
    parse the resource context uses."""
    from pathway_tpu.analysis.concurrency import (
        RuntimeAnalysisContext,
        analyze_runtime,
    )

    if paths is not None:
        concurrency_report = analyze_runtime()
        resource_report = analyze_resources(paths)
    else:
        modules = _load_modules(list(RESOURCE_MODULES))
        runtime_rel = set(RUNTIME_MODULES)
        runtime_mods = [
            m
            for m in modules
            if os.path.relpath(m.path, _REPO_ROOT).replace(os.sep, "/") in runtime_rel
        ]
        concurrency_report = analyze_runtime(ctx=RuntimeAnalysisContext(runtime_mods))
        resource_report = analyze_resources(
            ctx=ResourceAnalysisContext(
                modules, external_reads=_scan_external_reads(_REPO_ROOT)
            )
        )
    diagnostics = concurrency_report.diagnostics + resource_report.diagnostics
    diagnostics.sort(key=lambda d: (-int(d.severity), d.code, d.file or "", d.line or 0))
    return AnalysisReport(
        diagnostics,
        node_count=max(concurrency_report.node_count, resource_report.node_count),
        pass_seconds={
            **concurrency_report.pass_seconds,
            **resource_report.pass_seconds,
        },
        pass_checked={
            **concurrency_report.pass_checked,
            **resource_report.pass_checked,
        },
    )


_cached_report: "Optional[AnalysisReport]" = None


def resource_gate() -> None:
    """``PATHWAY_RESOURCE_LINT=off|warn|error`` (default ``off``): lint the
    runtime's resource/exception contracts before a run. ``warn`` logs and
    mirrors counters; ``error`` refuses the run on any PWA201–205 error. The
    report is cached process-wide — the runtime source cannot change under a
    live process."""
    from pathway_tpu.analysis.framework import enforce_gate, gate_mode

    mode = gate_mode("PATHWAY_RESOURCE_LINT")
    if mode is None:
        return
    global _cached_report
    if _cached_report is None:
        _cached_report = analyze_resources()
    enforce_gate(_cached_report, mode)
