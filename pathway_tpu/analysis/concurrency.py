"""Concurrency lint over the RUNTIME's own threaded code (PWA101–PWA104).

The graph-lint passes (PWA001–005) analyze USER dataflow graphs; the failure
model underneath them — fence/quiesce/rejoin, the aligned checkpoint protocol,
the recovery ladder — is itself a hand-written distributed protocol built from
Python threads, sockets, locks, and condition variables, and until now its only
correctness guard was chaos testing (whatever interleavings the OS scheduler
happened to produce). This module lints that runtime source statically, so
lock-order and lifecycle bugs surface at review time instead of as a wedged
cluster:

- **PWA101 — lock-order cycle.** A lock-acquisition graph is built over the
  threaded modules (``RUNTIME_MODULES``): every ``with <lock>:`` nested inside
  another — directly or through calls resolved interprocedurally (self-method
  and cross-module, e.g. the telemetry stage-counter lock taken by
  ``stage_add`` calls made under an exchange lock) — adds an edge. A cycle
  means two threads can acquire the same locks in opposite orders and
  deadlock; a self-edge means re-acquiring a non-reentrant lock. Error.
- **PWA102 — unbounded wait.** ``Condition.wait``/``Event.wait``/``Queue.get``
  with no timeout on runtime paths: the fence deadline, the supervisor's
  stall-killer, and teardown can only abort waits that wake up. Error.
- **PWA103 — unlocked shared write.** An attribute mutated under a lock in one
  method and with no lock in another (the RacerD-style inconsistent-locking
  heuristic). Constructor-only code is exempt (no peer threads exist yet —
  methods reachable ONLY from ``__init__`` and never escaping as callbacks are
  proven single-threaded); single-owner attributes (never locked anywhere)
  are not flagged. Warning — the heuristic cannot see ownership conventions,
  so confirmed-benign sites carry ``# noqa: PWA103`` with a reason.
- **PWA104 — thread-lifecycle hygiene.** A ``threading.Thread`` that is
  neither daemon nor joined in its creating scope outlives ``pw.run`` /
  server teardown and wedges interpreter shutdown. Error.

Surfaces mirror the graph lint: ``pathway_tpu.cli analyze --runtime`` (same
JSON format and 0/1/2 exit-code contract), an optional
``PATHWAY_RUNTIME_LINT=off|warn|error`` gate on ``pw.run`` (default ``off`` —
the runtime tree changes with the package, not the user program, so CI runs
the cli gate instead of every run paying a re-parse), and ``lint.diag.PWA10x``
stage counters + the ``lint`` flight event via
:meth:`~pathway_tpu.analysis.framework.AnalysisReport.emit_telemetry`.

Any finding can be suppressed inline with ``# noqa: PWA1xx`` (a bare
``# noqa`` suppresses all four); suppressions should say why.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

from pathway_tpu.analysis.framework import (
    AnalysisReport,
    Diagnostic,
    Severity,
)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: the threaded runtime layers the concurrency passes police. Relative to the
#: repo root; ``internals/sched.py`` + ``internals/protocol_models.py`` are the
#: model-checking harness itself — it eats its own dog food.
RUNTIME_MODULES: Tuple[str, ...] = (
    "pathway_tpu/parallel/cluster.py",
    "pathway_tpu/parallel/supervisor.py",
    "pathway_tpu/parallel/membership.py",
    "pathway_tpu/parallel/autoscaler.py",
    "pathway_tpu/parallel/replica.py",
    "pathway_tpu/parallel/threads.py",
    "pathway_tpu/engine/brownout.py",
    "pathway_tpu/models/embed_pipeline.py",
    "pathway_tpu/models/encoder_service.py",
    "pathway_tpu/ops/knn_tiers.py",
    "pathway_tpu/ops/knn_quant.py",
    "pathway_tpu/engine/http_server.py",
    "pathway_tpu/engine/telemetry.py",
    "pathway_tpu/engine/tracing.py",
    "pathway_tpu/internals/sched.py",
    "pathway_tpu/internals/protocol_models.py",
)

# threading-primitive constructors, by terminal callee name
_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Semaphore": "lock", "BoundedSemaphore": "lock"}
_COND_CTORS = {"Condition": "condition"}
_EVENT_CTORS = {"Event": "event"}
_QUEUE_CTORS = {"Queue": "queue", "SimpleQueue": "queue", "LifoQueue": "queue", "PriorityQueue": "queue"}
_ALL_CTORS = {**_LOCK_CTORS, **_COND_CTORS, **_EVENT_CTORS, **_QUEUE_CTORS}

# methods that block on each primitive kind (PWA102 scope)
_BLOCKING_METHODS = {
    "condition": {"wait", "wait_for"},
    "event": {"wait"},
    "queue": {"get", "join"},
}

# container-mutating method names (shared shape with passes.py's PWA001 set)
_MUTATOR_METHODS: Set[str] = {
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "remove", "discard", "clear", "setdefault", "sort", "reverse",
    "appendleft", "extendleft", "popleft",
}

_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z0-9, ]+))?", re.IGNORECASE)


# ---------------------------------------------------------------------------
# module model
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LockDef:
    """One lock-ish attribute or global: identity is ``scope.attr``."""

    scope: str  # class name, or module short name for globals
    attr: str  # attribute/global name; container locks carry a "[]" suffix
    kind: str  # lock | rlock | condition | event | queue
    module: str
    lineno: int

    @property
    def ident(self) -> str:
        return f"{self.scope}.{self.attr}"


@dataclass
class _CallSite:
    held: Tuple[str, ...]  # lock idents held at the call
    callee: Tuple[str, str, str]  # ("method", Class, name) | ("func", module, name)
    lineno: int


@dataclass
class _Mutation:
    attr: str
    lineno: int
    locked: bool


@dataclass
class _WaitSite:
    lock: LockDef
    method: str
    lineno: int
    has_timeout: bool


@dataclass
class _ThreadSite:
    lineno: int
    daemon: bool
    joined: bool
    assigned_to: Optional[str]


@dataclass
class _FuncInfo:
    module: str
    cls: Optional[str]
    name: str
    lineno: int
    acquires: Set[str] = field(default_factory=set)  # lock idents taken directly
    edges: List[Tuple[str, str, int]] = field(default_factory=list)  # (outer, inner, line)
    calls: List[_CallSite] = field(default_factory=list)
    mutations: List[_Mutation] = field(default_factory=list)
    waits: List[_WaitSite] = field(default_factory=list)
    threads: List[_ThreadSite] = field(default_factory=list)
    has_any_join: bool = False
    joined_names: Set[str] = field(default_factory=set)  # `x.join(...)` receivers
    daemon_names: Set[str] = field(default_factory=set)  # `x.daemon = True` targets

    @property
    def qual(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


@dataclass
class _ClassInfo:
    name: str
    module: str
    bases: List[str]
    lock_attrs: Dict[str, LockDef] = field(default_factory=dict)
    cond_alias: Dict[str, str] = field(default_factory=dict)  # cond attr -> lock attr
    methods: Dict[str, _FuncInfo] = field(default_factory=dict)
    escaped_methods: Set[str] = field(default_factory=set)  # passed as callbacks
    nonlock_attrs: Set[str] = field(default_factory=set)  # assigned non-primitives


@dataclass
class _ModuleInfo:
    short: str  # e.g. "cluster"
    path: str
    tree: ast.Module
    source_lines: List[str]
    classes: Dict[str, _ClassInfo] = field(default_factory=dict)
    global_locks: Dict[str, LockDef] = field(default_factory=dict)
    functions: Dict[str, _FuncInfo] = field(default_factory=dict)
    import_funcs: Dict[str, Tuple[str, str]] = field(default_factory=dict)  # local name -> (module short, func)
    import_modules: Dict[str, str] = field(default_factory=dict)  # local alias -> module short

    def noqa_codes(self, lineno: int) -> Optional[Set[str]]:
        """Codes suppressed on ``lineno`` (empty set = suppress everything)."""
        if not (1 <= lineno <= len(self.source_lines)):
            return None
        m = _NOQA_RE.search(self.source_lines[lineno - 1])
        if m is None:
            return None
        codes = m.group("codes")
        if not codes:
            return set()
        return {c.strip().upper() for c in codes.split(",") if c.strip()}


class RuntimeAnalysisContext:
    """Parsed view of the runtime modules shared by all four passes."""

    def __init__(self, modules: List[_ModuleInfo]):
        self.modules = modules
        # attr name -> every LockDef carrying it (the terminal-attribute
        # heuristic for `other.event.wait()` receivers)
        self.attr_index: Dict[str, List[LockDef]] = {}
        for mod in modules:
            for cls in mod.classes.values():
                for ld in cls.lock_attrs.values():
                    self.attr_index.setdefault(ld.attr, []).append(ld)
            for ld in mod.global_locks.values():
                self.attr_index.setdefault(ld.attr, []).append(ld)
        # attr names ALSO assigned non-primitive values somewhere: the
        # terminal-attribute heuristic must not fire on those (a model's
        # `cv = sched.condition(...)` is not ThreadExchangeHub's real one)
        self.ambiguous_attrs: Set[str] = set()
        for mod in modules:
            for cls in mod.classes.values():
                self.ambiguous_attrs |= cls.nonlock_attrs & set(self.attr_index)
        self._closure_cache: Dict[Tuple[str, str, str], Set[str]] = {}

    # -- resolution ----------------------------------------------------------

    def find_class(self, name: str) -> Optional[_ClassInfo]:
        for mod in self.modules:
            if name in mod.classes:
                return mod.classes[name]
        return None

    def resolve_method(self, cls_name: str, method: str) -> Optional[_FuncInfo]:
        """Look up a method on a class or (by name) its analyzed bases."""
        seen: Set[str] = set()
        stack = [cls_name]
        while stack:
            name = stack.pop()
            if name in seen:
                continue
            seen.add(name)
            cls = self.find_class(name)
            if cls is None:
                continue
            if method in cls.methods:
                return cls.methods[method]
            stack.extend(cls.bases)
        return None

    def class_lock(self, cls_name: str, attr: str) -> Optional[LockDef]:
        """A lock attr on a class or its analyzed bases, condition aliases
        canonicalized to the underlying lock (one identity per mutex)."""
        seen: Set[str] = set()
        stack = [cls_name]
        while stack:
            name = stack.pop()
            if name in seen:
                continue
            seen.add(name)
            cls = self.find_class(name)
            if cls is None:
                continue
            attr = cls.cond_alias.get(attr, attr)
            if attr in cls.lock_attrs:
                return cls.lock_attrs[attr]
            stack.extend(cls.bases)
        return None

    def resolve_func(self, module: str, name: str) -> Optional[_FuncInfo]:
        for mod in self.modules:
            if mod.short == module:
                return mod.functions.get(name)
        return None

    def acquire_closure(self, fn: _FuncInfo, _depth: int = 0) -> Set[str]:
        """Every lock ``fn`` may take, directly or through resolvable calls."""
        key = (fn.module, fn.cls or "", fn.name)
        got = self._closure_cache.get(key)
        if got is not None:
            return got
        self._closure_cache[key] = set(fn.acquires)  # cycle guard
        out = set(fn.acquires)
        if _depth < 12:
            for call in fn.calls:
                callee = self._callee_info(call)
                if callee is not None and callee is not fn:
                    out |= self.acquire_closure(callee, _depth + 1)
        self._closure_cache[key] = out
        return out

    def _callee_info(self, call: _CallSite) -> Optional[_FuncInfo]:
        kind, scope, name = call.callee
        if kind == "method":
            return self.resolve_method(scope, name)
        return self.resolve_func(scope, name)


# ---------------------------------------------------------------------------
# parsing
# ---------------------------------------------------------------------------


def _ctor_kind(call: ast.AST) -> Optional[str]:
    """'lock'/'condition'/… when ``call`` constructs a threading primitive."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    name = None
    if isinstance(fn, ast.Attribute):
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    return _ALL_CTORS.get(name or "")


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` → ``"X"``."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id in ("self", "cls")
    ):
        return node.attr
    return None


def _is_thread_ctor(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr == "Thread":
        return True
    return isinstance(fn, ast.Name) and fn.id == "Thread"


class _ModuleParser:
    """Builds a :class:`_ModuleInfo` from one source file."""

    def __init__(self, short: str, path: str, source: str):
        self.info = _ModuleInfo(
            short=short,
            path=path,
            tree=ast.parse(source, filename=path),
            source_lines=source.splitlines(),
        )

    def parse(self) -> _ModuleInfo:
        info = self.info
        for node in info.tree.body:
            if isinstance(node, ast.ImportFrom) and node.module:
                tail = node.module.rsplit(".", 1)[-1]
                for alias in node.names:
                    info.import_funcs[alias.asname or alias.name] = (tail, alias.name)
                    # `from pathway_tpu.engine import telemetry` also binds a
                    # MODULE name: register it as a module alias too, so
                    # `telemetry.stage_add(...)` resolves cross-module
                    info.import_modules[alias.asname or alias.name] = alias.name
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    tail = alias.name.rsplit(".", 1)[-1]
                    info.import_modules[alias.asname or tail] = tail
            elif isinstance(node, ast.Assign):
                kind = _ctor_kind(node.value)
                if kind is not None:
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            info.global_locks[target.id] = LockDef(
                                scope=info.short, attr=target.id, kind=kind,
                                module=info.short, lineno=node.lineno,
                            )
            elif isinstance(node, ast.ClassDef):
                self._parse_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = self._parse_function(node, cls=None)
                info.functions[fn.name] = fn
        return info

    # -- class level ---------------------------------------------------------

    def _parse_class(self, node: ast.ClassDef) -> None:
        cls = _ClassInfo(
            name=node.name,
            module=self.info.short,
            bases=[b.id for b in node.bases if isinstance(b, ast.Name)]
            + [b.attr for b in node.bases if isinstance(b, ast.Attribute)],
        )
        self.info.classes[node.name] = cls
        # first sweep: every `self.X = <primitive>()` anywhere in the class
        # (locks are usually born in __init__ but rejoin paths mint them late)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                kind = _ctor_kind(sub.value)
                for target in sub.targets:
                    attr = _self_attr(target)
                    if attr is not None and kind is None:
                        cls.nonlock_attrs.add(attr)
                    if attr is None:
                        # self._locks[k] = threading.Lock() → container of locks
                        if (
                            isinstance(target, ast.Subscript)
                            and kind is not None
                            and _self_attr(target.value) is not None
                        ):
                            container = _self_attr(target.value)
                            cls.lock_attrs.setdefault(
                                container + "[]",
                                LockDef(
                                    scope=node.name, attr=container + "[]", kind=kind,
                                    module=self.info.short, lineno=sub.lineno,
                                ),
                            )
                        continue
                    if kind is not None:
                        cls.lock_attrs.setdefault(
                            attr,
                            LockDef(
                                scope=node.name, attr=attr, kind=kind,
                                module=self.info.short, lineno=sub.lineno,
                            ),
                        )
                        # Condition(self._lock) shares the mutex with _lock:
                        # one identity, or PWA101 would see phantom 2-cycles
                        if (
                            kind == "condition"
                            and isinstance(sub.value, ast.Call)
                            and sub.value.args
                        ):
                            inner = _self_attr(sub.value.args[0])
                            if inner is not None:
                                cls.cond_alias[attr] = inner
            elif isinstance(sub, ast.Call):
                # self._locks.setdefault(k, threading.Lock())
                if (
                    isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "setdefault"
                    and len(sub.args) == 2
                    and _ctor_kind(sub.args[1]) is not None
                ):
                    container = _self_attr(sub.func.value)
                    if container is not None:
                        cls.lock_attrs.setdefault(
                            container + "[]",
                            LockDef(
                                scope=node.name, attr=container + "[]",
                                kind=_ctor_kind(sub.args[1]) or "lock",
                                module=self.info.short, lineno=sub.lineno,
                            ),
                        )
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = self._parse_function(item, cls=node.name)
                cls.methods[item.name] = fn
        # escaped methods: `self.m` referenced outside a direct call position
        # (Thread targets, callbacks) run on other threads — never
        # constructor-exempt for PWA103. AST has no parent links, so first
        # collect the Attribute nodes that ARE the func of a direct call.
        called_direct: Set[int] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
                called_direct.add(id(sub.func))
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Attribute)
                and _self_attr(sub) in cls.methods
                and id(sub) not in called_direct
            ):
                cls.escaped_methods.add(sub.attr)

    # -- function level ------------------------------------------------------

    def _parse_function(
        self, node: "ast.FunctionDef | ast.AsyncFunctionDef", cls: Optional[str]
    ) -> _FuncInfo:
        fn = _FuncInfo(module=self.info.short, cls=cls, name=node.name, lineno=node.lineno)
        local_waitables: Dict[str, str] = {}  # local var -> primitive kind
        local_locks: Dict[str, str] = {}  # local var -> lock ident
        thread_assigns: Dict[int, str] = {}  # id(Thread ctor Call) -> var name

        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                kind = _ctor_kind(sub.value)
                if kind is not None:
                    for target in sub.targets:
                        if isinstance(target, ast.Name):
                            local_waitables[target.id] = kind
                            local_locks[target.id] = (
                                f"{self.info.short}.{node.name}.{target.id}"
                            )
                if (
                    len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)
                    and isinstance(sub.value, ast.Call)
                    and _is_thread_ctor(sub.value)
                ):
                    thread_assigns[id(sub.value)] = sub.targets[0].id

        def lock_at(expr: ast.AST) -> Optional[str]:
            """Resolve an acquisition expression to a lock identity."""
            if isinstance(expr, ast.Call):
                # `with self._cond:` vs `cond.acquire()` handled by callers;
                # also `with self._lock_for(x):` — unresolvable
                return None
            if isinstance(expr, ast.Name):
                if expr.id in self.info.global_locks:
                    return self.info.global_locks[expr.id].ident
                return local_locks.get(expr.id)
            if isinstance(expr, ast.Subscript):
                base = _self_attr(expr.value)
                if base is not None and cls is not None:
                    return f"{cls}.{base}[]"
                return None
            if isinstance(expr, ast.Attribute):
                attr = _self_attr(expr)
                if attr is not None and cls is not None:
                    # alias-canonicalize through the class chain at report
                    # time; here use the raw (cls, attr) — the context
                    # resolves it in _canon below
                    return ("%s.%s" % (cls, attr))
                # other.cv / self._hub.cv: terminal-attribute heuristic,
                # resolved later by the context (needs the global attr index)
                return f"?attr.{expr.attr}"
            return None

        held: List[Tuple[str, int]] = []

        def visit(stmt: ast.AST) -> None:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) and stmt is not node:
                # nested defs (closures, Thread bodies) analyzed separately
                # under the parent's scope name; they don't inherit held locks
                inner = self._parse_function(stmt, cls=cls)
                inner.name = f"{node.name}.<locals>.{stmt.name}"
                if cls is not None:
                    self.info.classes[cls].methods[inner.name] = inner
                else:
                    self.info.functions[inner.name] = inner
                return
            if isinstance(stmt, ast.With):
                acquired: List[str] = []
                for item in stmt.items:
                    ident = lock_at(item.context_expr)
                    if ident is not None:
                        fn.acquires.add(ident)
                        for outer, _ln in held:
                            fn.edges.append((outer, ident, item.context_expr.lineno))
                        acquired.append(ident)
                        held.append((ident, item.context_expr.lineno))
                    else:
                        # `with telemetry.stage_timer(...):` — the context
                        # manager call itself may take locks; record it as a
                        # call site under whatever is currently held
                        visit(item.context_expr)
                for child in stmt.body:
                    visit(child)
                for _ in acquired:
                    held.pop()
                return
            if isinstance(stmt, ast.Call):
                self._record_call(fn, stmt, held, cls)
                self._record_wait(fn, stmt, local_waitables, cls)
                if _is_thread_ctor(stmt):
                    fn.threads.append(
                        _ThreadSite(
                            lineno=stmt.lineno,
                            daemon=any(
                                kw.arg == "daemon"
                                and isinstance(kw.value, ast.Constant)
                                and kw.value.value is True
                                for kw in stmt.keywords
                            ),
                            joined=False,
                            assigned_to=thread_assigns.get(id(stmt)),
                        )
                    )
                if isinstance(stmt.func, ast.Attribute) and stmt.func.attr == "join":
                    fn.has_any_join = True
                    if isinstance(stmt.func.value, ast.Name):
                        fn.joined_names.add(stmt.func.value.id)
                self._record_mutation_call(fn, stmt, bool(held))
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.Delete)):
                self._record_mutations(fn, stmt, bool(held))
            for child in ast.iter_child_nodes(stmt):
                visit(child)

        for stmt in node.body:
            visit(stmt)

        # `x.daemon = True` before start() upgrades that variable's sites
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Assign)
                and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Attribute)
                and sub.targets[0].attr == "daemon"
                and isinstance(sub.value, ast.Constant)
                and sub.value.value is True
            ):
                recv = sub.targets[0].value
                if isinstance(recv, ast.Name):
                    fn.daemon_names.add(recv.id)
                else:  # `self._t.daemon = True` — attribute the whole scope
                    for site in fn.threads:
                        site.daemon = True
        # join/daemon attribution: per-variable when the thread is bound to a
        # name (an unrelated join must not mask a leaked sibling thread);
        # scope-wide fallback only for unnamed creations (comprehensions,
        # `threads = [...]` lists joined through a loop variable)
        for site in fn.threads:
            if site.assigned_to is not None:
                site.joined = site.assigned_to in fn.joined_names
                site.daemon = site.daemon or site.assigned_to in fn.daemon_names
            else:
                site.joined = fn.has_any_join
        return fn

    def _record_call(
        self,
        fn: _FuncInfo,
        call: ast.Call,
        held: List[Tuple[str, int]],
        cls: Optional[str],
    ) -> None:
        func = call.func
        callee: Optional[Tuple[str, str, str]] = None
        if isinstance(func, ast.Attribute):
            if (
                isinstance(func.value, ast.Name)
                and func.value.id in ("self", "cls")
                and cls is not None
            ):
                callee = ("method", cls, func.attr)
            elif isinstance(func.value, ast.Name):
                mod = self.info.import_modules.get(func.value.id)
                if mod is not None:
                    callee = ("func", mod, func.attr)
        elif isinstance(func, ast.Name):
            if func.id in self.info.import_funcs:
                mod, name = self.info.import_funcs[func.id]
                callee = ("func", mod, name)
            else:
                callee = ("func", self.info.short, func.id)
        if callee is not None:
            fn.calls.append(
                _CallSite(
                    held=tuple(ident for ident, _ in held),
                    callee=callee,
                    lineno=call.lineno,
                )
            )

    def _record_wait(
        self,
        fn: _FuncInfo,
        call: ast.Call,
        local_waitables: Dict[str, str],
        cls: Optional[str],
    ) -> None:
        func = call.func
        if not isinstance(func, ast.Attribute):
            return
        method = func.attr
        if method not in {"wait", "wait_for", "get", "join"}:
            return
        recv = func.value
        lock: Optional[LockDef] = None
        if isinstance(recv, ast.Name) and recv.id in local_waitables:
            lock = LockDef(
                scope=fn.qual, attr=recv.id, kind=local_waitables[recv.id],
                module=self.info.short, lineno=call.lineno,
            )
        elif isinstance(recv, ast.Attribute):
            attr = recv.attr
            self_attr = _self_attr(recv)
            if self_attr is not None and cls is not None:
                lock = LockDef(
                    scope=cls, attr=self_attr, kind="?", module=self.info.short,
                    lineno=call.lineno,
                )
            else:
                # `req.event.wait()`: terminal-attribute, resolved by the pass
                lock = LockDef(
                    scope="?", attr=attr, kind="?", module=self.info.short,
                    lineno=call.lineno,
                )
        if lock is None:
            return
        has_timeout = False
        # positional timeout slots: wait(timeout) is first; wait_for(pred,
        # timeout) and get(block, timeout) are SECOND — `q.get(True)` is the
        # block flag, still an unbounded wait; Queue.join() takes none
        if method in ("wait_for", "get"):
            pos = call.args[1:2]
        elif method == "wait":
            pos = call.args[:1]
        else:
            pos = []
        has_timeout = any(
            not (isinstance(a, ast.Constant) and a.value is None) for a in pos
        )
        for kw in call.keywords:
            if kw.arg == "timeout" and not (
                isinstance(kw.value, ast.Constant) and kw.value.value is None
            ):
                has_timeout = True
        fn.waits.append(
            _WaitSite(lock=lock, method=method, lineno=call.lineno, has_timeout=has_timeout)
        )

    def _record_mutations(
        self,
        fn: _FuncInfo,
        stmt: "ast.Assign | ast.AugAssign | ast.Delete",
        locked: bool,
    ) -> None:
        targets: List[ast.AST]
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, ast.AugAssign):
            targets = [stmt.target]
        else:
            targets = list(stmt.targets)

        def hit(target: ast.AST) -> None:
            if isinstance(target, ast.Tuple):
                for el in target.elts:
                    hit(el)
                return
            attr = _self_attr(target)
            if attr is None and isinstance(target, ast.Subscript):
                attr = _self_attr(target.value)
            if attr is None and isinstance(target, ast.Name):
                # module-global mutation inside a module-level function
                if fn.cls is None:
                    attr = f"<global>{target.id}"
            if attr is not None:
                fn.mutations.append(_Mutation(attr=attr, lineno=stmt.lineno, locked=locked))

        for target in targets:
            hit(target)

    def _record_mutation_call(self, fn: _FuncInfo, call: ast.Call, locked: bool) -> None:
        func = call.func
        if not isinstance(func, ast.Attribute) or func.attr not in _MUTATOR_METHODS:
            return
        recv = func.value
        attr = _self_attr(recv)
        if attr is None and isinstance(recv, ast.Subscript):
            attr = _self_attr(recv.value)
        if attr is None and isinstance(recv, ast.Name) and fn.cls is None:
            attr = f"<global>{recv.id}"
        if attr is not None:
            fn.mutations.append(_Mutation(attr=attr, lineno=call.lineno, locked=locked))


# ---------------------------------------------------------------------------
# context construction
# ---------------------------------------------------------------------------


def _load_modules(paths: "Optional[List[str]]" = None) -> List[_ModuleInfo]:
    out: List[_ModuleInfo] = []
    for rel in paths if paths is not None else RUNTIME_MODULES:
        path = rel if os.path.isabs(rel) else os.path.join(_REPO_ROOT, rel)
        if not os.path.exists(path):
            continue  # optional modules (sched lands with this PR; stay robust)
        short = os.path.splitext(os.path.basename(path))[0]
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        out.append(_ModuleParser(short, path, source).parse())
    return out


def build_runtime_context(paths: "Optional[List[str]]" = None) -> RuntimeAnalysisContext:
    return RuntimeAnalysisContext(_load_modules(paths))


def _canon(ctx: RuntimeAnalysisContext, ident: str, module: _ModuleInfo) -> Optional[str]:
    """Canonicalize a raw acquisition identity: condition aliases collapse to
    their mutex, `?attr.X` terminal-attribute refs resolve when unambiguous,
    unknown class attrs (non-lock `with`s, e.g. files) drop out."""
    if ident.startswith("?attr."):
        attr = ident[len("?attr."):]
        defs = [d for d in ctx.attr_index.get(attr, []) if d.kind != "event"]
        if len({d.ident for d in defs}) == 1:
            d = defs[0]
            canon = ctx.class_lock(d.scope, d.attr)
            return canon.ident if canon is not None else d.ident
        return None
    scope, _, attr = ident.partition(".")
    if scope == module.short or "." in attr:
        # module-global or local lock: already canonical
        return ident
    ld = ctx.class_lock(scope, attr)
    if ld is not None:
        return ld.ident
    if attr.endswith("[]"):
        return ident
    return None  # `with self.something:` that is not a known lock


# ---------------------------------------------------------------------------
# passes
# ---------------------------------------------------------------------------


class ConcurrencyPass:
    """One runtime-source lint pass (mirrors AnalysisPass, different ctx)."""

    code = "PWA100"
    title = ""

    def run(self, ctx: RuntimeAnalysisContext) -> List[Diagnostic]:
        raise NotImplementedError

    def diag(
        self,
        severity: Severity,
        message: str,
        *,
        module: _ModuleInfo,
        lineno: int,
        function: str = "",
        **details: Any,
    ) -> Optional[Diagnostic]:
        noqa = module.noqa_codes(lineno)
        if noqa is not None and (not noqa or self.code in noqa):
            return None
        line_text = (
            module.source_lines[lineno - 1]
            if 1 <= lineno <= len(module.source_lines)
            else None
        )
        return Diagnostic(
            code=self.code,
            severity=severity,
            message=message,
            node_kind="runtime",
            node_name=function,
            file=os.path.relpath(module.path, _REPO_ROOT)
            if module.path.startswith(_REPO_ROOT)
            else module.path,
            line=lineno,
            function=function,
            line_text=line_text,
            details=details,
        )


def _iter_funcs(ctx: RuntimeAnalysisContext) -> Iterator[Tuple[_ModuleInfo, Optional[_ClassInfo], _FuncInfo]]:
    for mod in ctx.modules:
        for fn in mod.functions.values():
            yield mod, None, fn
        for cls in mod.classes.values():
            for fn in cls.methods.values():
                yield mod, cls, fn


class LockOrderPass(ConcurrencyPass):
    """PWA101: cycles (and non-reentrant self-loops) in the lock-acquisition
    graph built from nested ``with`` blocks and interprocedural call closure."""

    code = "PWA101"
    title = "lock-order cycle"

    def build_graph(
        self, ctx: RuntimeAnalysisContext
    ) -> Dict[Tuple[str, str], List[Tuple[str, int, str]]]:
        """(outer, inner) -> [(file module, line, function)] acquisition edges."""
        edges: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}

        def add(outer: str, inner: str, mod: _ModuleInfo, line: int, qual: str) -> None:
            edges.setdefault((outer, inner), []).append((mod.short, line, qual))

        for mod, _cls, fn in _iter_funcs(ctx):
            for outer, inner, line in fn.edges:
                o = _canon(ctx, outer, mod)
                i = _canon(ctx, inner, mod)
                if o is not None and i is not None:
                    add(o, i, mod, line, fn.qual)
            for call in fn.calls:
                if not call.held:
                    continue
                callee = ctx._callee_info(call)
                if callee is None:
                    continue
                callee_mod = next(
                    (m for m in ctx.modules if m.short == callee.module), mod
                )
                inner_locks = {
                    _canon(ctx, a, callee_mod)
                    for a in ctx.acquire_closure(callee)
                }
                for outer in call.held:
                    o = _canon(ctx, outer, mod)
                    if o is None:
                        continue
                    for i in inner_locks:
                        # i == o is kept: calling a method that re-acquires a
                        # held non-reentrant lock is the self-deadlock case
                        if i is not None:
                            add(o, i, mod, call.lineno, fn.qual)
        return edges

    def run(self, ctx: RuntimeAnalysisContext) -> List[Diagnostic]:
        edges = self.build_graph(ctx)
        adj: Dict[str, Set[str]] = {}
        for (outer, inner), _sites in edges.items():
            adj.setdefault(outer, set()).add(inner)
        out: List[Diagnostic] = []
        # self-loops: re-acquiring a non-reentrant lock deadlocks immediately
        for (outer, inner), sites in sorted(edges.items()):
            if outer != inner:
                continue
            if self._is_rlock(ctx, outer):
                continue
            mod = next((m for m in ctx.modules if m.short == sites[0][0]), ctx.modules[0])
            d = self.diag(
                Severity.ERROR,
                f"non-reentrant lock {outer} is re-acquired while already held "
                "(direct or through the call chain): the thread deadlocks "
                "against itself",
                module=mod, lineno=sites[0][1], function=sites[0][2],
                lock=outer,
            )
            if d is not None:
                out.append(d)
        # cycles of length >= 2
        for cycle in self._cycles(adj):
            pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
            sites = [edges.get(p, [("?", 0, "?")])[0] for p in pairs]
            mod = next(
                (m for m in ctx.modules if m.short == sites[0][0]), ctx.modules[0]
            )
            where = "; ".join(
                f"{a}→{b} at {s[0]}.py:{s[1]} ({s[2]})" for (a, b), s in zip(pairs, sites)
            )
            d = self.diag(
                Severity.ERROR,
                "lock-order cycle: " + " → ".join(cycle + [cycle[0]]) + " — two "
                "threads taking these locks in opposite orders deadlock under "
                f"the wrong interleaving [{where}]",
                module=mod, lineno=sites[0][1], function=sites[0][2],
                cycle=cycle,
            )
            if d is not None:
                out.append(d)
        return out

    @staticmethod
    def _is_rlock(ctx: RuntimeAnalysisContext, ident: str) -> bool:
        scope, _, attr = ident.partition(".")
        for mod in ctx.modules:
            cls = mod.classes.get(scope)
            if cls is not None and attr in cls.lock_attrs:
                return cls.lock_attrs[attr].kind == "rlock"
            if mod.short == scope and attr in mod.global_locks:
                return mod.global_locks[attr].kind == "rlock"
        return False

    @staticmethod
    def _cycles(adj: Dict[str, Set[str]]) -> List[List[str]]:
        """Simple cycles (each reported once, rotated to its min node)."""
        seen: Set[Tuple[str, ...]] = set()
        out: List[List[str]] = []

        def dfs(start: str, node: str, path: List[str], visited: Set[str]) -> None:
            for nxt in sorted(adj.get(node, ())):
                if nxt == start and len(path) >= 2:
                    lo = path.index(min(path))
                    canon = tuple(path[lo:] + path[:lo])
                    if canon not in seen:
                        seen.add(canon)
                        out.append(list(canon))
                elif nxt not in visited and nxt > start:
                    visited.add(nxt)
                    dfs(start, nxt, path + [nxt], visited)
                    visited.discard(nxt)

        for start in sorted(adj):
            dfs(start, start, [start], {start})
        return out


class UnboundedWaitPass(ConcurrencyPass):
    """PWA102: ``Condition.wait``/``Event.wait``/``Queue.get`` with no timeout.
    The fence deadline, the supervisor's stall-killer, and teardown can only
    abort waits that periodically wake; an untimed wait is a wedge."""

    code = "PWA102"
    title = "unbounded blocking wait"

    def run(self, ctx: RuntimeAnalysisContext) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for mod, cls, fn in _iter_funcs(ctx):
            for site in fn.waits:
                if site.has_timeout:
                    continue
                kind = self._waitable_kind(ctx, site, cls)
                if kind is None or site.method not in _BLOCKING_METHODS.get(kind, ()):
                    continue
                d = self.diag(
                    Severity.ERROR,
                    f"{kind} {site.lock.scope}.{site.lock.attr}.{site.method}() "
                    "has no timeout: the epoch-fence deadline, the supervisor's "
                    "stall-killer, and shutdown cannot abort a wait that never "
                    "wakes — wait in a bounded loop and re-check the abort "
                    "condition",
                    module=mod, lineno=site.lineno, function=fn.qual,
                    primitive=kind, method=site.method,
                )
                if d is not None:
                    out.append(d)
        return out

    @staticmethod
    def _waitable_kind(
        ctx: RuntimeAnalysisContext, site: _WaitSite, cls: Optional[_ClassInfo]
    ) -> Optional[str]:
        lock = site.lock
        if lock.kind != "?":
            return lock.kind if lock.kind in _BLOCKING_METHODS else None
        if lock.scope != "?" and cls is not None:
            ld = ctx.class_lock(lock.scope, lock.attr)
            if ld is not None:
                return ld.kind if ld.kind in _BLOCKING_METHODS else None
            return None
        # terminal-attribute heuristic: `req.event.wait()` — the attr name
        # must resolve to primitives EVERYWHERE it is assigned, or the
        # receiver may be something else entirely
        if lock.attr in ctx.ambiguous_attrs:
            return None
        defs = ctx.attr_index.get(lock.attr, [])
        kinds = {d.kind for d in defs if d.kind in _BLOCKING_METHODS}
        if len(kinds) == 1:
            return next(iter(kinds))
        return None


class UnlockedSharedWritePass(ConcurrencyPass):
    """PWA103: an attribute written under a lock in one method and with no
    lock in another (inconsistent locking). Constructor-reachable-only code is
    exempt — no peer thread exists before ``__init__`` returns."""

    code = "PWA103"
    title = "shared-mutable write outside the owning lock"

    def run(self, ctx: RuntimeAnalysisContext) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for mod in ctx.modules:
            for cls in mod.classes.values():
                out.extend(self._check_class(ctx, mod, cls))
            # module-global equivalent over module-level functions
            guarded: Set[str] = set()
            for fn in mod.functions.values():
                for m in fn.mutations:
                    if m.attr.startswith("<global>") and m.locked:
                        guarded.add(m.attr)
            for fn in mod.functions.values():
                for m in fn.mutations:
                    if m.attr in guarded and not m.locked:
                        d = self.diag(
                            Severity.WARNING,
                            f"module global {m.attr[8:]!r} is written under a "
                            f"lock elsewhere but without one in {fn.qual}: "
                            "either every writer holds the lock or none "
                            "meaningfully does",
                            module=mod, lineno=m.lineno, function=fn.qual,
                            attr=m.attr[8:],
                        )
                        if d is not None:
                            out.append(d)
        return out

    def _check_class(
        self, ctx: RuntimeAnalysisContext, mod: _ModuleInfo, cls: _ClassInfo
    ) -> List[Diagnostic]:
        exempt = self._constructor_only(cls)
        guarded: Set[str] = set()
        for name, fn in cls.methods.items():
            if name.split(".")[0] in exempt:
                continue
            for m in fn.mutations:
                if m.locked:
                    guarded.add(m.attr)
        out: List[Diagnostic] = []
        for name, fn in cls.methods.items():
            if name.split(".")[0] in exempt:
                continue
            for m in fn.mutations:
                if m.attr in guarded and not m.locked:
                    d = self.diag(
                        Severity.WARNING,
                        f"{cls.name}.{m.attr} is written under a lock in other "
                        f"methods but without one in {fn.qual}: a concurrent "
                        "reader/writer can observe a torn update — hold the "
                        "owning lock here too (or mark the single-owner "
                        "convention with `# noqa: PWA103 (<why>)`)",
                        module=mod, lineno=m.lineno, function=fn.qual,
                        attr=m.attr, cls=cls.name,
                    )
                    if d is not None:
                        out.append(d)
        return out

    @staticmethod
    def _constructor_only(cls: _ClassInfo) -> Set[str]:
        """Methods reachable ONLY from ``__init__`` (and ``__init__`` itself):
        they run before any peer thread can exist, so unlocked writes there are
        single-threaded by construction. A method that escapes as a callback
        (``target=self._reader``) is never exempt."""
        callers: Dict[str, Set[str]] = {}
        for name, fn in cls.methods.items():
            base = name.split(".")[0]
            for call in fn.calls:
                if call.callee[0] == "method" and call.callee[1] == cls.name:
                    callers.setdefault(call.callee[2], set()).add(base)
        exempt: Set[str] = {"__init__"}
        changed = True
        while changed:
            changed = False
            for name in cls.methods:
                base = name.split(".")[0]
                if base in exempt or base in cls.escaped_methods:
                    continue
                who = callers.get(base)
                if who and who <= exempt:
                    exempt.add(base)
                    changed = True
        return exempt


class ThreadLifecyclePass(ConcurrencyPass):
    """PWA104: a thread that is neither daemon nor joined in its creating
    scope survives ``pw.run``/server teardown and wedges interpreter exit
    (non-daemon threads block process shutdown)."""

    code = "PWA104"
    title = "non-daemon thread with no join on the shutdown path"

    def run(self, ctx: RuntimeAnalysisContext) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for mod, _cls, fn in _iter_funcs(ctx):
            for site in fn.threads:
                if site.daemon or site.joined:
                    continue
                d = self.diag(
                    Severity.ERROR,
                    f"thread created in {fn.qual} is neither daemon=True nor "
                    "joined in this scope: it outlives run/teardown, holds its "
                    "resources, and blocks interpreter shutdown — pass "
                    "daemon=True (and make its loop abort-checked) or join it "
                    "on the shutdown path",
                    module=mod, lineno=site.lineno, function=fn.qual,
                )
                if d is not None:
                    out.append(d)
        return out


def default_concurrency_passes() -> List[ConcurrencyPass]:
    return [
        LockOrderPass(),
        UnboundedWaitPass(),
        UnlockedSharedWritePass(),
        ThreadLifecyclePass(),
    ]


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def analyze_runtime(
    paths: "Optional[List[str]]" = None,
    *,
    passes: "Optional[List[ConcurrencyPass]]" = None,
    ctx: "Optional[RuntimeAnalysisContext]" = None,
) -> AnalysisReport:
    """Run the PWA101–104 pipeline over the runtime modules (or ``paths``).
    Same report type as the graph lint: JSON shape, exit-code contract, and
    ``emit_telemetry`` all carry over."""
    from pathway_tpu.analysis.framework import run_runtime_passes

    if ctx is None:
        ctx = build_runtime_context(paths)
    if passes is None:
        passes = default_concurrency_passes()
    return run_runtime_passes(
        passes, ctx, family="concurrency",
        node_count=sum(1 for _ in _iter_funcs(ctx)),
    )


def analyze_source(source: str, name: str = "planted") -> AnalysisReport:
    """Lint one in-memory module (tests plant violations this way)."""
    info = _ModuleParser(name, f"<{name}>", source).parse()
    return analyze_runtime(ctx=RuntimeAnalysisContext([info]))


_cached_report: "Optional[AnalysisReport]" = None


def runtime_gate() -> None:
    """``PATHWAY_RUNTIME_LINT=off|warn|error`` (default ``off``): lint the
    runtime's own concurrency before a run. ``warn`` logs and mirrors counters;
    ``error`` refuses the run on any PWA101–104 error. The report is cached
    process-wide — the runtime source cannot change under a live process."""
    from pathway_tpu.analysis.framework import enforce_gate, gate_mode

    mode = gate_mode("PATHWAY_RUNTIME_LINT")
    if mode is None:
        return
    global _cached_report
    if _cached_report is None:
        _cached_report = analyze_runtime()
    enforce_gate(_cached_report, mode)
