"""The five shipped graph-lint passes (PWA001–PWA005).

Each pass walks the parsed operator DAG statically — no evaluator is
instantiated, no source polled — so the analyzer is safe to run at graph build
time, in CI (``pathway_tpu.cli analyze``), and before every ``pw.run``.
"""

from __future__ import annotations

import dis
import functools
import types
from typing import Any, Dict, Iterator, List, Set, Tuple

from pathway_tpu.analysis.framework import (
    AnalysisContext,
    AnalysisPass,
    Diagnostic,
    Severity,
)
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals import parse_graph as pg


# ---------------------------------------------------------------------------
# PWA001 — determinism: bytecode inspection of apply/UDF callables
# ---------------------------------------------------------------------------

# module -> attributes whose call yields a different value per invocation
_NONDET_MODULE_ATTRS: Dict[str, Set[str]] = {
    "time": {
        "time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
        "perf_counter_ns", "process_time", "process_time_ns", "clock_gettime",
        "localtime", "gmtime", "ctime", "asctime",
    },
    "random": {
        "random", "randint", "randrange", "getrandbits", "uniform", "choice",
        "choices", "sample", "shuffle", "gauss", "normalvariate", "betavariate",
        "expovariate", "triangular", "vonmisesvariate", "paretovariate",
        "weibullvariate", "lognormvariate", "randbytes", "seed",
    },
    "uuid": {"uuid1", "uuid4"},
    "secrets": {"token_bytes", "token_hex", "token_urlsafe", "randbelow", "choice", "randbits"},
    "os": {"urandom", "getpid", "times"},
    "datetime": {"now", "utcnow", "today"},
}

# names that are nondeterministic when loaded as bare globals
# (``from time import time`` / ``from random import random`` style imports)
_NONDET_DIRECT: Set[str] = {
    "time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
    "perf_counter_ns", "urandom", "uuid1", "uuid4", "getrandbits",
    "token_bytes", "token_hex", "token_urlsafe", "randint", "randrange",
    "shuffle", "gauss", "uniform", "randbytes",
}

_MUTATOR_METHODS: Set[str] = {
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "remove", "discard", "clear", "setdefault", "sort", "reverse",
    "__setitem__", "__delitem__", "appendleft", "extendleft",
}

_ATTR_OPS = {"LOAD_ATTR", "LOAD_METHOD"}


def _unwrap_callable(fn: Any) -> Any:
    """Follow wrapper chains down to the code-bearing user callable."""
    seen: Set[int] = set()
    while id(fn) not in seen:
        seen.add(id(fn))
        if isinstance(fn, functools.partial):
            fn = fn.func
            continue
        wrapped = getattr(fn, "__wrapped__", None)
        if wrapped is not None and wrapped is not fn:
            fn = wrapped
            continue
        break
    if not hasattr(fn, "__code__"):
        call = getattr(fn, "__call__", None)
        inner = getattr(call, "__func__", call)
        if hasattr(inner, "__code__"):
            return inner
    return fn


def _code_objects(code: types.CodeType) -> Iterator[types.CodeType]:
    """The code object and every nested one (lambdas, comprehensions)."""
    yield code
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            yield from _code_objects(const)


def _nondet_value(value: Any, attr: "str | None") -> "str | None":
    """Classify a resolved global/closure value (module, function, class) as a
    nondeterminism source; returns a human-readable ``what`` or None."""
    if value is None:
        return None
    if isinstance(value, types.ModuleType):
        mod = value.__name__
        if attr is not None:
            if attr in _NONDET_MODULE_ATTRS.get(mod, ()):
                return f"{mod}.{attr}()"
            if mod == "numpy" and attr == "random":
                return "numpy.random.*"
        return None
    if isinstance(value, type):  # e.g. datetime.datetime.now()
        if getattr(value, "__module__", "") == "datetime" and attr in (
            "now", "utcnow", "today",
        ):
            return f"datetime.{value.__name__}.{attr}()"
        return None
    # direct function reference (``from time import time``, bound random methods)
    mod = getattr(value, "__module__", None)
    name = getattr(value, "__name__", None)
    if mod in _NONDET_MODULE_ATTRS and name in _NONDET_MODULE_ATTRS[mod]:
        return f"{mod}.{name}()"
    if mod == "nt" or mod == "posix":  # os.urandom is implemented in posix/nt
        if name == "urandom":
            return "os.urandom()"
    return None


def _nondet_chain(value: Any, attrs: "Tuple[str, ...]") -> "str | None":
    """Classify ``value.attrs[0].attrs[1]...`` by resolving the attribute chain
    step by step — catches ``datetime.datetime.now()`` (two attrs deep from the
    module) as well as ``time.time()`` (one) and ``from time import time``
    direct references (zero)."""
    what = _nondet_value(value, attrs[0] if attrs else None)
    if what is not None:
        return what
    if attrs and isinstance(value, (types.ModuleType, type)):
        try:
            step = getattr(value, attrs[0])
        except Exception:
            return None
        return _nondet_chain(step, attrs[1:])
    return None


def _scan_callable(fn: Any) -> List[Tuple[str, str]]:
    """(reason_kind, what) findings for one callable's bytecode tree.

    Global and closure loads are resolved to their actual values where
    possible, so ``import random`` at any enclosing scope is caught and a user
    function merely *named* ``random`` is not; unresolvable names fall back to
    name matching."""
    code = fn.__code__
    fn_globals: Dict[str, Any] = getattr(fn, "__globals__", {})
    closure_values: Dict[str, Any] = {}
    for name, cell in zip(code.co_freevars, getattr(fn, "__closure__", None) or ()):
        try:
            closure_values[name] = cell.cell_contents
        except ValueError:
            pass  # not yet filled (self-referential defs)

    def resolve(opname: str, name: str) -> Tuple[Any, bool]:
        """(value, resolved) for a LOAD_GLOBAL/LOAD_DEREF name."""
        if opname == "LOAD_GLOBAL":
            if name in fn_globals:
                return fn_globals[name], True
            builtins = fn_globals.get("__builtins__")
            bdict = (
                builtins if isinstance(builtins, dict) else getattr(builtins, "__dict__", {})
            )
            if name in bdict:
                return bdict[name], True
            return None, False
        if name in closure_values:
            return closure_values[name], True
        return None, False

    findings: List[Tuple[str, str]] = []
    for co in _code_objects(code):
        instrs = list(dis.get_instructions(co))
        freevars = set(co.co_freevars)
        for i, ins in enumerate(instrs):
            nxt = instrs[i + 1] if i + 1 < len(instrs) else None
            if ins.opname in ("LOAD_GLOBAL", "LOAD_DEREF"):
                name = ins.argval
                # consecutive attribute loads form one access chain
                # (``datetime.datetime.now`` is LOAD_GLOBAL + two LOAD_ATTRs)
                attrs: List[str] = []
                j = i + 1
                while (
                    j < len(instrs)
                    and instrs[j].opname in _ATTR_OPS
                    and len(attrs) < 3
                ):
                    attrs.append(instrs[j].argval)
                    j += 1
                attr = attrs[0] if attrs else None
                # nested code objects share the top callable's globals; their
                # own cells are unresolvable statically and fall back to names
                value, resolved = resolve(ins.opname, name)
                if resolved:
                    what = _nondet_chain(value, tuple(attrs))
                    if what is not None:
                        findings.append(("nondet_call", what))
                elif name in _NONDET_MODULE_ATTRS and attr is not None:
                    if attr in _NONDET_MODULE_ATTRS[name]:
                        findings.append(("nondet_call", f"{name}.{attr}()"))
                elif name in _NONDET_DIRECT and ins.opname == "LOAD_GLOBAL":
                    findings.append(("nondet_call", f"{name}()"))
            if ins.opname == "STORE_GLOBAL":
                findings.append(("global_mutation", f"writes global {ins.argval!r}"))
            elif ins.opname == "DELETE_GLOBAL":
                findings.append(("global_mutation", f"deletes global {ins.argval!r}"))
            elif ins.opname == "STORE_DEREF" and ins.argval in freevars:
                findings.append(
                    ("nonlocal_mutation", f"rebinds closed-over {ins.argval!r}")
                )
            elif (
                ins.opname == "LOAD_DEREF"
                and ins.argval in freevars
                and nxt is not None
                and nxt.opname in _ATTR_OPS
                and nxt.argval in _MUTATOR_METHODS
            ):
                findings.append(
                    (
                        "closure_mutation",
                        f"mutates closed-over {ins.argval!r} via .{nxt.argval}()",
                    )
                )
            elif ins.opname == "STORE_SUBSCR" and i >= 2:
                # ``container[key] = value`` pushes value, container, key: the
                # CONTAINER load sits two instructions back when the key is a
                # single load. Matching the exact position (not "any deref
                # nearby") keeps a local dict indexed by a closed-over KEY from
                # being flagged; multi-instruction keys are conservatively
                # skipped — an error-severity false positive blocks CI.
                prev = instrs[i - 2]
                if prev.opname == "LOAD_DEREF" and prev.argval in freevars:
                    findings.append(
                        (
                            "closure_mutation",
                            f"item-assigns into closed-over {prev.argval!r}",
                        )
                    )
    return findings


_REASON_TEXT = {
    "nondet_call": "calls a nondeterministic source",
    "global_mutation": "mutates global state",
    "nonlocal_mutation": "mutates enclosing-scope state",
    "closure_mutation": "mutates state captured in its closure",
}


class DeterminismPass(AnalysisPass):
    """PWA001: a UDF whose bytecode reaches ``time``/``random``/``uuid``/
    ``os.urandom`` — or mutates global/closure state — produces different
    values on a journal/checkpoint replay, silently breaking the bit-identical
    recovery contract every rung of the failure ladder depends on."""

    code = "PWA001"
    title = "nondeterministic or stateful UDF"

    def __init__(self) -> None:
        # one bytecode scan per distinct callable per analysis run, not per
        # apply site: a shared UDF selected in hundreds of nodes scans once
        # (keyed by id(fn); the stored fn reference keeps the id stable)
        self._scan_cache: Dict[int, Tuple[Any, List[Tuple[str, str]]]] = {}

    def _findings(self, fn: Any) -> List[Tuple[str, str]]:
        got = self._scan_cache.get(id(fn))
        if got is not None and got[0] is fn:
            return got[1]
        findings = _scan_callable(fn)
        self._scan_cache[id(fn)] = (fn, findings)
        return findings

    def run(self, ctx: AnalysisContext) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for node in ctx.nodes:
            seen: Set[Tuple[str, str, str]] = set()
            for _root, apply_e in ctx.apply_expressions(node):
                fn = getattr(apply_e, "_source_fun", None) or apply_e._fun
                fn = _unwrap_callable(fn)
                if getattr(fn, "__code__", None) is None:
                    continue  # builtins / C callables: nothing to inspect
                fn_name = getattr(fn, "__name__", "<udf>")
                for kind, what in self._findings(fn):
                    key = (fn_name, kind, what)
                    if key in seen:
                        continue
                    seen.add(key)
                    deterministic = bool(getattr(apply_e, "_deterministic", False))
                    out.append(
                        self.diag(
                            Severity.ERROR,
                            f"UDF {fn_name!r} {_REASON_TEXT[kind]} ({what}); its "
                            "output cannot be reproduced by a journal replay, so "
                            "recovery and rejoin would silently diverge"
                            + (
                                " (declared deterministic=True, which replay "
                                "relies on)"
                                if deterministic
                                else ""
                            ),
                            node,
                            udf=fn_name,
                            reason=kind,
                            what=what,
                        )
                    )
        return out


# ---------------------------------------------------------------------------
# PWA002 — rewind safety: propagate REWIND_SAFE through the DAG
# ---------------------------------------------------------------------------


class RewindSafetyPass(AnalysisPass):
    """PWA002: drain-sensitive operators (``REWIND_SAFE=False`` on the
    evaluator class) disable the cheapest recovery rung — incremental rewind —
    for the whole graph. Under persistence this is a build-time warning instead
    of a mis-fired rung discovered during a failover."""

    code = "PWA002"
    title = "drain-sensitive operator disables incremental rewind"

    def run(self, ctx: AnalysisContext) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        unsafe = [
            node
            for node in ctx.nodes
            if not getattr(ctx.evaluator_class(node) or object, "REWIND_SAFE", True)
        ]
        if not unsafe:
            return out
        severity = Severity.WARNING if ctx.persistence else Severity.INFO
        unsafe_ids = {n.id for n in unsafe}
        for node in unsafe:
            # every node downstream of an unsafe one recovers through rung 2+
            affected = sum(
                1 for n in ctx.nodes if node.id in ctx.upstream_ids(n)
            )
            out.append(
                self.diag(
                    severity,
                    f"operator {node.kind!r} is not rewind-safe: a fenced "
                    "survivor cannot undo an interrupted commit in place, so "
                    "recovery skips the incremental-rewind rung and pays a "
                    "checkpoint + tail replay instead"
                    + (
                        ""
                        if ctx.persistence
                        else " (informational: persistence is not enabled)"
                    ),
                    node,
                    downstream_operators=affected,
                    rewind_unsafe_total=len(unsafe_ids),
                )
            )
        return out


# ---------------------------------------------------------------------------
# PWA003 — unbounded state: stateful operators over unbounded streams
# ---------------------------------------------------------------------------

# kinds whose evaluator accumulates state per distinct key/row, growing without
# bound when fed an unbounded stream with no forget/TTL upstream
_STATEFUL_KINDS: Dict[str, str] = {
    "groupby": "per-group aggregates",
    "join": "both sides' matched rows",
    "deduplicate": "the last row of every key",
    "sort": "the full sorted key set",
    "sorted_index": "one tree node per row",
    "stateful_reduce": "per-key accumulator state",
    "gradual_broadcast": "per-row threshold positions",
}

_FORGETTING_KINDS = frozenset({"forget"})


class UnboundedStatePass(AnalysisPass):
    """PWA003: a stateful evaluator fed by an unbounded streaming source with
    no ``forget``/TTL operator on the path accumulates state forever — the
    process OOMs eventually; windows want a temporal behavior (cutoff/delay)
    that compiles to a forget upstream."""

    code = "PWA003"
    title = "unbounded state over an unbounded stream"

    def _unbounded_inputs(self, ctx: AnalysisContext) -> List[pg.Node]:
        from pathway_tpu.engine.datasource import StreamingDataSource

        out = []
        for node in ctx.nodes:
            if not isinstance(node, pg.InputNode):
                continue
            # static/batch-mode connectors ride a StreamingDataSource too but
            # declare themselves bounded on the node (fs.read mode="static")
            if not node.config.get("streaming", True):
                continue
            source = node.config.get("source")
            if isinstance(source, StreamingDataSource) and not getattr(
                source, "loopback", False
            ):
                out.append(node)
        return out

    def run(self, ctx: AnalysisContext) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        unbounded = self._unbounded_inputs(ctx)
        if not unbounded:
            return out
        forgetters = [n for n in ctx.nodes if n.kind in _FORGETTING_KINDS]
        for node in ctx.nodes:
            what = _STATEFUL_KINDS.get(node.kind)
            if what is None and node.kind == "external_index":
                # live re-answered queries (asof_now=False) pin every query row
                if node.config.get("asof_now", True):
                    continue
                what = "every live query for re-answering"
            if what is None:
                continue
            ups = ctx.upstream_ids(node)
            feeding = [src for src in unbounded if src.id in ups]
            if not feeding:
                continue
            # a source is bounded only when EVERY path from it to this node
            # passes through a forget: walk backward from the node, refusing to
            # expand through forget nodes — any source still reached has a
            # forget-free path and feeds unbounded rows (a forget on a sibling
            # branch of a join must not mask the uncovered branch)
            forget_ids = {f.id for f in forgetters}
            reachable: Set[int] = set()
            stack = list(node.inputs)
            while stack:
                producer = stack.pop()._node
                if producer.id in reachable or producer.id in forget_ids:
                    continue
                reachable.add(producer.id)
                stack.extend(producer.inputs)
            uncovered = [src for src in feeding if src.id in reachable]
            if not uncovered:
                continue
            out.append(
                self.diag(
                    Severity.WARNING,
                    f"stateful operator {node.kind!r} keeps {what} and is fed "
                    f"by unbounded streaming source(s) "
                    f"{sorted(s.id for s in uncovered)} with no forget/TTL "
                    "upstream: its state grows without bound; add a temporal "
                    "behavior (cutoff) or ``_forget`` upstream, or feed it a "
                    "bounded source",
                    node,
                    sources=sorted(s.id for s in uncovered),
                )
            )
        return out


# ---------------------------------------------------------------------------
# PWA004 — device placement: dtype propagation + device kwarg consistency
# ---------------------------------------------------------------------------


class DevicePlacementPass(AnalysisPass):
    """PWA004: (a) a host Python UDF embedded inside a numeric expression tree
    splits what would lower to ONE jitted XLA kernel into device→host→device
    round-trips every commit; (b) KNN/embed stores configured with differing
    ``device=`` kwargs ping-pong batches between devices at every handoff.

    Since the whole-commit fusion compiler landed
    (``pathway_tpu/analysis/fusion.py`` + ``engine/fusion.py``), this is no
    longer a hypothetical: the SAME analysis decides fusion-region boundaries,
    so every PWA004 warning is a real lost-performance report — the flagged
    UDF is precisely what breaks an operator chain out of its fused XLA
    program and back onto per-node host dispatch."""

    code = "PWA004"
    title = "host/device placement hazard"

    def run(self, ctx: AnalysisContext) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        out.extend(self._pingpong(ctx))
        out.extend(self._device_kwargs(ctx))
        return out

    def _pingpong(self, ctx: AnalysisContext) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for node in ctx.nodes:
            flagged: Set[int] = set()
            for root in ctx.expressions(node):
                for e in ctx.expr_tree(root):
                    if not isinstance(
                        e,
                        (expr.ColumnBinaryOpExpression, expr.ColumnUnaryOpExpression),
                    ):
                        continue
                    for sub in ctx.expr_tree(e):
                        if sub is e or not isinstance(sub, expr.ApplyExpression):
                            continue
                        if id(sub) in flagged:
                            continue
                        args = sub._args + tuple(sub._kwargs.values())
                        if not args:
                            continue
                        if not all(
                            ctx.is_device_dtype(ctx.infer_dtype(a)) for a in args
                        ):
                            continue
                        if not ctx.is_device_dtype(sub._return_type):
                            continue
                        flagged.add(id(sub))
                        fn = getattr(sub, "_source_fun", None) or sub._fun
                        fn_name = getattr(
                            _unwrap_callable(fn), "__name__", "<udf>"
                        )
                        out.append(
                            self.diag(
                                Severity.WARNING,
                                f"host UDF {fn_name!r} sits inside a numeric "
                                "expression chain whose surrounding ops lower "
                                "to one fused device kernel: every commit pays "
                                "a device→host→device round-trip; hoist the "
                                "UDF out of the numeric chain or express it "
                                "with column operators",
                                node,
                                udf=fn_name,
                            )
                        )
            del flagged
        return out

    def _device_kwargs(self, ctx: AnalysisContext) -> List[Diagnostic]:
        placements: List[Tuple[pg.Node, Any]] = []

        from pathway_tpu.internals.table import Table

        def probe(node: pg.Node, value: Any, depth: int = 0) -> None:
            # a Table column named "device" is a ColumnReference, not a placement
            if depth > 3 or isinstance(value, (expr.ColumnExpression, pg.Node, Table)):
                return
            if isinstance(value, dict):
                for v in value.values():
                    probe(node, v, depth + 1)
                return
            if isinstance(value, (list, tuple)):
                for v in value:
                    probe(node, v, depth + 1)
                return
            if isinstance(value, (str, bytes, int, float, bool, type(None), type)):
                return
            if isinstance(value, types.ModuleType) or callable(value):
                return
            device = getattr(value, "device", None)
            if device is not None and not isinstance(device, property):
                placements.append((node, device))

        for node in ctx.nodes:
            probe(node, node.config)
        distinct = {str(d) for _, d in placements}
        if len(distinct) <= 1:
            return []
        return [
            self.diag(
                Severity.WARNING,
                f"store/operator pinned to device {d!s} while other operators "
                f"in this graph use {sorted(distinct - {str(d)})}: batches "
                "ping-pong between devices at every handoff; pin all stores "
                "of one pipeline to one device (or shard explicitly)",
                node,
                device=str(d),
                devices_in_graph=sorted(distinct),
            )
            for node, d in placements
        ]


# ---------------------------------------------------------------------------
# PWA005 — checkpoint compatibility under persistence
# ---------------------------------------------------------------------------


class CheckpointCompatibilityPass(AnalysisPass):
    """PWA005: under persistence, operators whose state sits outside the
    snapshot protocol (``SNAPSHOT_CAPTURE=False``) abort or silently weaken
    checkpoints, and sources with no resumable offset state re-ingest rows on
    resume. Quiet when persistence is off — nothing is promised then."""

    code = "PWA005"
    title = "operator/source incompatible with checkpointing"

    def run(self, ctx: AnalysisContext) -> List[Diagnostic]:
        if not ctx.persistence:
            return []
        from pathway_tpu.engine.datasource import DataSource

        out: List[Diagnostic] = []
        for node in ctx.nodes:
            cls = ctx.evaluator_class(node)
            if cls is not None and not getattr(cls, "SNAPSHOT_CAPTURE", True):
                out.append(
                    self.diag(
                        Severity.ERROR,
                        f"operator {node.kind!r} holds state outside the "
                        "snapshot protocol (device-resident or externally "
                        "mutated): a cluster checkpoint either aborts "
                        "(UnpicklableStateError) or restores without it; "
                        "recovery falls back to full journal replay — disable "
                        "checkpoint compaction or keep this operator out of "
                        "persistence-enabled graphs",
                        node,
                        evaluator=cls.__name__,
                    )
                )
            if isinstance(node, pg.InputNode):
                source = node.config.get("source")
                if source is None:
                    continue
                if type(source).offset_state is DataSource.offset_state:
                    out.append(
                        self.diag(
                            Severity.WARNING,
                            f"input source {type(source).__name__!r} has no "
                            "resumable offset state: a persistence resume "
                            "cannot tell which rows were already journaled and "
                            "will re-ingest them; implement "
                            "``offset_state``/``restore``",
                            node,
                            source=type(source).__name__,
                        )
                    )
        return out


def default_passes() -> List[AnalysisPass]:
    return [
        DeterminismPass(),
        RewindSafetyPass(),
        UnboundedStatePass(),
        DevicePlacementPass(),
        CheckpointCompatibilityPass(),
    ]
