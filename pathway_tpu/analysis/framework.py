"""Graph-lint pass framework: diagnostics, analysis context, pass manager.

A build-time static-analysis layer over the operator DAG in
``internals/parse_graph.py``. Passes walk the parsed graph (never the running
engine) and emit structured :class:`Diagnostic` records carrying the code,
severity, message, and the user source location captured at operator-creation
time (``internals/trace.py`` — the same frame that annotates runtime errors).

The DAG walk, consumer maps, and dtype helpers here are deliberately
evaluator-independent so ROADMAP item 3's whole-commit XLA fusion compiler can
reuse them for partitioning decisions instead of re-deriving the graph shape.
"""

from __future__ import annotations

import enum
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Set, Tuple

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals import parse_graph as pg


class Severity(enum.IntEnum):
    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:
        return self.name.lower()


@dataclass(frozen=True)
class Diagnostic:
    """One structured finding: code + severity + message + user source location."""

    code: str
    severity: Severity
    message: str
    node_id: int = -1
    node_kind: str = ""
    node_name: str = ""
    file: Optional[str] = None
    line: Optional[int] = None
    function: Optional[str] = None
    line_text: Optional[str] = None
    details: Dict[str, Any] = field(default_factory=dict, compare=False)

    @property
    def location(self) -> str:
        if self.file is None:
            return ""
        return f"{self.file}:{self.line}" if self.line is not None else self.file

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "code": self.code,
            "severity": str(self.severity),
            "message": self.message,
            "node_id": self.node_id,
            "node_kind": self.node_kind,
            "node_name": self.node_name,
        }
        if self.file is not None:
            out["file"] = self.file
            out["line"] = self.line
            out["function"] = self.function
        if self.details:
            out["details"] = self.details
        return out

    def format(self) -> str:
        where = f" [{self.location}]" if self.location else ""
        node = (
            f" node#{self.node_id}({self.node_kind})" if self.node_id >= 0 else ""
        )
        text = f"{self.code} {self.severity}{node}: {self.message}{where}"
        if self.line_text:
            text += f"\n    {self.line_text.strip()}"
        return text


def _diag_from_node(
    code: str, severity: Severity, message: str, node: "pg.Node | None", **details: Any
) -> Diagnostic:
    frame = getattr(node, "user_frame", None) if node is not None else None
    return Diagnostic(
        code=code,
        severity=severity,
        message=message,
        node_id=node.id if node is not None else -1,
        node_kind=node.kind if node is not None else "",
        node_name=getattr(node, "name", "") if node is not None else "",
        file=frame.filename if frame is not None else None,
        line=frame.line_number if frame is not None else None,
        function=frame.function if frame is not None else None,
        line_text=frame.line if frame is not None else None,
        details=details,
    )


_DEVICE_DTYPES = (dt.INT, dt.FLOAT, dt.BOOL)


class AnalysisContext:
    """Shared graph view handed to every pass: nodes, edges, expression and
    dtype helpers. Built once per analysis run; passes must not mutate it."""

    def __init__(self, graph: Any, *, persistence: bool = False):
        self.graph = graph
        self.nodes: List[pg.Node] = list(graph.nodes)
        self.persistence = persistence
        # consumer edges (node.id -> nodes reading its output table)
        self._consumers: Dict[int, List[pg.Node]] = {}
        for node in self.nodes:
            for table in node.inputs:
                self._consumers.setdefault(table._node.id, []).append(node)
        self._upstream_cache: Dict[int, Set[int]] = {}

    # -- DAG helpers ---------------------------------------------------------

    def consumers(self, node: pg.Node) -> List[pg.Node]:
        return self._consumers.get(node.id, [])

    def producers(self, node: pg.Node) -> List[pg.Node]:
        return [t._node for t in node.inputs]

    def upstream_ids(self, node: pg.Node) -> Set[int]:
        """All transitive producer node ids of ``node`` (excluding itself)."""
        got = self._upstream_cache.get(node.id)
        if got is not None:
            return got
        out: Set[int] = set()
        stack = [t._node for t in node.inputs]
        while stack:
            up = stack.pop()
            if up.id in out:
                continue
            out.add(up.id)
            stack.extend(t._node for t in up.inputs)
        self._upstream_cache[node.id] = out
        return out

    def evaluator_class(self, node: pg.Node) -> "type | None":
        from pathway_tpu.engine.evaluators import EVALUATORS

        return EVALUATORS.get(type(node))

    # -- expression helpers --------------------------------------------------

    # operator kinds whose config embeds a NESTED graph's tables/expressions;
    # their inner expressions are analyzed through the inner graph, not here
    NESTED_KINDS = frozenset(
        {"iterate", "iterate_result", "row_transformer", "row_transformer_result"}
    )

    def expressions(self, node: pg.Node) -> Iterator[expr.ColumnExpression]:
        """Every ColumnExpression in the node's config (top-level, not subtrees)."""
        if node.kind in self.NESTED_KINDS:
            return
        seen: Set[int] = set()

        def walk(value: Any) -> Iterator[expr.ColumnExpression]:
            if isinstance(value, expr.ColumnExpression):
                if id(value) not in seen:
                    seen.add(id(value))
                    yield value
            elif isinstance(value, dict):
                for v in value.values():
                    yield from walk(v)
            elif isinstance(value, (list, tuple)):
                for v in value:
                    yield from walk(v)

        yield from walk(node.config)

    @staticmethod
    def expr_tree(root: expr.ColumnExpression) -> Iterator[expr.ColumnExpression]:
        """The expression and all its subexpressions, preorder."""
        stack = [root]
        while stack:
            e = stack.pop()
            yield e
            stack.extend(e._deps())

    def apply_expressions(
        self, node: pg.Node
    ) -> Iterator[Tuple[expr.ColumnExpression, expr.ApplyExpression]]:
        """(root expression, apply subexpression) pairs for every UDF call site."""
        for root in self.expressions(node):
            for e in self.expr_tree(root):
                if isinstance(e, expr.ApplyExpression):
                    yield root, e

    def infer_dtype(self, e: expr.ColumnExpression) -> dt.DType:
        from pathway_tpu.internals.type_interpreter import infer_dtype

        try:
            return infer_dtype(e)
        except Exception:
            return dt.ANY

    def is_device_dtype(self, dtype: Any) -> bool:
        """Device-friendly scalar dtypes: the expression evaluator lowers pure
        numeric trees over these to one jitted XLA kernel."""
        return any(dtype == d for d in _DEVICE_DTYPES)


class AnalysisPass:
    """One lint pass. Subclasses set ``code``/``title`` and implement ``run``."""

    code: str = "PWA000"
    title: str = ""

    def run(self, ctx: AnalysisContext) -> List[Diagnostic]:
        raise NotImplementedError

    def diag(
        self,
        severity: Severity,
        message: str,
        node: "pg.Node | None" = None,
        **details: Any,
    ) -> Diagnostic:
        return _diag_from_node(self.code, severity, message, node, **details)


class AnalysisReport:
    """All diagnostics of one analyzer run plus per-pass timings."""

    def __init__(
        self,
        diagnostics: List[Diagnostic],
        *,
        node_count: int = 0,
        pass_seconds: "Dict[str, float] | None" = None,
        pass_checked: "Dict[str, bool] | None" = None,
    ):
        self.diagnostics = diagnostics
        self.node_count = node_count
        self.pass_seconds = pass_seconds or {}
        # per-pass "did it actually run": a crashed pass reports False so the
        # lost coverage is machine-visible in the JSON output, not just a
        # "NOT being checked" warning a CI grep can miss
        self.pass_checked = (
            pass_checked
            if pass_checked is not None
            else {code: True for code in self.pass_seconds}
        )

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.ERROR]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.WARNING]

    @property
    def infos(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.INFO]

    def by_code(self, code: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.code == code]

    def exit_code(self, *, strict: bool = False) -> int:
        """CI contract: 0 clean, 1 warnings-only, 2 errors; ``strict`` promotes
        warnings to the error exit."""
        if self.errors:
            return 2
        if self.warnings:
            return 2 if strict else 1
        return 0

    def summary_line(self) -> str:
        return (
            f"graph lint: {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s), {len(self.infos)} info "
            f"over {self.node_count} operator(s)"
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "summary": {
                "errors": len(self.errors),
                "warnings": len(self.warnings),
                "info": len(self.infos),
                "nodes": self.node_count,
                "pass_seconds": {k: round(v, 6) for k, v in self.pass_seconds.items()},
                "checked": dict(sorted(self.pass_checked.items())),
            },
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True, default=str)

    # -- telemetry mirroring (PR-5 metrics plane) ----------------------------

    def emit_telemetry(self) -> None:
        """Mirror counts into the stage counters and the flight recorder so a
        post-mortem dump can say "this graph ran with N known lint errors"."""
        from pathway_tpu.engine import telemetry
        from pathway_tpu.engine.profile import get_flight_recorder

        updates: Dict[str, float] = {
            "lint.runs": 1.0,
            "lint.errors": float(len(self.errors)),
            "lint.warnings": float(len(self.warnings)),
        }
        codes: Dict[str, int] = {}
        for d in self.diagnostics:
            codes[d.code] = codes.get(d.code, 0) + 1
        for code, count in codes.items():
            updates[f"lint.diag.{code}"] = float(count)
        telemetry.stage_add_many(updates)
        recorder = get_flight_recorder()
        if self.diagnostics:
            recorder.record_event(
                "lint",
                errors=len(self.errors),
                warnings=len(self.warnings),
                codes=codes,
            )


class GraphLintError(Exception):
    """``PATHWAY_LINT=error``: the graph carries error-severity diagnostics and
    the run was refused before the first commit."""

    def __init__(self, report: AnalysisReport):
        self.report = report
        lines = [report.summary_line()]
        lines += [d.format() for d in report.errors]
        lines.append("set PATHWAY_LINT=warn (or off) to run anyway")
        super().__init__("\n".join(lines))


def run_runtime_passes(passes: List[Any], ctx: Any, *, family: str, node_count: int) -> AnalysisReport:
    """Shared pass-runner for the runtime lint families (PWA10x concurrency,
    PWA20x resources): per-pass timings + ``checked`` flags, the crashed-pass
    "NOT being checked" WARNING (a silently-dead pass must not report the tree
    clean — exit 1, 2 under --strict), and the severity/code/location sort."""
    diagnostics: List[Diagnostic] = []
    timings: Dict[str, float] = {}
    checked: Dict[str, bool] = {}
    for p in passes:
        t0 = time.perf_counter()
        try:
            found = p.run(ctx)
            checked[p.code] = True
        except Exception as exc:
            found = [
                Diagnostic(
                    code=p.code,
                    severity=Severity.WARNING,
                    message=(
                        f"{family} pass crashed ({type(exc).__name__}: {exc}); "
                        "its diagnostics are unavailable for this tree — the "
                        f"{p.code} guarantee is NOT being checked"
                    ),
                )
            ]
            checked[p.code] = False
        diagnostics.extend(found)
        timings[p.code] = time.perf_counter() - t0
    diagnostics.sort(key=lambda d: (-int(d.severity), d.code, d.file or "", d.line or 0))
    return AnalysisReport(
        diagnostics, node_count=node_count, pass_seconds=timings, pass_checked=checked
    )


def gate_mode(env_var: str) -> "str | None":
    """Parse a ``<env_var>=off|warn|error`` lint-gate knob (default ``off``).
    ``None`` means off; an unrecognized value falls back LOUDLY to ``warn``
    instead of silently disarming the gate."""
    import logging

    mode = os.environ.get(env_var, "off").strip().lower()
    if mode in ("off", "0", "false", "no", "none", ""):
        return None
    if mode not in ("warn", "error"):
        logging.getLogger("pathway_tpu.analysis").warning(
            "unrecognized %s=%r (expected off|warn|error); falling back to 'warn'",
            env_var, mode,
        )
        mode = "warn"
    return mode


def enforce_gate(report: AnalysisReport, mode: str) -> None:
    """The shared warn/error gate tail: mirror telemetry, log findings, and
    under ``error`` refuse the run on any error-severity diagnostic."""
    import logging

    report.emit_telemetry()
    if report.diagnostics:
        log = logging.getLogger("pathway_tpu.analysis")
        for d in report.errors + report.warnings:
            log.warning("%s", d.format())
    if mode == "error" and report.errors:
        raise GraphLintError(report)


class GraphCaptureInterrupt(BaseException):
    """Raised by ``GraphRunner.run`` under ``PATHWAY_LINT_CAPTURE=1``: the graph
    is fully built and the program must not execute. Derives from BaseException
    so user-level ``except Exception`` blocks cannot swallow the capture."""

    def __init__(self, graph: Any, *, persistence: bool = False):
        self.graph = graph
        self.persistence = persistence
        super().__init__("graph captured for lint analysis; run suppressed")


class PassManager:
    """Runs a pass pipeline over one graph and folds the diagnostics."""

    def __init__(self, passes: "List[AnalysisPass] | None" = None):
        if passes is None:
            from pathway_tpu.analysis.passes import default_passes

            passes = default_passes()
        self.passes = passes

    def run(
        self,
        graph: Any = None,
        *,
        persistence: bool = False,
        ctx: "AnalysisContext | None" = None,
    ) -> AnalysisReport:
        if graph is None:
            graph = pg.G._current
        if ctx is None:
            # callers holding a context already (GraphRunner shares one between
            # the lint gate and the fusion planner) pass it in — the DAG walk
            # and consumer maps are built once per runner, not per consumer
            ctx = AnalysisContext(graph, persistence=persistence)
        diagnostics: List[Diagnostic] = []
        timings: Dict[str, float] = {}
        checked: Dict[str, bool] = {}
        for p in self.passes:
            t0 = time.perf_counter()
            try:
                found = p.run(ctx)
                checked[p.code] = True
            except Exception as exc:  # a broken pass must never block a run
                found = [
                    p.diag(
                        Severity.INFO,
                        f"analysis pass crashed ({type(exc).__name__}: {exc}); "
                        "its diagnostics are unavailable for this graph",
                    )
                ]
                checked[p.code] = False
            diagnostics.extend(found)
            timings[p.code] = time.perf_counter() - t0
        diagnostics.sort(key=lambda d: (-int(d.severity), d.code, d.node_id))
        return AnalysisReport(
            diagnostics, node_count=len(ctx.nodes), pass_seconds=timings,
            pass_checked=checked,
        )
