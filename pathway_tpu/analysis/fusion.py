"""Fusion planning pass: partition the operator DAG into fusable regions.

The whole-commit fusion compiler (``pathway_tpu/engine/fusion.py``) executes
maximal chains of pure columnar operators as single compiled programs instead
of one evaluator dispatch per node. This module is the *planning* half: it
walks the same :class:`~pathway_tpu.analysis.framework.AnalysisContext` the
graph-lint passes use (consumer maps, expression walkers, dtype propagation —
built ONCE per runner and shared with the lint gate) and decides, statically:

- which nodes are **chain-eligible** — single-input ``rowwise``/``filter``
  nodes whose expressions reference only their own input table and contain no
  host UDF (``apply``/``udf`` call sites — the exact thing PWA004 flags as a
  fused-kernel splitter);
- which nodes are **region members** — stateful columnar operators
  (``join``/``groupby``/``concat``) whose arrangements are carried across
  commits by their evaluators and which a region may span;
- where a region must **break** — host UDFs, cross-table references, sources,
  sinks, nested graphs, and drain-sensitive evaluators (``REWIND_SAFE=False``:
  their flush rides a live-only signal no compiled replay can reproduce).

The plan itself is pure data (:class:`FusionPlan`): the engine-side compiler
turns each chain into an executable :class:`~pathway_tpu.engine.fusion.ChainProgram`,
and the flight recorder logs ``plan.to_event()`` so a post-mortem names what
was fused at crash time.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Set, Tuple

from pathway_tpu.analysis.framework import AnalysisContext
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals import parse_graph as pg

# Expression node types a chain program can evaluate with the stock
# interpreter over its column environment (everything the per-node path
# supports EXCEPT reducer leaves, which never appear in rowwise/filter
# configs). Host-UDF call sites (ApplyExpression and subclasses) are region
# boundaries, not chain citizens.
_CHAIN_SAFE_EXPRS: Tuple[type, ...] = (
    expr.ColumnConstExpression,
    expr.ColumnReference,
    expr.ColumnBinaryOpExpression,
    expr.ColumnUnaryOpExpression,
    expr.IfElseExpression,
    expr.IsNoneExpression,
    expr.IsNotNoneExpression,
    expr.CoalesceExpression,
    expr.RequireExpression,
    expr.CastExpression,
    expr.ConvertExpression,
    expr.DeclareTypeExpression,
    expr.UnwrapExpression,
    expr.FillErrorExpression,
    expr.PointerExpression,
    expr.MakeTupleExpression,
    expr.GetExpression,
    expr.MethodCallExpression,
)

# Subset of _CHAIN_SAFE_EXPRS with no raise path: a dead (unconsumed) output
# column built purely from these may be skipped entirely — evaluating it could
# only produce values nobody reads (division poisons cells, it never raises).
PURE_EXPRS: Tuple[type, ...] = (
    expr.ColumnConstExpression,
    expr.ColumnReference,
    expr.ColumnBinaryOpExpression,
    expr.ColumnUnaryOpExpression,
    expr.IfElseExpression,
    expr.IsNoneExpression,
    expr.IsNotNoneExpression,
)


def expr_chain_safe(e: expr.ColumnExpression) -> bool:
    """True when the whole tree is built from chain-safe expression types
    (in particular: no ``apply``/``udf`` host call site anywhere)."""
    for sub in AnalysisContext.expr_tree(e):
        if isinstance(sub, expr.ApplyExpression):
            return False  # host UDF (incl. batch/async flavors): region boundary
        if not isinstance(sub, _CHAIN_SAFE_EXPRS):
            return False
    return True


def expr_pure(e: expr.ColumnExpression) -> bool:
    """True when evaluating the tree can neither raise nor touch host state —
    the condition for dead-column elimination to be unobservable."""
    return all(isinstance(sub, PURE_EXPRS) for sub in AnalysisContext.expr_tree(e))


@dataclass
class ChainSpec:
    """One maximal run of CONSECUTIVE chain-eligible nodes, each consuming the
    previous node's output (the head consumes ``input_id``). Consecutiveness in
    graph order is required so fused execution preserves the exact substep
    ordering every other operator observes."""

    node_ids: List[int]
    input_id: int

    def __len__(self) -> int:
        return len(self.node_ids)


@dataclass
class FusedRegion:
    """A connected subgraph of fusable operators (chains + stateful members),
    reported for observability: the flight recorder logs regions so a crash
    dump names what was fused."""

    member_ids: List[int]
    kinds: Dict[str, int] = field(default_factory=dict)


@dataclass
class FusionPlan:
    chains: List[ChainSpec]
    regions: List[FusedRegion]
    # node id -> why it was refused (observability; also unit-tested)
    boundaries: Dict[int, str] = field(default_factory=dict)
    plan_seconds: float = 0.0

    @property
    def ops_fused(self) -> int:
        return sum(len(c) for c in self.chains)

    def to_event(self) -> Dict[str, Any]:
        """Compact payload for the ``fusion`` flight-recorder event: enough to
        reconstruct the region plan from a crash dump."""
        return {
            "chains": [
                {"input": c.input_id, "nodes": list(c.node_ids)} for c in self.chains
            ],
            "regions": [
                {"members": r.member_ids, "kinds": r.kinds} for r in self.regions
            ],
            "ops_fused": self.ops_fused,
            "plan_seconds": round(self.plan_seconds, 6),
        }


# operator kinds whose evaluators may participate in a fused region as
# stateful members: their arrangements (join sides, group slots, concat
# multiplicities) are carried across commits by the evaluator itself, so a
# region can span them without re-materializing state per substep
_MEMBER_KINDS = frozenset({"join", "groupby", "concat"})
_CHAIN_KINDS = frozenset({"rowwise", "filter"})


class FusionPlanner:
    """Static fusion planning over a shared :class:`AnalysisContext`."""

    def __init__(self, ctx: AnalysisContext):
        self.ctx = ctx

    # -- per-node classification ---------------------------------------------

    def chain_eligible(self, node: pg.Node) -> "str | None":
        """None when ``node`` may join a chain; otherwise the boundary reason."""
        ctx = self.ctx
        if node.kind not in _CHAIN_KINDS:
            return "kind"
        if len(node.inputs) != 1:
            return "multi_input"
        cls = ctx.evaluator_class(node)
        if cls is None or not getattr(cls, "REWIND_SAFE", True):
            # drain-sensitive evaluators flush on a live-only signal; a fused
            # program cannot reproduce it (none of these kinds are chain kinds
            # today — belt and braces against future evaluator changes)
            return "drain_sensitive"
        own = node.inputs[0]
        for root in ctx.expressions(node):
            if not expr_chain_safe(root):
                # the same condition PWA004 warns about: a host UDF embedded in
                # the columnar chain splits the fused program
                return "host_udf"
            for ref in root._column_refs:
                if ref.table is not own:
                    # cross-table references are LIVE dependencies resolved
                    # against materialized state mid-substep — a chain must not
                    # absorb them (RowwiseEvaluator._cross_refresh semantics)
                    return "cross_table_ref"
        return None

    def fusable_member(self, node: pg.Node) -> bool:
        """Stateful operators a region may span (executed by their own
        incremental evaluators, state carried across commits)."""
        if node.kind not in _MEMBER_KINDS:
            return False
        cls = self.ctx.evaluator_class(node)
        return cls is not None and getattr(cls, "REWIND_SAFE", True)

    # -- planning -------------------------------------------------------------

    def plan(self) -> FusionPlan:
        import time as _time

        t0 = _time.perf_counter()
        ctx = self.ctx
        nodes = ctx.nodes
        boundaries: Dict[int, str] = {}
        eligible: Dict[int, bool] = {}
        for node in nodes:
            why = self.chain_eligible(node)
            if why is None:
                eligible[node.id] = True
            else:
                eligible[node.id] = False
                if node.kind in _CHAIN_KINDS:
                    boundaries[node.id] = why

        # chains: maximal runs of eligible nodes that are CONSECUTIVE in graph
        # order and linearly linked (each consumes the previous one's output)
        chains: List[ChainSpec] = []
        current: List[pg.Node] = []

        def flush() -> None:
            if len(current) >= 2:
                chains.append(
                    ChainSpec(
                        node_ids=[n.id for n in current],
                        input_id=current[0].inputs[0]._node.id,
                    )
                )
            current.clear()

        for node in nodes:
            if eligible.get(node.id) and current and node.inputs[0]._node is current[-1]:
                current.append(node)
            else:
                flush()
                if eligible.get(node.id):
                    current.append(node)
        flush()

        # regions (reporting): connected components over fusable nodes — chain
        # members plus stateful member kinds — linked by direct edges
        in_chain: Set[int] = {nid for c in chains for nid in c.node_ids}
        fusable: Set[int] = set(in_chain)
        node_by_id = {n.id: n for n in nodes}
        for node in nodes:
            if self.fusable_member(node):
                fusable.add(node.id)
        parent: Dict[int, int] = {nid: nid for nid in fusable}

        def find(x: int) -> int:
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        def union(a: int, b: int) -> None:
            ra, rb = find(a), find(b)
            if ra != rb:
                parent[ra] = rb

        for nid in fusable:
            for inp in node_by_id[nid].inputs:
                if inp._node.id in fusable:
                    union(nid, inp._node.id)
        groups: Dict[int, List[int]] = {}
        for nid in sorted(fusable):
            groups.setdefault(find(nid), []).append(nid)
        regions = []
        for members in groups.values():
            if len(members) < 2:
                continue
            kinds: Dict[str, int] = {}
            for nid in members:
                k = node_by_id[nid].kind
                kinds[k] = kinds.get(k, 0) + 1
            regions.append(FusedRegion(member_ids=members, kinds=kinds))

        plan = FusionPlan(chains=chains, regions=regions, boundaries=boundaries)
        plan.plan_seconds = _time.perf_counter() - t0
        return plan


def plan_fusion(ctx: AnalysisContext) -> FusionPlan:
    """Plan whole-commit fusion over an existing analysis context (the one the
    lint gate already built — one DAG walk per runner, not two)."""
    return FusionPlanner(ctx).plan()
