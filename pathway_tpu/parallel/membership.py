"""Elastic mesh membership: epoch-fenced grow/shrink of a spawn cluster.

ROADMAP item 1: a ``MEMBERSHIP_CHANGE(target_n)`` transition that rides the
existing fence/quiesce machinery. The supervisor publishes a *directive*
(generation, target worker count, next epoch) into the supervise dir; the
workers agree on it through the per-commit neu allgather, quiesce at one
commit boundary, partition their state into per-new-owner *handoff fragments*
(the reshard treated as an array redistribution — every keyed state array is
gathered by ``shard_of(key, new_n)`` and scattered to its new owner, the
DrJAX MapReduce-primitives view of the reshard), commit a *membership
manifest* through the PR-6 checkpoint machinery, and only then rewire the
mesh: joiners install, leavers drain and release. A joiner's catch-up is the
manifest + fragments + journal tail — never a full-history replay.

The state machine was modeled FIRST (``membership_model`` in
``internals/protocol_models.py``) and explored under ``internals/sched.py``;
the invariants proven there (single owner per key range at every epoch, no
row lost or duplicated across the handoff, leavers drained before release,
no stale-epoch delivery, no deadlock) are the contract this module
implements against real sockets and stores.

This module owns the pieces that are neither mesh (``parallel/cluster.py``)
nor engine (``engine/runner.py``): the typed errors, the directive file
protocol between supervisor and workers, the per-node reshard-policy
analysis, and the fragment build/import helpers.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

# directive file written atomically by the supervisor into the supervise dir;
# workers poll it at commit boundaries and agree on it via the neu allgather
DIRECTIVE_FILE = "membership.json"


class MembershipMismatchError(ValueError):
    """A persisted store (journal header, store meta, checkpoint manifest)
    names a different worker count than this run. Carries enough to triage:
    was the cluster scaled with ``--scale`` (relaunch with ``-n manifest_n``,
    or let the supervisor adapt) or is the store from another deployment?

    Subclasses ``ValueError`` so pre-elastic ``except ValueError`` refusal
    handling keeps working."""

    def __init__(
        self,
        what: str,
        *,
        manifest_n: "int | None",
        current_n: int,
        epoch: int = 0,
    ):
        self.manifest_n = manifest_n
        self.current_n = current_n
        self.epoch = epoch
        super().__init__(
            f"persisted {what} was written by a run with {manifest_n} worker "
            f"process(es) but this run uses {current_n} (store epoch "
            f"{epoch}): the journal and checkpoints are sharded per worker. "
            f"If the cluster was resized with `spawn --scale`, relaunch with "
            f"-n {manifest_n} (the supervisor does this automatically when "
            "adapting after a mid-transition crash); if you never scaled, "
            "the store belongs to a different deployment — clear the "
            "persistence directory to start fresh"
        )


class MembershipUnsupportedError(RuntimeError):
    """The running graph (or its sources) holds state this build cannot
    re-partition across a membership change. The scale request is REFUSED —
    loudly, with the reason — and the cluster keeps running at its current
    size."""


@dataclass(frozen=True)
class MembershipDirective:
    """One requested membership change, written by the supervisor."""

    generation: int  # monotonically increasing per supervise dir
    target_n: int
    epoch: int  # the epoch the new topology will run at
    from_n: int  # worker count when the directive was issued
    # who asked: "operator" (--scale / control endpoint / plan) or
    # "autoscaler" (the closed control loop) — refusal feedback and
    # post-mortems attribute the decision. NOT part of as_tuple(): the
    # per-commit vote payload stays the stable 4-tuple.
    origin: str = "operator"

    def as_tuple(self) -> tuple:
        return (self.generation, self.target_n, self.epoch, self.from_n)

    @classmethod
    def from_tuple(cls, t: "tuple | list | None") -> "Optional[MembershipDirective]":
        if not t:
            return None
        g, n, e, f = t
        return cls(int(g), int(n), int(e), int(f))


def directive_path(supervise_dir: str) -> str:
    return os.path.join(supervise_dir, DIRECTIVE_FILE)


def write_directive(supervise_dir: str, directive: MembershipDirective) -> None:
    path = directive_path(supervise_dir)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(
            {
                "generation": directive.generation,
                "target_n": directive.target_n,
                "epoch": directive.epoch,
                "from_n": directive.from_n,
                "origin": directive.origin,
            },
            f,
        )
    os.replace(tmp, path)


def read_directive(supervise_dir: "str | None") -> "Optional[MembershipDirective]":
    if not supervise_dir:
        return None
    try:
        with open(directive_path(supervise_dir)) as f:
            raw = json.load(f)
    except (OSError, ValueError):
        return None
    try:
        return MembershipDirective(
            int(raw["generation"]), int(raw["target_n"]),
            int(raw["epoch"]), int(raw["from_n"]),
            origin=str(raw.get("origin", "operator")),
        )
    except (KeyError, TypeError, ValueError):
        return None


def clear_directive(supervise_dir: "str | None") -> None:
    if not supervise_dir:
        return
    try:
        os.unlink(directive_path(supervise_dir))
    except OSError:
        pass


# ---------------------------------------------------------------------------
# reshard-policy analysis
# ---------------------------------------------------------------------------
#
# Which new rank owns each piece of a node's state after the transition?
#
#   "bykey"     — rows live at their row/group key's owner (outputs of
#                 row-key and group-key exchanges through key-preserving
#                 chains, plus join/dedup/having whose exchange or instance
#                 key equals the output row key): partition every keyed
#                 state array by shard_of(key, new_n).
#   "source"    — never exchanged: rows sit where they were ingested, so
#                 they move exactly when their *source shard* moves (fs file
#                 ownership is hash-of-path mod n). Partitioned by the key ->
#                 new-owner map the reshardable sources export; keys outside
#                 the map (rank-local sources) stay on a surviving donor and
#                 fall back to shard_of on a leaver (their streams are final
#                 — the preflight refuses live rank-local streams on
#                 leavers).
#   "root"      — centralized on rank 0 (sort, temporal behaviors, iterate,
#                 row transformers): rank 0 survives every transition, so
#                 the full state ships to rank 0 (a no-op move for live
#                 rank 0).
#   "derived:N" — key-DERIVING node N (reindex/flatten/concat-reindex): an
#                 output row resides wherever its input row lived, so the
#                 owner function composes as base_owner(prov[out_key])
#                 through node N's provenance map (``plan.derived_base``
#                 holds the base placement per derived node).
#   "replicate" — replicated index content (every rank already holds
#                 identical state by the broadcast construction): rank 0's
#                 rebuild descriptor ships to every new rank.
#
# Every graph kind maps to an explicit policy class in
# ``RESHARD_KIND_POLICIES``; a kind missing from the table is a loud typed
# refusal ("no declared reshard policy"), never a silent guess — a new
# evaluator must declare how its state rides the handoff before graphs
# using it can scale.

# key-preserving kinds (mirror of GraphRunner.setup's placement analysis):
# output row keys equal input row keys, so ownership flows through unchanged
_KEY_PRESERVING = {
    "rowwise", "filter", "update_rows", "update_cells", "intersect",
    "difference", "restrict", "having", "with_universe_of",
    "remove_errors", "concat", "output", "asof_now", "ix",
}

_NESTED_KINDS = {
    "iterate", "iterate_result", "row_transformer", "row_transformer_result",
}

#: every graph node kind -> reshard policy class. "inherit" means ownership
#: flows from the (non-broadcast) inputs, still subject to the
#: key-preservation check and the evaluator's own ``reshard_check``;
#: "derived" composes the owner through the node's provenance map. A kind
#: absent from this table refuses loudly (see ``compute_reshard_plan``).
RESHARD_KIND_POLICIES: Dict[str, str] = {
    "input": "source",
    # nested subgraphs centralize on rank 0 (rank 0 survives every transition)
    "iterate": "root",
    "iterate_result": "root",
    "row_transformer": "root",
    "row_transformer_result": "root",
    # exchanged/keyed by a key equal to the OUTPUT row key
    "groupby": "bykey",       # routed by group key == output row key
    "join": "bykey",          # arrangements partition by join key; outputs
                              # re-exchange by output row key after the join
    "deduplicate": "bykey",   # instance route key == output row key
    "having": "bykey",        # indexer routes carry the base row key
    # key-DERIVING: owner composes through the tracked provenance map
    "reindex": "derived",
    "flatten": "derived",
    "concat": "inherit",      # promoted to "derived" in reindex mode
    "external_index": "replicate",
    # key-preserving / policy-declaring pass-through kinds: ownership flows
    # from inputs, or the evaluator declares "root"/"rowkey" itself
    "rowwise": "inherit",
    "filter": "inherit",
    "update_rows": "inherit",
    "update_cells": "inherit",
    "intersect": "inherit",
    "difference": "inherit",
    "restrict": "inherit",
    "with_universe_of": "inherit",
    "remove_errors": "inherit",
    "output": "inherit",
    "asof_now": "inherit",
    "ix": "inherit",
    "sort": "inherit",
    "sorted_index": "inherit",
    "gradual_broadcast": "inherit",
    "buffer": "inherit",
    "forget": "inherit",
    "freeze": "inherit",
    "stateful_reduce": "inherit",
}


@dataclass
class ReshardPlan:
    """Per-node reshard policies, or the reasons the transition is refused.

    ``refused_nodes`` carries the structured per-node view ({"node", "kind",
    "reason"}) for /healthz and supervisor post-mortems; ``refusals`` is the
    same information formatted for logs and the preflight vote payload.
    ``derived_base`` maps a key-deriving node id to the placement string its
    provenance resolves into (possibly another ``derived:M`` — chains
    compose)."""

    policies: Dict[int, str]
    refusals: List[str]
    refused_nodes: List[Dict[str, Any]] = None  # type: ignore[assignment]
    derived_base: Dict[int, str] = None  # type: ignore[assignment]

    def __post_init__(self) -> None:
        if self.refused_nodes is None:
            self.refused_nodes = []
        if self.derived_base is None:
            self.derived_base = {}

    @property
    def ok(self) -> bool:
        return not self.refusals


def compute_reshard_plan(runner: Any) -> ReshardPlan:
    """Classify every node of the running graph for the handoff. Pure
    analysis — no state is touched. Conservative: anything the fragment
    builder cannot partition exactly is a refusal, never a silent guess."""
    from pathway_tpu.engine.evaluators import InputEvaluator, OutputEvaluator
    from pathway_tpu.internals import parse_graph as pg

    policies: Dict[int, str] = {}
    refusals: List[str] = []
    refused_nodes: List[Dict[str, Any]] = []
    derived_base: Dict[int, str] = {}
    memo: Dict[int, str] = {}
    reasons: Dict[int, str] = {}

    def refuse(node: Any, reason: str) -> str:
        reasons.setdefault(node.id, reason)
        return "refuse"

    def placement(node: Any) -> str:
        got = memo.get(node.id)
        if got is not None:
            return got
        memo[node.id] = "refuse"  # cycle guard (loop-back chains)
        p = _place(node)
        memo[node.id] = p
        return p

    def _place(node: Any) -> str:
        if isinstance(node, pg.InputNode):
            return "source"
        kind_policy = RESHARD_KIND_POLICIES.get(node.kind)
        if kind_policy is None:
            return refuse(
                node,
                f"kind {node.kind!r} declares no reshard policy — a new "
                "evaluator must be added to RESHARD_KIND_POLICIES (with an "
                "export path for its state) before graphs using it can "
                "change membership",
            )
        ev = runner.evaluators.get(node.id)
        pol = tuple(getattr(ev, "_cluster_policies", ()) or ())
        if kind_policy == "root" or "root" in pol:
            return "root"
        if kind_policy == "replicate":
            return "replicate"
        if kind_policy == "bykey":
            return "bykey"
        if kind_policy == "derived" or (
            node.kind == "concat" and node.config.get("reindex", False)
        ):
            bases = {placement(inp._node) for inp in node.inputs}
            if len(bases) != 1:
                return refuse(
                    node,
                    "key-deriving node over inputs with mixed placements "
                    f"({', '.join(sorted(bases))}) — the provenance map "
                    "cannot name a single base owner per derived key",
                )
            base = bases.pop()
            if base == "refuse":
                return refuse(node, "an input of this node already refuses")
            if base == "root":
                return "root"  # all input rows sit on rank 0; so do outputs
            derived_base[node.id] = base
            return f"derived:{node.id}"
        # inherit: ownership flows from the (non-broadcast) inputs
        if "custom" in pol and not getattr(ev, "RESHARD_ROUTE_BYKEY", False):
            return refuse(
                node,
                "exchanged by a custom route key that is not the output "
                "row key — its keyed state cannot be placed by "
                "shard_of(output key) (declare RESHARD_ROUTE_BYKEY if the "
                "route IS the output key)",
            )
        if "rowkey" in pol or "custom" in pol:
            return "bykey"
        contrib = [
            placement(inp._node)
            for i, inp in enumerate(node.inputs)
            if not (i < len(pol) and pol[i] == "broadcast")
        ] or [placement(inp._node) for inp in node.inputs]
        if not contrib:
            return "source"
        if any(c == "refuse" for c in contrib):
            return refuse(node, "an input of this node already refuses")
        if not all(c == contrib[0] for c in contrib):
            return refuse(
                node,
                "inputs have mixed placements "
                f"({', '.join(sorted(set(contrib)))}) — rows of this node "
                "have no single owner function",
            )
        p = contrib[0]
        if (
            (p in ("bykey", "source") or p.startswith("derived:"))
            and node.kind not in _KEY_PRESERVING
            and node.kind != "external_index"
        ):
            # key-changing op without provenance tracking: output keys are
            # neither the exchange key nor the preserved input key.
            # external_index is exempt: its output universe IS the query
            # input's universe (replies keyed by query key).
            return refuse(
                node,
                "output keys are a derivation this build does not track "
                "provenance for — state keyed by them cannot be placed",
            )
        return p

    def record_refusal(node: Any, reason: str) -> None:
        refusals.append(f"node {node.id} ({node.kind}): {reason}")
        refused_nodes.append(
            {"node": node.id, "kind": node.kind, "reason": reason}
        )

    for node in runner._nodes:
        ev = runner.evaluators.get(node.id)
        if isinstance(ev, (InputEvaluator, OutputEvaluator)):
            # sources hand off through the source-state path; sinks are
            # rank-local delivery bookkeeping (retraction/snapshot replay
            # handles them at the transition)
            continue
        p = placement(node)
        if p == "refuse":
            record_refusal(
                node,
                reasons.get(
                    node.id,
                    "state cannot be re-partitioned across a membership "
                    "change",
                ),
            )
            continue
        if node.kind == "external_index":
            # an index that exports a rebuildable descriptor replicates to
            # the new topology (its data side is broadcast — every rank
            # already holds identical content); the typed refusal is KEPT
            # for index types that cannot export a descriptor
            reason = ev.reshard_check() if ev is not None else "no evaluator"
            if reason is not None:
                record_refusal(node, reason)
                continue
            policies[node.id] = "replicate"
            continue
        if not getattr(ev, "SNAPSHOT_CAPTURE", True):
            record_refusal(
                node,
                "state lives outside the snapshot protocol "
                "(device-resident) and cannot ride the handoff fragments",
            )
            continue
        if p == "bykey" or p == "source" or p.startswith("derived:"):
            reason = ev.reshard_check() if ev is not None else None
            if reason is not None:
                record_refusal(node, reason)
                continue
        policies[node.id] = p
    return ReshardPlan(policies, refusals, refused_nodes, derived_base)


def preflight_sources(runner: Any, new_n: int, me: int) -> List[str]:
    """Source-side capability check. A leaver's live streams must be
    transferable (fs scans reshard; finished streams and loopbacks are
    inert); a rank-local live stream on a leaver would silently stop
    ingesting — refuse instead."""
    refusals: List[str] = []
    leaving = me >= new_n
    for node, _ev in runner._sources:
        source = node.config["source"]
        subject = getattr(source, "subject", None)
        reshardable = subject is not None and hasattr(subject, "reshard_exports")
        if reshardable:
            continue
        if getattr(source, "loopback", False):
            continue
        if leaving and not source.is_finished():
            refusals.append(
                f"source node {node.id}: rank {me} is draining but this "
                "live stream is rank-local (no reshard support) — its "
                "future rows would be lost; finish or reshard the source "
                "before scaling this rank away"
            )
    return refusals


# ---------------------------------------------------------------------------
# handoff fragments
# ---------------------------------------------------------------------------


def _owner_fn_bykey(new_n: int) -> Callable[[Any], Any]:
    from pathway_tpu.internals.keys import shard_of

    def owner_of(keys: Any) -> Any:
        return shard_of(keys, new_n)

    return owner_of


def _owner_fn_source(
    key_owner_map: Dict[bytes, int], default_owner: "int | None", new_n: int
) -> Callable[[Any], Any]:
    """Per-row owners for ingest-placed state: the source-exported key map
    decides; unmapped keys stay on the donor (survivor) or hash out
    (leaver — their streams are final by preflight)."""
    import numpy as np

    from pathway_tpu.internals.keys import shard_of

    def owner_of(keys: Any) -> Any:
        fallback = (
            shard_of(keys, new_n)
            if default_owner is None
            else np.full(len(keys), default_owner, dtype=np.int64)
        )
        out = fallback.copy()
        for i in range(len(keys)):
            got = key_owner_map.get(keys[i].tobytes())
            if got is not None:
                out[i] = got
        return out

    return owner_of


def _owner_fn_derived(ev: Any, base_fn: Callable[[Any], Any]) -> Callable[[Any], Any]:
    """Owner for key-DERIVED state: map each derived key through the
    evaluator's provenance to the input key whose placement decides
    residence, then ask the base owner. Keys without a provenance entry
    (never produced on this rank — e.g. replay-memo entries keyed by
    pre-derivation keys) fall through to the base owner unmapped."""
    import numpy as np

    from pathway_tpu.internals.keys import KEY_DTYPE

    def owner_of(keys: Any) -> Any:
        prov = getattr(ev, "_reshard_prov", None) or {}
        if not prov:
            return base_fn(keys)
        mapped = np.empty(len(keys), dtype=KEY_DTYPE)
        for i in range(len(keys)):
            kb = keys[i].tobytes()
            src = prov.get(kb, kb)
            mapped[i] = np.frombuffer(src, dtype=KEY_DTYPE)[0]
        return base_fn(mapped)

    return owner_of


def _make_owner_resolver(
    runner: Any,
    plan: ReshardPlan,
    new_n: int,
    key_map: Dict[bytes, int],
    me: int,
    leaving: bool,
) -> Callable[[str], Callable[[Any], Any]]:
    """Memoized placement-string -> owner-function resolver. ``derived:N``
    placements compose recursively through their base placement (chains of
    reindex/flatten over reindex compose all the way down to bykey/source)."""
    bykey = _owner_fn_bykey(new_n)
    bysource = _owner_fn_source(key_map, None if leaving else me, new_n)
    fns: Dict[str, Callable[[Any], Any]] = {"bykey": bykey, "source": bysource}

    def owner_for(policy: str) -> Callable[[Any], Any]:
        fn = fns.get(policy)
        if fn is None:
            if not policy.startswith("derived:"):
                raise MembershipUnsupportedError(
                    f"no owner function for reshard policy {policy!r}"
                )
            nid = int(policy.split(":", 1)[1])
            base = plan.derived_base.get(nid, "bykey")
            fn = _owner_fn_derived(runner.evaluators[nid], owner_for(base))
            fns[policy] = fn
        return fn

    return owner_for


def build_source_exports(
    runner: Any, new_n: int
) -> Tuple[Dict[int, Dict[int, list]], Dict[bytes, int]]:
    """Ask every reshardable source to partition its durable scan state by
    new owner. Returns ``(per_dest {rank: {node_id: [state deltas]}},
    key_owner_map {row-key bytes -> new owner})`` — the map also drives the
    "source"-policy state-table partition. Pure read: nothing is removed
    from the live sources until the transition commits."""
    per_dest: Dict[int, Dict[int, list]] = {}
    key_map: Dict[bytes, int] = {}
    for node, _ev in runner._sources:
        source = node.config["source"]
        subject = getattr(source, "subject", None)
        exports = getattr(subject, "reshard_exports", None)
        if exports is None:
            continue
        by_owner = exports(new_n)
        for dest, deltas in by_owner.items():
            if not deltas:
                continue
            per_dest.setdefault(dest, {}).setdefault(node.id, []).extend(deltas)
        key_owners = getattr(subject, "reshard_key_owners", None)
        if key_owners is not None:
            for kb, owner in key_owners(new_n):
                key_map[kb] = owner
    return per_dest, key_map


def build_fragments(
    runner: Any,
    plan: ReshardPlan,
    new_n: int,
    commit: int,
    generation: int,
    source_state: "Tuple[dict, dict] | None" = None,
) -> Tuple[Dict[int, dict], Dict[str, int]]:
    """Partition this rank's entire engine state into one fragment per new
    rank (including one addressed to itself — crash recovery reloads the
    full set, so fragments must be complete, not deltas against live
    state). ``source_state`` is a precomputed :func:`build_source_exports`
    result (the caller reuses it for the sink retractions — rebuilding it
    copies every emitted row again). Returns ``(fragments, stats)``."""
    import numpy as np  # noqa: F401  (vectorized owners)

    from pathway_tpu.internals.config import get_pathway_config

    me = get_pathway_config().process_id
    leaving = me >= new_n
    source_exports, key_map = (
        source_state
        if source_state is not None
        else build_source_exports(runner, new_n)
    )
    owner_for = _make_owner_resolver(runner, plan, new_n, key_map, me, leaving)
    bykey = owner_for("bykey")

    fragments: Dict[int, dict] = {
        dest: {
            "format": 1,
            "from_rank": me,
            "commit": commit,
            "generation": generation,
            "states": {},
            "evals": {},
            "evals_full": {},
            "evals_rebuild": {},
            "source_offsets": {},
            "source_deltas": {},
        }
        for dest in range(new_n)
    }
    rows_moved = 0
    for nid, policy in plan.policies.items():
        ev = runner.evaluators[nid]
        state = runner.states.get(nid)
        if policy == "replicate":
            # replicated index content: identical on every old rank by the
            # broadcast construction, so rank 0's descriptor is authoritative
            # and ships to EVERY new rank; the keyed query-side state
            # partitions by row key like any bykey evaluator
            if me == 0:
                desc = ev.rebuild_descriptor()
                for dest in range(new_n):
                    fragments[dest]["evals_rebuild"][nid] = desc
            for dest, payload in ev.reshard_export(bykey, new_n).items():
                fragments[dest]["evals"][nid] = payload
            if state is not None and nid in runner._materialized:
                for dest, part in state.reshard_partition(bykey).items():
                    fragments[dest]["states"][nid] = part
                    if dest != me:
                        rows_moved += len(part[0])
            continue
        if policy == "root":
            # centralized state lives at rank 0 ONLY — rank 0's copy is
            # authoritative, and a non-root rank's empty mirror must never
            # clobber it at import
            if me == 0:
                fragments[0]["evals_full"][nid] = ev.state_dict()
                if state is not None and nid in runner._materialized:
                    snap = state.snapshot()
                    if len(snap):
                        fragments[0]["states"][nid] = (
                            snap.keys, snap.diffs, dict(snap.columns)
                        )
            continue
        owner_of = owner_for(policy)
        payloads = ev.reshard_export(owner_of, new_n)
        for dest, payload in payloads.items():
            fragments[dest]["evals"][nid] = payload
        if state is not None and nid in runner._materialized:
            for dest, part in state.reshard_partition(owner_of).items():
                fragments[dest]["states"][nid] = part
                if dest != me:
                    rows_moved += len(part[0])
    # source continuation offsets ride the self-addressed fragment (a crash
    # recovery of THIS rank resumes its own counters); moved scan state is
    # addressed to its new owner
    if not leaving:
        for node, _ev in runner._sources:
            offsets = node.config["source"].offset_state()
            offsets.pop("state_deltas", None)
            fragments[me]["source_offsets"][node.id] = offsets
    for dest, by_node in source_exports.items():
        if dest >= new_n:
            continue
        for nid, deltas in by_node.items():
            fragments[dest]["source_deltas"].setdefault(nid, []).extend(deltas)
    stats = {"rows_handed_off": rows_moved}
    return fragments, stats


#: default per-chunk budget for the streamed handoff (bytes of payload per
#: mini-fragment). Overridden by PATHWAY_RESHARD_CHUNK_BYTES.
DEFAULT_RESHARD_CHUNK_BYTES = 1 << 22


def reshard_chunk_bytes() -> int:
    raw = os.environ.get("PATHWAY_RESHARD_CHUNK_BYTES", "")
    try:
        got = int(raw)
    except ValueError:
        got = 0
    return got if got > 0 else DEFAULT_RESHARD_CHUNK_BYTES


def _approx_nbytes(obj: Any) -> int:
    """Cheap recursive payload-size estimate for chunk budgeting. Exactness
    does not matter — it only decides where chunk boundaries fall."""
    import numpy as np

    if obj is None:
        return 8
    if isinstance(obj, np.ndarray):
        return int(obj.nbytes)
    if isinstance(obj, (bytes, bytearray, str)):
        return len(obj)
    if isinstance(obj, dict):
        return 64 + sum(
            _approx_nbytes(k) + _approx_nbytes(v) for k, v in obj.items()
        )
    if isinstance(obj, (list, tuple, set, frozenset)):
        return 64 + sum(_approx_nbytes(v) for v in obj)
    return 64


def build_fragment_chunks(
    runner: Any,
    plan: ReshardPlan,
    new_n: int,
    commit: int,
    generation: int,
    source_state: "Tuple[dict, dict] | None" = None,
    chunk_bytes: "int | None" = None,
) -> Tuple[Any, Dict[str, int]]:
    """Streamed counterpart of :func:`build_fragments`: yields
    ``(dest, chunk)`` mini-fragments whose payload stays under the chunk
    budget, so a donor's peak handoff memory is O(chunk x peers) instead of
    O(state). Each chunk has the full format-1 fragment shape and imports
    independently through :func:`import_fragments` (state-table parts apply
    as incremental deltas; evaluator exports are merge-disjoint by
    construction), carrying at most one payload per (section, node) — plus a
    ``kinds`` list naming the node kinds aboard, which the chaos harness
    gates its chunk-level faults on.

    Returns ``(chunk_iterator, stats)``; ``stats`` is populated AS the
    iterator is drained (read it only after the dump loop finishes).
    Unsplittable payloads (root state dicts, rebuild descriptors) ride a
    single chunk whatever their size — the budget bounds the partitionable
    state, which is what grows with the workload."""
    from pathway_tpu.internals.config import get_pathway_config

    me = get_pathway_config().process_id
    leaving = me >= new_n
    source_exports, key_map = (
        source_state
        if source_state is not None
        else build_source_exports(runner, new_n)
    )
    owner_for = _make_owner_resolver(runner, plan, new_n, key_map, me, leaving)
    bykey = owner_for("bykey")
    budget = int(chunk_bytes) if chunk_bytes else reshard_chunk_bytes()
    budget = max(1, budget)
    # row budget for export-side slicing: conservative rows-per-chunk guess;
    # the byte accounting below is what actually seals chunks
    budget_rows = max(64, budget // 512)
    kinds_of = {n.id: n.kind for n in runner._nodes}
    stats: Dict[str, int] = {"rows_handed_off": 0, "chunks": 0}

    def pieces():
        """(dest, section, nid, payload, moved_rows) in node order."""
        for nid, policy in plan.policies.items():
            ev = runner.evaluators[nid]
            state = runner.states.get(nid)
            if policy == "replicate":
                if me == 0:
                    desc = ev.rebuild_descriptor()
                    for dest in range(new_n):
                        yield dest, "evals_rebuild", nid, desc, 0
                for dest, payload in ev.reshard_export(bykey, new_n).items():
                    yield dest, "evals", nid, payload, 0
                if state is not None and nid in runner._materialized:
                    for dest, part in state.reshard_partition_chunks(
                        bykey, budget_rows
                    ):
                        yield dest, "states", nid, part, (
                            len(part[0]) if dest != me else 0
                        )
                continue
            if policy == "root":
                if me == 0:
                    yield 0, "evals_full", nid, ev.state_dict(), 0
                    if state is not None and nid in runner._materialized:
                        snap = state.snapshot()
                        if len(snap):
                            yield 0, "states", nid, (
                                snap.keys, snap.diffs, dict(snap.columns)
                            ), 0
                continue
            owner_of = owner_for(policy)
            parts_fn = getattr(ev, "reshard_export_parts", None)
            if parts_fn is not None:
                for dest, piece in parts_fn(owner_of, new_n, budget_rows):
                    yield dest, "evals", nid, piece, 0
            else:
                for dest, payload in ev.reshard_export(owner_of, new_n).items():
                    yield dest, "evals", nid, payload, 0
            if state is not None and nid in runner._materialized:
                for dest, part in state.reshard_partition_chunks(
                    owner_of, budget_rows
                ):
                    yield dest, "states", nid, part, (
                        len(part[0]) if dest != me else 0
                    )
        if not leaving:
            for node, _ev in runner._sources:
                offsets = node.config["source"].offset_state()
                offsets.pop("state_deltas", None)
                yield me, "source_offsets", node.id, offsets, 0
        for dest, by_node in source_exports.items():
            if dest >= new_n:
                continue
            for nid, deltas in by_node.items():
                yield dest, "source_deltas", nid, list(deltas), 0

    def new_chunk() -> dict:
        return {
            "format": 1,
            "from_rank": me,
            "commit": commit,
            "generation": generation,
            "states": {},
            "evals": {},
            "evals_full": {},
            "evals_rebuild": {},
            "source_offsets": {},
            "source_deltas": {},
            "kinds": [],
        }

    def seal(chunk: dict) -> dict:
        chunk["kinds"] = sorted(set(chunk["kinds"]))
        stats["chunks"] += 1
        return chunk

    def chunks():
        open_chunks: Dict[int, list] = {}  # dest -> [chunk, approx bytes]
        touched: set = set()
        for dest, section, nid, payload, moved in pieces():
            touched.add(dest)
            ent = open_chunks.get(dest)
            if ent is None:
                ent = open_chunks[dest] = [new_chunk(), 0]
            if nid in ent[0][section]:
                # one payload per (section, node) per chunk: importing a
                # chunk must never see two payloads collide under one id
                yield dest, seal(ent[0])
                ent = open_chunks[dest] = [new_chunk(), 0]
            ent[0][section][nid] = payload
            ent[0]["kinds"].append(kinds_of.get(nid, "input"))
            ent[1] += _approx_nbytes(payload)
            stats["rows_handed_off"] += moved
            if ent[1] >= budget:
                yield dest, seal(ent[0])
                del open_chunks[dest]
        for dest in sorted(open_chunks):
            yield dest, seal(open_chunks[dest][0])
        # every destination gets at least one chunk: the per-dest manifest
        # must exist for the loader to tell "empty handoff" from "torn write"
        for dest in range(new_n):
            if dest not in touched:
                yield dest, seal(new_chunk())

    return chunks(), stats


def import_fragments(runner: Any, frags: List[dict]) -> Dict[str, int]:
    """Merge handoff fragments addressed to this rank into FRESH evaluator /
    state-table instances (the caller reset them). Order-independent: key
    partitions are disjoint by construction; root/full states appear in
    exactly one fragment. Accepts both whole fragments (gather transport)
    and streamed chunks (:func:`build_fragment_chunks`) — a chunk is just a
    small fragment."""
    from pathway_tpu.engine.columnar import Delta
    from pathway_tpu.internals.chaos import get_chaos
    from pathway_tpu.internals.config import get_pathway_config

    chaos = get_chaos()
    rows = 0
    for frag in frags:
        if chaos is not None and "deduplicate" in (frag.get("kinds") or ()):
            # dedup_install_kill: die right before applying a chunk that
            # carries dedup instance state — the install barrier must fail,
            # the previous topology's state must stand, and the recovery
            # ladder must replay the transition bit-identically
            chaos.maybe_scale_kill(
                get_pathway_config().process_id, "dedup_install_kill",
                commit=int(frag.get("commit", -1)),
            )
        for nid, (keys, diffs, columns) in frag.get("states", {}).items():
            nid = int(nid)
            state = runner.states.get(nid)
            if state is not None and len(keys):
                state.apply(Delta(keys, diffs, columns))
                rows += len(keys)
        for nid, payload in frag.get("evals", {}).items():
            ev = runner.evaluators.get(int(nid))
            if ev is not None:
                ev.reshard_import(payload)
        for nid, blobs in frag.get("evals_full", {}).items():
            ev = runner.evaluators.get(int(nid))
            if ev is not None:
                ev.load_state_dict(blobs)
        for nid, desc in frag.get("evals_rebuild", {}).items():
            ev = runner.evaluators.get(int(nid))
            if ev is not None and desc is not None:
                ev.install_rebuild_descriptor(desc)
    return {"rows_imported": rows}


def merge_fragment_sources(frags: List[dict]) -> Tuple[Dict[int, dict], Dict[int, list]]:
    """Collect the source continuation offsets + scan-state deltas addressed
    to this rank across all fragments (cold-start restore path)."""
    offsets: Dict[int, dict] = {}
    deltas: Dict[int, list] = {}
    for frag in frags:
        for nid, offs in frag.get("source_offsets", {}).items():
            offsets[int(nid)] = offs
        for nid, entries in frag.get("source_deltas", {}).items():
            deltas.setdefault(int(nid), []).extend(entries)
    return offsets, deltas
