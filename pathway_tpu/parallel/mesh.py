"""Device-mesh construction.

Replaces the reference's worker/process topology config (``src/engine/dataflow/config.rs:88`` —
``PATHWAY_THREADS``/``PATHWAY_PROCESSES`` → timely ``CommunicationConfig``) with a named
``jax.sharding.Mesh``. Axis conventions:

- ``data``  — batch/row parallelism (the reference's hash-sharded worker axis);
- ``model`` — tensor parallelism inside kernels (no reference analog: the reference has no
  DNN compute; this axis exists because our hot path IS a DNN + matmul-KNN).

Multi-host: on a real pod, ``jax.devices()`` already spans hosts and ICI/DCN routing is
XLA's job — the same mesh code covers single-chip, one host × N chips, and N hosts.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh


def mesh_shape_for(n_devices: int, model_parallel: Optional[int] = None) -> tuple[int, int]:
    """(data, model) factorization. Prefers the largest model axis ≤4 that divides n
    (MiniLM has 12 heads → model axis must divide 12 for head-sharded TP)."""
    if model_parallel is None:
        for m in (4, 2, 1):
            if n_devices % m == 0 and 12 % m == 0:
                model_parallel = m
                break
        else:
            model_parallel = 1
    if n_devices % model_parallel:
        raise ValueError(f"{n_devices} devices not divisible by model={model_parallel}")
    return n_devices // model_parallel, model_parallel


def make_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = ("data", "model"),
    model_parallel: Optional[int] = None,
) -> Mesh:
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if n_devices > len(devices):
        raise ValueError(f"requested {n_devices} devices, have {len(devices)}")
    data, model = mesh_shape_for(n_devices, model_parallel)
    grid = np.asarray(devices[:n_devices]).reshape(data, model)
    return Mesh(grid, axis_names=tuple(axis_names))


_default_mesh: Optional[Mesh] = None


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    """Configure the mesh the ENGINE runs on (the reference's worker-count config,
    ``PATHWAY_THREADS``/``PATHWAY_PROCESSES`` → here a device mesh). When set with a
    ``data`` axis larger than 1, external KNN indexes build mesh-sharded stores and
    large groupby-reduce batches route through the key-hash exchange."""
    global _default_mesh
    _default_mesh = mesh


def get_default_mesh() -> Optional[Mesh]:
    return _default_mesh


def data_shards(mesh: Optional[Mesh]) -> int:
    if mesh is None or "data" not in mesh.axis_names:
        return 1
    return mesh.shape["data"]


def cpu_virtual_devices(n: int) -> None:
    """Request an n-device virtual CPU platform. Must run before jax initializes; used by
    test conftest / dryrun drivers (mirrors the driver's
    ``xla_force_host_platform_device_count`` validation mode)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    # the axon TPU plugin (registered by sitecustomize) grabs the tunnel and overrides
    # platform selection even under JAX_PLATFORMS=cpu — force CPU and drop its factory
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass


def shard_map_compat(f, mesh, in_specs, out_specs):
    """``shard_map`` across jax versions: 0.4.x exposes it under
    ``jax.experimental`` with ``check_rep``; newer jax exports it top-level
    with the kwarg renamed ``check_vma``. The replication check is disabled
    either way (the callers' collectives produce replicated outputs by
    construction)."""
    try:
        from jax import shard_map as _sm
    except ImportError:  # pragma: no cover - version-dependent
        from jax.experimental.shard_map import shard_map as _sm
    try:
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)
    except TypeError:  # pragma: no cover - version-dependent
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False)
