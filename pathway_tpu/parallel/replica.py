"""Read-replica serving fleet: bounded-staleness followers with
kill-invisible failover.

ROADMAP item 4: the cluster so far scales INGEST (elastic ranks, autoscaler)
but every retrieval query still lands on the primary's serving plane. This
module adds a fleet of read-only replicas that scale QUERY capacity
independently of ingest:

- **cold start** — a replica bootstraps from the latest read-back-verified
  bootstrap export in the replica feed (``persistence/replica_feed.py``):
  bounded row fragments stream through
  :meth:`~pathway_tpu.ops.knn.BruteForceKnnIndex.install_descriptor_rows`,
  so peak bootstrap memory stays flat however large the corpus. A torn
  bootstrap (checksum mismatch on any fragment) is a TYPED refusal
  (``ReplicaBootstrapError``) — the replica reports ``refused`` and stays out
  of rotation; it never serves a half-installed index;
- **follow** — after bootstrap the replica tails the feed's per-commit row
  frames, applying each exactly once (a frame at or below the applied commit
  id is skipped — the double-apply guard ``replica_follow_model`` proves);
- **bounded staleness** — every query may carry ``max_staleness_s``; a
  replica that cannot satisfy the bound sheds with HTTP 429 and an honest
  integer ``Retry-After`` (``engine/brownout.py:retry_after_int``, the one
  formatter every shed path shares) estimated from its poll cadence and
  pending-frame backlog;
- **kill-invisible failover** — the router walks the fleet round-robin and
  falls back to the primary; a SIGKILL'd replica surfaces as a connect error
  the router absorbs, never as a client-visible 5xx;
- **independent autoscaling** — the fleet grows/shrinks on query load through
  the same damped pure controller the ingest autoscaler uses
  (``AutoscalePolicy.replica_from_env()``), without touching ingest ranks.

Replica results are BITWISE-equal to the primary's at the same commit id
(the ``bench.py replicas`` honesty key): fragments install through the same
``add_many`` path the primary ingested through, and quantized stores
regenerate codes bit-identically per the ``quant_state`` contract.

Each replica is a separate PROCESS (``python -m pathway_tpu.parallel.replica``)
supervised by :class:`ReplicaFleet` — the supervisor embeds a fleet next to
its ingest ranks (``Supervisor(replicas=N)`` / ``PATHWAY_REPLICAS``), writes
replica post-mortems with the same attribution discipline as rank
post-mortems (exit cause, last applied commit, staleness at death), and
preserves replica flight dumps past supervise-dir cleanup.

Env knobs (the fleet's own namespace — full table in README.md):

======================================  =======  ===========================
``PATHWAY_REPLICAS``                    0        fleet size at spawn
``PATHWAY_REPLICA_FEED``                —        feed root directory
``PATHWAY_REPLICA_PORT``                0        serving port (0 = OS picks)
``PATHWAY_REPLICA_POLL_S``              0.05     frame-tail poll period
``PATHWAY_REPLICA_FRAGMENT_ROWS``       4096     bootstrap fragment rows
``PATHWAY_REPLICA_MAX_RESTARTS``        10       per-fleet relaunch budget
``PATHWAY_REPLICA_AUTOSCALE``           off      ``on`` scales the fleet
``PATHWAY_REPLICA_AUTOSCALE_MIN``       1        fleet floor
``PATHWAY_REPLICA_AUTOSCALE_MAX``       4        fleet ceiling
``PATHWAY_REPLICA_AUTOSCALE_QPS``       200      target queries/s per replica
======================================  =======  ===========================
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from pathway_tpu.internals.config import env_float as _env_float
from pathway_tpu.persistence.replica_feed import (
    ReplicaBootstrapError,
    ReplicaFeed,
    ReplicaFeedError,
)

_STATUS_PREFIX = "replica-"
_STATUS_SUFFIX = ".status.json"

#: replica flight dumps live in a subdirectory of the supervise dir so a
#: replica's ``flight-rank-K.json`` can never collide with ingest rank K's
FLIGHT_SUBDIR = "replicas"


def replica_status_path(supervise_dir: str, replica_id: int) -> str:
    return os.path.join(
        supervise_dir, f"{_STATUS_PREFIX}{replica_id}{_STATUS_SUFFIX}"
    )


def write_replica_status(
    supervise_dir: str, replica_id: int, payload: Dict[str, Any]
) -> None:
    """Atomically publish one replica's liveness record (same rename
    discipline as the rank status files — a reader never sees a torn JSON)."""
    path = replica_status_path(supervise_dir, replica_id)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def read_replica_statuses(
    supervise_dir: str, n: int
) -> Dict[int, Dict[str, Any]]:
    out: Dict[int, Dict[str, Any]] = {}
    for rid in range(n):
        try:
            with open(replica_status_path(supervise_dir, rid)) as f:
                out[rid] = json.load(f)
        except (OSError, ValueError):
            continue
    return out


# -- typed serving errors ------------------------------------------------------


class ReplicaStaleError(RuntimeError):
    """The replica cannot satisfy the query's ``max_staleness_s`` bound.
    Carries the honest retry estimate the shed response advertises."""

    def __init__(self, staleness_s: float, retry_after_s: float):
        self.staleness_s = float(staleness_s)
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            f"replica is {staleness_s:.3f}s stale, beyond the query's bound — "
            f"retry in ~{retry_after_s:.2f}s or relax max_staleness_s"
        )


class ReplicaNotServingError(RuntimeError):
    """The replica is not in rotation (still bootstrapping, or its bootstrap
    was refused). The router treats this as failover, never a client 5xx."""

    def __init__(self, state: str, cause: "Optional[BaseException]" = None):
        self.state = state
        self.cause = cause
        detail = f": {cause}" if cause is not None else ""
        super().__init__(f"replica is not serving (state={state}){detail}")


class ReplicaUnavailableError(RuntimeError):
    """Every candidate (fleet AND primary fallback) was exhausted. Only
    raised when the router has no primary — with one configured, this error
    is unreachable by construction."""


def _stage_add(name: str, value: float = 1.0) -> None:
    try:
        from pathway_tpu.engine import telemetry

        telemetry.stage_add(name, value)
    except Exception:
        pass


def _flight_event(kind: str, **details: Any) -> None:
    try:
        from pathway_tpu.engine.profile import get_flight_recorder

        get_flight_recorder().record_event(kind, **details)
    except Exception:
        pass


# -- the follower --------------------------------------------------------------


class ReplicaFollower:
    """Read-only index follower: bootstrap from the feed, tail its frames.

    The ``index_factory`` receives the bootstrap HEADER (dim, metric, quant
    sidecars, filter data) and returns a fresh index implementing the
    descriptor-install contract (``install_descriptor_header`` /
    ``install_descriptor_rows`` / ``search_many``). Thread-safe: one RLock
    covers apply and search, so a query never reads a half-applied frame."""

    def __init__(
        self,
        feed: ReplicaFeed,
        index_factory: "Callable[[Dict[str, Any]], Any]",
        *,
        replica_id: int = 0,
        poll_s: "float | None" = None,
        clock: "Callable[[], float]" = time.monotonic,
    ):
        self.feed = feed
        self.replica_id = int(replica_id)
        self.poll_s = (
            float(poll_s)
            if poll_s is not None
            else _env_float("PATHWAY_REPLICA_POLL_S", 0.05)
        )
        self._clock = clock
        self._index_factory = index_factory
        self._lock = threading.RLock()
        self.index: Any = None
        self.state = "init"  # init|bootstrapping|following|refused|stopped
        self.applied_commit = -1
        self.refusal: "Optional[BaseException]" = None
        # clock() of the last poll that left the replica caught up with the
        # feed tip — staleness is measured from here
        self._fresh_as_of: "Optional[float]" = None
        self.served = 0
        self.shed = 0
        # formatted trace context of the last applied frame's originating
        # commit — replica_serve spans link back through this rider
        self._trace_rider: "Optional[str]" = None

    # -- lifecycle -------------------------------------------------------------

    def bootstrap(self) -> int:
        """Cold-start from the latest read-back-verified bootstrap export.
        Raises :class:`ReplicaFeedError` when no bootstrap exists yet, and
        :class:`ReplicaBootstrapError` (after marking the replica
        ``refused``) on a torn export — a typed refusal, not a crash."""
        with self._lock:
            self.state = "bootstrapping"
        holder: Dict[str, Any] = {}

        def install_header(header: Dict[str, Any]) -> None:
            index = self._index_factory(header)
            index.install_descriptor_header(header)
            holder["index"] = index

        def install_fragment(keys: List[Any], vectors: Any) -> None:
            holder["index"].install_descriptor_rows(keys, vectors)

        try:
            commit = self.feed.load_bootstrap(
                replica_id=self.replica_id,
                install_header=install_header,
                install_fragment=install_fragment,
            )
        except ReplicaBootstrapError as exc:
            with self._lock:
                self.state = "refused"
                self.refusal = exc
            _stage_add("replica.bootstrap_refused")
            _flight_event(
                "replica_refused", replica=self.replica_id, error=str(exc)[:240]
            )
            raise
        with self._lock:
            self.index = holder["index"]
            self.applied_commit = commit
            self.state = "following"
            self._fresh_as_of = self._clock()
        _stage_add("replica.bootstraps")
        _flight_event(
            "replica_bootstrap", replica=self.replica_id, commit=commit
        )
        return commit

    def poll_frames(self) -> int:
        """Apply every feed frame past the applied commit, in commit order.
        Returns the number applied. The chaos harness can stretch this poll
        (``replica_lag``) or SIGKILL mid-apply (``replica_kill``)."""
        with self._lock:
            if self.state != "following":
                return 0
            applied_floor = self.applied_commit
        try:
            from pathway_tpu.internals.chaos import get_chaos

            chaos = get_chaos()
        except Exception:
            chaos = None
        if chaos is not None:
            lag = chaos.replica_lag_s(self.replica_id)
            if lag > 0:
                time.sleep(lag)
        applied = 0
        for commit, path in self.feed.frames_after(applied_floor):
            payload = self.feed.read_frame(path)
            apply_t0 = time.perf_counter()
            with self._lock:
                if payload["commit"] <= self.applied_commit:
                    # double-apply guard: a frame re-listed across polls (or
                    # re-read after a racing prune+re-export) is a no-op —
                    # replica_follow_model proves replays break bitwise parity
                    _stage_add("replica.frames_skipped")
                    continue
                self._apply_locked(payload)
                self.applied_commit = int(payload["commit"])
                self._trace_rider = payload.get("trace") or self._trace_rider
            applied += 1
            _stage_add("replica.frames_applied")
            _stage_add("replica.rows_applied", len(payload.get("keys") or ()))
            self._trace_apply(payload, time.perf_counter() - apply_t0)
            if chaos is not None:
                chaos.maybe_replica_kill(self.replica_id, int(payload["commit"]))
        with self._lock:
            self._fresh_as_of = self._clock()
        _stage_add("replica.polls")
        try:
            from pathway_tpu.engine.profile import histogram

            histogram("pathway_replica_staleness_seconds").observe(
                self.staleness_s()
            )
        except Exception:
            pass
        return applied

    def _apply_locked(self, payload: Dict[str, Any]) -> None:
        # removals first: a key both removed and re-upserted in one commit
        # must land at the upsert's vector (add_many upserts via remove+add)
        for key in payload.get("removals") or ():
            self.index.remove(key)  # noqa: PWA103 (caller holds self._lock — the _locked suffix)
        keys = list(payload.get("keys") or ())
        if keys:
            self.index.install_descriptor_rows(keys, payload["vectors"])  # noqa: PWA103 (caller holds self._lock)
        filter_data = payload.get("filter_data") or {}
        if filter_data:
            # AFTER the upsert — add_many pops filter entries for re-added keys
            self.index.filter_data.update(filter_data)  # noqa: PWA103 (caller holds self._lock)

    def _trace_apply(self, payload: Dict[str, Any], elapsed: float) -> None:
        """Emit a ``replica_apply`` span as a child of the originating
        commit's trace (the rider the primary attached to the feed frame).
        Backdated to cover the apply — spans never sit on the hot path."""
        rider = payload.get("trace")
        if not rider:
            return
        try:
            from pathway_tpu.engine.tracing import (
                get_tracer,
                parse_trace_header,
            )

            tracer = get_tracer()
            if not tracer.enabled:
                return
            parent = parse_trace_header(str(rider))
            if parent is None:
                return
            span = tracer.start(
                "replica_apply",
                f"apply commit {int(payload['commit'])}",
                ctx=parent,
                attrs={
                    "replica": self.replica_id,
                    "commit": int(payload["commit"]),
                    "rows": len(payload.get("keys") or ()),
                },
            )
            if span is not None:
                span.ts -= elapsed
                span.ts_mono -= elapsed
                span.duration_s = max(elapsed, 1e-9)
                tracer.finish(span)
        except Exception:
            pass

    def applied_trace_rider(self) -> "Optional[str]":
        """Formatted trace context of the last applied feed frame's
        originating commit (None before any traced frame applies)."""
        with self._lock:
            return self._trace_rider

    # -- serving ---------------------------------------------------------------

    def staleness_s(self) -> float:
        """Seconds since this replica last confirmed it was caught up with
        the feed tip. Infinity before the first successful bootstrap."""
        with self._lock:
            fresh = self._fresh_as_of
        if fresh is None:
            return float("inf")
        return max(0.0, self._clock() - fresh)

    def pending_frames(self) -> int:
        with self._lock:
            floor = self.applied_commit
        try:
            return len(self.feed.frames_after(floor))
        except ReplicaFeedError:
            return 0

    def retry_estimate_s(self) -> float:
        """Honest shed estimate: one poll per pending frame plus the poll
        now in flight — how long until this replica is plausibly fresh."""
        return self.poll_s * (self.pending_frames() + 1)

    def search_many(
        self,
        vectors: List[Any],
        limits: List[int],
        *,
        max_staleness_s: "float | None" = None,
        filter_exprs: "List[Any] | None" = None,
    ) -> "Tuple[int, List[List[tuple]]]":
        """Answer a query batch at this replica's applied commit. Raises
        :class:`ReplicaNotServingError` out of rotation and
        :class:`ReplicaStaleError` when the staleness bound cannot be met."""
        with self._lock:
            if self.state != "following":
                _stage_add("replica.refused_query")
                raise ReplicaNotServingError(self.state, self.refusal)
            staleness = self.staleness_s()
            if max_staleness_s is not None and staleness > float(max_staleness_s):
                self.shed += 1
                _stage_add("replica.shed_stale")
                raise ReplicaStaleError(staleness, self.retry_estimate_s())
            results = self.index.search_many(vectors, limits, filter_exprs)
            commit = self.applied_commit
            self.served += 1
        _stage_add("replica.serve")
        return commit, results

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            staleness = self.staleness_s()
            return {
                "kind": "replica",
                "replica": self.replica_id,
                "state": self.state,
                "applied_commit": self.applied_commit,
                "staleness_s": (
                    None if staleness == float("inf") else round(staleness, 4)
                ),
                "served_total": self.served,
                "shed_total": self.shed,
                "refusal": (
                    None if self.refusal is None else str(self.refusal)[:240]
                ),
            }


def default_index_factory(header: Dict[str, Any]) -> Any:
    """Build the replica's index from the bootstrap header: a plain dense
    index, or the tiered/quantized store when the header carries quant
    sidecars (the install path verifies mode parity either way)."""
    from pathway_tpu.ops.knn import BruteForceKnnIndex

    dim = int(header.get("dim") or 0)
    if dim <= 0:
        raise ReplicaBootstrapError(
            "bootstrap header carries no dim — the export predates the "
            "replica-feed contract; re-export with a current primary"
        )
    metric = str(header.get("metric") or "l2sq")
    quant = header.get("quant") or {}
    if str(quant.get("mode", "off")) != "off":
        # quantized geometry rides the tiered IVF store; the header install
        # verifies mode parity (PATHWAY_IVF_QUANT must match the primary)
        from pathway_tpu.ops.knn import IvfKnnIndex

        return IvfKnnIndex(dim, metric=metric, tiered=True)
    return BruteForceKnnIndex(dim, metric=metric)


# -- the serving endpoint ------------------------------------------------------


class ReplicaServer:
    """Per-replica HTTP surface: ``POST /v1/retrieve`` (query batch with an
    optional ``max_staleness_s`` bound), ``GET /healthz`` (JSON liveness with
    the applied commit and staleness), ``GET /metrics``/``/status``
    (OpenMetrics — replica gauges + the shared process metrics plane, so the
    same strict-grammar tests cover worker and replica expositions)."""

    def __init__(self, follower: ReplicaFollower, port: int = 0):
        self.follower = follower
        follower_ref = follower
        server_ref = self

        class Handler(BaseHTTPRequestHandler):
            def _send(
                self, code: int, body: bytes, content_type: str,
                headers: "Dict[str, str] | None" = None,
            ) -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _send_json(
                self, code: int, payload: Dict[str, Any],
                headers: "Dict[str, str] | None" = None,
            ) -> None:
                self._send(
                    code,
                    json.dumps(payload, sort_keys=True).encode(),
                    "application/json",
                    headers,
                )

            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                if self.path == "/healthz":
                    payload = follower_ref.snapshot()
                    payload["alive"] = True
                    payload["port"] = server_ref.port
                    self._send_json(200, payload)
                    return
                if self.path in ("/status", "/metrics"):
                    body = server_ref.to_openmetrics().encode()
                    self._send(200, body, "application/openmetrics-text")
                    return
                self.send_response(404)
                self.end_headers()

            def do_POST(self) -> None:  # noqa: N802 (http.server API)
                if self.path != "/v1/retrieve":
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    req = json.loads(self.rfile.read(length) or b"{}")
                    vectors = req["vectors"]
                    k = int(req.get("k", 3))
                    max_staleness = req.get("max_staleness_s")
                    filters = req.get("filters")
                except (KeyError, ValueError, TypeError) as exc:
                    self._send_json(400, {"error": f"bad request: {exc}"})
                    return
                from pathway_tpu.engine.brownout import retry_after_int

                # replica_serve span: child of the query's incoming trace
                # (X-Pathway-Trace), linked back to the originating commit's
                # trace via the rider the last applied feed frame carried
                serve_headers: Dict[str, str] = {}
                serve_span = None
                serve_t0 = time.perf_counter()
                try:
                    from pathway_tpu.engine import tracing as _tracing

                    tracer = _tracing.get_tracer()
                    if tracer.enabled:
                        parent = _tracing.parse_trace_header(
                            self.headers.get(_tracing.TRACE_HEADER) or ""
                        )
                        links = []
                        rider = follower_ref.applied_trace_rider()
                        if rider:
                            link_ctx = _tracing.parse_trace_header(rider)
                            if link_ctx is not None:
                                links.append(link_ctx)
                        serve_span = tracer.start(
                            "replica_serve",
                            "POST /v1/retrieve",
                            ctx=parent,
                            links=tuple(links),
                            attrs={"replica": follower_ref.replica_id},
                        )
                        if serve_span is not None:
                            serve_headers[_tracing.TRACE_HEADER] = (
                                _tracing.format_trace_header(
                                    serve_span.context()
                                )
                            )
                except Exception:
                    serve_span = None

                def _finish_span(
                    status: int, commit: "Optional[int]" = None
                ) -> None:
                    if serve_span is None:
                        return
                    try:
                        from pathway_tpu.engine.tracing import get_tracer

                        serve_span.attrs["status"] = status
                        if commit is not None:
                            serve_span.attrs["commit"] = commit
                        serve_span.duration_s = max(
                            time.perf_counter() - serve_t0, 1e-9
                        )
                        get_tracer().finish(serve_span)
                    except Exception:
                        pass

                try:
                    commit, results = follower_ref.search_many(
                        vectors,
                        [k] * len(vectors),
                        max_staleness_s=max_staleness,
                        filter_exprs=filters,
                    )
                except ReplicaStaleError as exc:
                    _finish_span(429)
                    serve_headers.update(
                        {"Retry-After": retry_after_int(exc.retry_after_s)}
                    )
                    self._send_json(
                        429,
                        {
                            "error": "stale",
                            "staleness_s": round(exc.staleness_s, 4),
                        },
                        headers=serve_headers,
                    )
                    return
                except ReplicaNotServingError as exc:
                    # out of rotation — the router fails over; a 503 here is
                    # router-facing, never client-facing
                    _finish_span(503)
                    self._send_json(
                        503,
                        {"error": "not_serving", "state": exc.state},
                        headers=serve_headers,
                    )
                    return
                _finish_span(200, commit)
                self._send_json(
                    200,
                    {
                        "commit": commit,
                        "results": [
                            [[key, score] for key, score in row]
                            for row in results
                        ],
                    },
                    headers=serve_headers,
                )

            def log_message(self, *args: Any) -> None:
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", int(port)), Handler)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(
            target=self.httpd.serve_forever,
            daemon=True,
            name=f"pathway:replica-{follower.replica_id}-http",
        )
        self.thread.start()

    def to_openmetrics(self) -> str:
        from pathway_tpu.engine.http_server import metrics_plane_lines

        snap = self.follower.snapshot()
        staleness = snap["staleness_s"]
        lines = [
            "# HELP pathway_replica_applied_commit Last commit id applied by this replica",
            "# TYPE pathway_replica_applied_commit gauge",
            f"pathway_replica_applied_commit {snap['applied_commit']}",
            "# HELP pathway_replica_staleness_current_seconds Seconds since this replica last matched the feed tip",
            "# TYPE pathway_replica_staleness_current_seconds gauge",
            "pathway_replica_staleness_current_seconds "
            + ("+Inf" if staleness is None else repr(float(staleness))),
            "# HELP pathway_replica_served A counter of query batches served by this replica",
            "# TYPE pathway_replica_served counter",
            f"pathway_replica_served_total {snap['served_total']}",
            "# HELP pathway_replica_shed A counter of query batches shed for staleness",
            "# TYPE pathway_replica_shed counter",
            f"pathway_replica_shed_total {snap['shed_total']}",
        ]
        lines.extend(metrics_plane_lines())
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def close(self) -> None:
        httpd, self.httpd = self.httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()


# -- the router ----------------------------------------------------------------


class ReplicaRouter:
    """Client-side fleet router: round-robin over replica endpoints with a
    primary fallback. A dead/refusing replica is absorbed (cooldown + next
    candidate), a stale replica's 429 tries the rest of the fleet before the
    primary — the client NEVER sees a 5xx from a killed replica.

    ``primary`` is a callable ``(vectors, k, filters) -> (commit, results)``
    (typically a closure over the primary's index) — always fresh, so with a
    primary configured every query is answerable."""

    def __init__(
        self,
        endpoints: List[str],
        primary: "Optional[Callable[..., Tuple[int, List[List[tuple]]]]]" = None,
        *,
        timeout_s: float = 5.0,
        unhealthy_cooldown_s: float = 1.0,
        clock: "Callable[[], float]" = time.monotonic,
    ):
        self.endpoints = list(endpoints)
        self.primary = primary
        self.timeout_s = float(timeout_s)
        self.unhealthy_cooldown_s = float(unhealthy_cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._rr = 0
        self._unhealthy_until: Dict[str, float] = {}
        self.stats = {
            "served": 0, "replica_served": 0, "primary_served": 0,
            "failovers": 0, "sheds_seen": 0,
        }

    def _candidates(self) -> List[str]:
        now = self._clock()
        with self._lock:
            start = self._rr
            self._rr = (self._rr + 1) % max(1, len(self.endpoints))
            ordered = (
                self.endpoints[start:] + self.endpoints[:start]
            )
            return [
                ep
                for ep in ordered
                if self._unhealthy_until.get(ep, 0.0) <= now
            ]

    def _mark_unhealthy(self, endpoint: str) -> None:
        with self._lock:
            self._unhealthy_until[endpoint] = (
                self._clock() + self.unhealthy_cooldown_s
            )

    def retrieve(
        self,
        vectors: List[Any],
        k: int,
        *,
        max_staleness_s: "float | None" = None,
        filters: "List[Any] | None" = None,
    ) -> "Tuple[Optional[int], List[List[tuple]]]":
        """Serve one query batch from the fleet, failing over silently."""
        import urllib.error
        import urllib.request

        started = self._clock()
        body = json.dumps(
            {
                "vectors": [
                    [float(x) for x in vec] for vec in vectors
                ],
                "k": int(k),
                "max_staleness_s": max_staleness_s,
                "filters": filters,
            }
        ).encode()
        tried = 0
        min_retry: "Optional[float]" = None
        for endpoint in self._candidates():
            tried += 1
            try:
                req = urllib.request.Request(
                    f"{endpoint}/v1/retrieve",
                    data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                    payload = json.loads(resp.read())
            except urllib.error.HTTPError as exc:
                if exc.code == 429:
                    # an honest shed: another replica (or the primary) may be
                    # fresher — keep the smallest advertised backoff in case
                    # nothing else can answer
                    with self._lock:
                        self.stats["sheds_seen"] += 1
                    _stage_add("replica.router.shed_seen")
                    try:
                        retry = float(exc.headers.get("Retry-After") or 1)
                    except (TypeError, ValueError):
                        retry = 1.0
                    min_retry = (
                        retry if min_retry is None else min(min_retry, retry)
                    )
                else:
                    # 503 not_serving / unexpected status: out of rotation
                    self._mark_unhealthy(endpoint)
                    _stage_add("replica.router.unhealthy")
                continue
            except (OSError, ValueError) as exc:
                # connect refused / reset / timeout / torn body — the
                # kill-invisible path: absorb and move on
                self._mark_unhealthy(endpoint)
                _stage_add("replica.router.unhealthy")
                _flight_event(
                    "replica_failover",
                    endpoint=endpoint,
                    error=str(exc)[:120],
                )
                continue
            results = [
                [(key, float(score)) for key, score in row]
                for row in payload["results"]
            ]
            self._note_served(started, tried, kind="replica")
            return int(payload["commit"]), results
        if self.primary is not None:
            commit, results = self.primary(vectors, k, filters)
            self._note_served(started, tried + 1, kind="primary")
            return commit, results
        if min_retry is not None:
            raise ReplicaStaleError(float("nan"), min_retry)
        raise ReplicaUnavailableError(
            f"all {len(self.endpoints)} replica endpoint(s) are unreachable "
            "and no primary fallback is configured"
        )

    def _note_served(self, started: float, tried: int, *, kind: str) -> None:
        with self._lock:
            self.stats["served"] += 1
            self.stats[f"{kind}_served"] += 1
            failed_over = tried > 1 or kind == "primary"
            if failed_over:
                self.stats["failovers"] += 1
        _stage_add("replica.router.served")
        _stage_add(f"replica.router.{kind}_served")
        if failed_over:
            _stage_add("replica.router.failover")
            elapsed = max(0.0, self._clock() - started)
            try:
                from pathway_tpu.engine.profile import histogram

                histogram("pathway_replica_failover_seconds").observe(elapsed)
            except Exception:
                pass


# -- the fleet (supervisor side) -----------------------------------------------


class ReplicaFleet:
    """Launch and watch N replica processes next to the ingest ranks.

    Replica deaths do NOT consume the ingest restart budget — a replica is
    stateless below its feed, so a relaunch is cheap and bounded by its own
    ``PATHWAY_REPLICA_MAX_RESTARTS``. Post-mortem attribution (exit cause,
    last applied commit, staleness at death) and flight-dump preservation
    mirror the rank discipline in ``parallel/supervisor.py``."""

    def __init__(
        self,
        *,
        feed_root: str,
        supervise_dir: str,
        run_id: str,
        n: int = 1,
        base_env: "Optional[Dict[str, str]]" = None,
        autoscale: "bool | None" = None,
    ):
        self.feed_root = feed_root
        self.supervise_dir = supervise_dir
        self.run_id = run_id
        self.target_n = int(n)
        self.base_env = dict(base_env) if base_env is not None else dict(os.environ)
        self.procs: Dict[int, "subprocess.Popen[bytes]"] = {}
        self.restarts = 0
        self.max_restarts = int(
            _env_float("PATHWAY_REPLICA_MAX_RESTARTS", 10)
        )
        self.post_mortems: List[str] = []
        self._last_status: Dict[int, Dict[str, Any]] = {}
        self._controller: Any = None
        self._signal_carry: "Optional[tuple]" = None
        self._last_sample_at: "Optional[float]" = None
        if autoscale is None:
            from pathway_tpu.parallel.autoscaler import replica_autoscale_enabled

            autoscale = replica_autoscale_enabled()
        if autoscale:
            from pathway_tpu.parallel.autoscaler import (
                AutoscaleController,
                AutoscalePolicy,
            )

            policy = AutoscalePolicy.replica_from_env()
            self.target_n = max(policy.min_workers, min(policy.max_workers, self.target_n))
            self._controller = AutoscaleController(policy, self.target_n)

    # -- process plumbing ------------------------------------------------------

    def _child_env(self, replica_id: int) -> Dict[str, str]:
        env = dict(self.base_env)
        env["PATHWAY_REPLICA_ID"] = str(replica_id)
        env["PATHWAY_REPLICA_FEED"] = self.feed_root
        env["PATHWAY_REPLICA_PORT"] = env.get("PATHWAY_REPLICA_PORT", "0")
        env["PATHWAY_SUPERVISE_DIR"] = self.supervise_dir
        env["PATHWAY_RUN_ID"] = self.run_id
        env["PATHWAY_FLIGHT_RECORDER_DIR"] = os.path.join(
            self.supervise_dir, FLIGHT_SUBDIR
        )
        # replicas are serving-plane processes: never let them inherit the
        # ingest ranks' process identity or re-enter the spawn machinery
        for noise in ("PATHWAY_PROCESS_ID", "PATHWAY_RESTART_COUNT"):
            env.pop(noise, None)
        return env

    def _launch(self, replica_id: int) -> None:
        os.makedirs(
            os.path.join(self.supervise_dir, FLIGHT_SUBDIR), exist_ok=True
        )
        self.procs[replica_id] = subprocess.Popen(
            [sys.executable, "-m", "pathway_tpu.parallel.replica"],
            env=self._child_env(replica_id),
        )
        _stage_add("replica.fleet.launch")

    def start(self) -> None:
        for rid in range(self.target_n):
            if rid not in self.procs:
                self._launch(rid)

    def statuses(self) -> Dict[int, Dict[str, Any]]:
        live = read_replica_statuses(self.supervise_dir, self.target_n)
        self._last_status.update(live)
        return live

    def endpoints(self) -> List[str]:
        """Base URLs of every replica that has advertised a port."""
        out = []
        for rid in sorted(self.procs):
            status = self._last_status.get(rid) or {}
            port = status.get("port")
            if port:
                out.append(f"http://127.0.0.1:{int(port)}")
        return out

    def wait_serving(
        self, n: "int | None" = None, deadline_s: float = 240.0
    ) -> List[str]:
        """Block until ``n`` replicas report ``following`` (default: the
        whole fleet); returns their endpoints. Raises TimeoutError past the
        deadline — spawn-convergence tests budget 240 s."""
        want = self.target_n if n is None else int(n)
        deadline = time.monotonic() + float(deadline_s)
        while True:
            live = self.statuses()
            serving = [
                rid
                for rid, st in live.items()
                if st.get("state") == "following" and st.get("port")
            ]
            if len(serving) >= want:
                return self.endpoints()
            if time.monotonic() > deadline:
                states = {rid: st.get("state") for rid, st in live.items()}
                raise TimeoutError(
                    f"replica fleet did not converge: {len(serving)}/{want} "
                    f"serving after {deadline_s:.0f}s (states={states})"
                )
            self.watch_once()
            time.sleep(0.05)

    # -- death handling --------------------------------------------------------

    def _preserve_flight_dump(self, replica_id: int) -> "Optional[str]":
        import shutil
        import tempfile

        src = os.path.join(
            self.supervise_dir, FLIGHT_SUBDIR, f"flight-rank-{replica_id}.json"
        )
        if not os.path.exists(src):
            return None
        dst = os.path.join(
            tempfile.gettempdir(),
            f"pathway-flight-{self.run_id}-replica-{replica_id}.json",
        )
        try:
            shutil.copyfile(src, dst)
            return dst
        except OSError:
            return None

    def _attribute_death(self, replica_id: int, code: int) -> str:
        from pathway_tpu.parallel.supervisor import describe_exit

        status = self._last_status.get(replica_id) or {}
        staleness = status.get("staleness_s")
        dump = self._preserve_flight_dump(replica_id)
        line = (
            f"replica {replica_id}: {describe_exit(code)}; "
            f"last applied commit "
            f"{status.get('applied_commit', 'unknown')}; "
            f"staleness at death "
            f"{'unknown' if staleness is None else f'{staleness:.3f}s'}"
            + (f"; flight dump preserved at {dump}" if dump else "")
        )
        self.post_mortems.append(line)
        return line

    def watch_once(self) -> List[str]:
        """One watch tick: reap dead replicas, attribute, relaunch within
        the fleet's own budget. Returns new post-mortem lines (the
        supervisor prints them — a replica death is an EVENT, not a cluster
        failure)."""
        lines: List[str] = []
        self.statuses()
        for rid, proc in list(self.procs.items()):
            code = proc.poll()
            if code is None:
                continue
            lines.append(self._attribute_death(rid, code))
            _flight_event(
                "replica_failover", replica=rid, exit_code=code, relaunch=True
            )
            del self.procs[rid]
            try:
                os.unlink(replica_status_path(self.supervise_dir, rid))
            except OSError:
                pass
            if rid < self.target_n:
                if self.restarts < self.max_restarts:
                    self.restarts += 1
                    _stage_add("replica.fleet.relaunch")
                    self._launch(rid)
                else:
                    lines.append(
                        f"replica {rid}: relaunch budget exhausted "
                        f"({self.max_restarts}) — fleet degrades to "
                        f"{len(self.procs)} replica(s); the router's primary "
                        "fallback keeps serving"
                    )
        return lines

    # -- autoscaling -----------------------------------------------------------

    def autoscale_tick(self, now: "float | None" = None) -> "Optional[int]":
        """Drive the fleet's damped controller from the replicas' served/shed
        counters. Fleet transitions are immediate (launch/terminate a
        process) so issue and completion collapse into one tick."""
        if self._controller is None:
            return None
        if now is None:
            now = time.monotonic()
        policy = self._controller.policy
        if (
            self._last_sample_at is not None
            and now - self._last_sample_at < policy.sample_period_s
        ):
            return None
        self._last_sample_at = now
        signals, self._signal_carry = _fleet_signals(
            self.statuses(), self._signal_carry, now, self.target_n
        )
        target = self._controller.sample(now, signals)
        if target is None:
            return None
        self._controller.on_issued(target, now)
        self.scale_to(target)
        self._controller.on_complete(target, now)
        _stage_add("replica.fleet.scale")
        _flight_event("replica_failover", fleet_scaled_to=target)
        return target

    def scale_to(self, target: int) -> None:
        target = max(0, int(target))
        old = self.target_n
        self.target_n = target
        for rid in range(old, target):  # grow
            if rid not in self.procs:
                self._launch(rid)
        for rid in range(target, old):  # shrink: highest ids drain first
            proc = self.procs.pop(rid, None)
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
            try:
                os.unlink(replica_status_path(self.supervise_dir, rid))
            except OSError:
                pass

    def autoscaler_line(self) -> "Optional[str]":
        if self._controller is None:
            return None
        last = self._controller.last_decision()
        return (
            f"replica autoscaler: n={self._controller.current_n}, "
            f"state={self._controller.state}"
            + (f"; last decision: {last.kind} -> {last.target_n} ({last.reason})" if last else "")
        )

    def stop(self) -> None:
        """Terminate the fleet, preserving flight dumps first (the supervise
        dir is about to be rmtree'd)."""
        for rid, proc in list(self.procs.items()):
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + 10.0
        for rid, proc in list(self.procs.items()):
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            self._preserve_flight_dump(rid)
        self.procs.clear()


def _fleet_signals(
    statuses: Dict[int, Dict[str, Any]],
    prev: "Optional[tuple]",
    now: float,
    current_n: int,
) -> "tuple":
    """Fold replica status files into one AutoscaleSignals sample: query
    rate as ``ingest_rate`` (the controller is policy-agnostic — a rate
    against per-unit capacity), staleness sheds as ``shed_rate``."""
    from pathway_tpu.parallel.autoscaler import AutoscaleSignals

    served = 0.0
    shed = 0.0
    stable = True
    for rid in range(current_n):
        status = statuses.get(rid)
        if status is None:
            stable = False
            continue
        # a refused replica is PRESENT but out of rotation: it must not
        # freeze the controller (stable) nor add capacity (it serves nothing)
        served += float(status.get("served_total") or 0.0)
        shed += float(status.get("shed_total") or 0.0)
    carry = (now, served, shed)
    if prev is None:
        return AutoscaleSignals(stable=stable, current_n=current_n), carry
    prev_now, prev_served, prev_shed = prev
    dt = max(1e-6, now - prev_now)
    return (
        AutoscaleSignals(
            ingest_rate=max(0.0, served - prev_served) / dt,
            shed_rate=max(0.0, shed - prev_shed) / dt,
            stable=stable,
            current_n=current_n,
        ),
        carry,
    )


# -- the replica child process -------------------------------------------------


def main() -> int:
    """Entry point of one replica process (``python -m
    pathway_tpu.parallel.replica``): bootstrap, follow, serve, publish."""
    replica_id = int(_env_float("PATHWAY_REPLICA_ID", 0))
    feed_root = os.environ.get("PATHWAY_REPLICA_FEED")
    if not feed_root:
        print(
            "replica: PATHWAY_REPLICA_FEED is required (the feed root the "
            "primary exports bootstraps and frames into)",
            file=sys.stderr,
        )
        return 2
    port = int(_env_float("PATHWAY_REPLICA_PORT", 0))
    supervise_dir = os.environ.get("PATHWAY_SUPERVISE_DIR")
    bootstrap_deadline = _env_float("PATHWAY_REPLICA_BOOTSTRAP_DEADLINE_S", 240.0)

    try:
        from pathway_tpu.engine.profile import get_flight_recorder

        get_flight_recorder().configure(rank=replica_id, default_dir=None)
    except Exception:
        pass

    stop = threading.Event()

    def _on_term(signum: int, frame: Any) -> None:
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    follower = ReplicaFollower(
        ReplicaFeed(feed_root), default_index_factory, replica_id=replica_id
    )
    server = ReplicaServer(follower, port=port)

    def publish() -> None:
        if supervise_dir is None:
            return
        payload = follower.snapshot()
        payload["port"] = server.port
        payload["pid"] = os.getpid()
        payload["time"] = time.time()
        write_replica_status(supervise_dir, replica_id, payload)

    try:
        publish()
        # wait for the primary's first bootstrap export, then cold-start;
        # a TORN export is a typed refusal — stay up, out of rotation, so
        # the operator sees "refused" instead of a crash loop
        deadline = time.monotonic() + bootstrap_deadline
        while not stop.is_set():
            if follower.feed.latest_bootstrap() is None:
                # nothing exported yet: keep waiting (the primary may still
                # be warming up) — only a TORN export is a refusal
                if time.monotonic() > deadline:
                    print(
                        f"replica {replica_id}: no bootstrap export appeared "
                        f"within {bootstrap_deadline:.0f}s — refusing",
                        file=sys.stderr,
                    )
                    follower.state = "refused"
                    publish()
                    break
                stop.wait(min(0.2, follower.poll_s * 2))
                continue
            try:
                follower.bootstrap()
            except ReplicaBootstrapError:
                pass  # typed refusal: stay up, out of rotation
            publish()
            break
        publish()
        while not stop.is_set():
            if follower.state == "following":
                follower.poll_frames()
            publish()
            stop.wait(follower.poll_s)
    finally:
        try:
            follower.state = "stopped"
            publish()
        except Exception:
            pass
        server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
