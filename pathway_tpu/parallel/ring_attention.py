"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context path: the sequence dimension is sharded across devices; K/V blocks rotate
around the ring via ``lax.ppermute`` (ICI neighbor exchange) while each device keeps its
query block resident, accumulating an online (flash-style) softmax — numerically exact, with
peak memory O(seq/n_devices) per device and compute/communication overlapped by XLA.

The reference has no sequence dimension (stream-length is handled incrementally,
``SURVEY.md`` §5 "Long-context"); this module exists because our flagship compute path is a
transformer. Design follows the public ring-attention recipe (blockwise softmax
accumulation + ring permute), implemented with ``shard_map`` so XLA sees static shapes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

_NEG = -1e30


def _ring_attention_local(
    q: jax.Array,  # (B, Sq, H, D) — this device's query block
    k: jax.Array,  # (B, Sk, H, D) — this device's key block (will rotate)
    v: jax.Array,  # (B, Sk, H, D)
    kv_mask: jax.Array,  # (B, Sk) bool — valid keys (rotates with k/v)
    axis_name: str,
) -> jax.Array:
    n = lax.psum(1, axis_name)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    perm = [(j, (j + 1) % n) for j in range(n)]

    b, sq, h, d = q.shape
    acc = jnp.zeros((b, sq, h, d), dtype=jnp.float32)
    m = jnp.full((b, h, sq), _NEG, dtype=jnp.float32)
    l = jnp.zeros((b, h, sq), dtype=jnp.float32)

    def step(carry, _):
        k_blk, v_blk, mask_blk, acc, m, l = carry
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
        ) * scale
        s = jnp.where(mask_blk[:, None, None, :], s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        # keys masked out contribute exp(_NEG - m) ≈ 0 already; correction for old acc:
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32)
        )
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        mask_blk = lax.ppermute(mask_blk, axis_name, perm)
        return (k_blk, v_blk, mask_blk, acc, m_new, l), None

    (_, _, _, acc, m, l), _ = lax.scan(
        step, (k, v, kv_mask, acc, m, l), None, length=n
    )
    out = acc / jnp.maximum(l.transpose(0, 2, 1)[..., None], 1e-30)
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: Optional[jax.Array] = None,
    *,
    mesh: Mesh,
    axis: str = "model",
) -> jax.Array:
    """Exact attention with the sequence axis sharded over ``axis``.

    Args are (batch, seq, heads, head_dim); ``kv_mask`` is (batch, seq) bool. The sequence
    axis of all inputs must be divisible by the mesh axis size. Batch stays sharded over
    ``data`` if it already is.
    """
    if kv_mask is None:
        kv_mask = jnp.ones(k.shape[:2], dtype=bool)
    fn = functools.partial(_ring_attention_local, axis_name=axis)
    qspec = P("data", axis, None, None)
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(qspec, qspec, qspec, P("data", axis)),
        out_specs=qspec,
        check_vma=False,
    )(q, k, v, kv_mask)


def attention_reference(
    q: jax.Array, k: jax.Array, v: jax.Array, kv_mask: Optional[jax.Array] = None
) -> jax.Array:
    """Single-device exact attention — the oracle ring_attention must match."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)
