"""Mesh-sharded KNN: the multi-worker sharded index (BASELINE config #5).

The reference shards index rows across workers by key and exchanges query/result streams
over TCP (``src/engine/dataflow/operators/external_index.rs`` + ``shard.rs``). Here the
vector store is ONE logical ``(capacity, dim)`` array row-sharded over the ``data`` mesh
axis; a search is a ``shard_map``: each device computes a local MXU matmul + ``top_k``
over its rows, then one ``all_gather`` of (n_shards × k) candidates and a final merge
``top_k`` — the ICI all-gather top-k merge pattern.

Rows shard contiguously (NamedSharding block layout); the host allocator hands out slots
round-robin across shards so loads stay balanced the way the reference's key-hash routing
does.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from pathway_tpu.parallel.mesh import shard_map_compat
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pathway_tpu.ops.knn import SlotIngestMixin, pad_pow2, pow2_target


def _local_search(
    data: jax.Array,  # (cap_local, dim) this shard's rows
    valid: jax.Array,  # (cap_local,)
    norms: jax.Array,  # (cap_local,)
    queries: jax.Array,  # (q, dim) replicated
    k: int,
    metric: str,
    axis: str,
) -> Tuple[jax.Array, jax.Array]:
    scores = jnp.dot(queries, data.T, preferred_element_type=jnp.float32)
    if metric == "l2sq":
        qn = jnp.sum(queries * queries, axis=1, keepdims=True)
        scores = -(qn + norms[None, :] - 2.0 * scores)
    elif metric == "cos":
        qn = jnp.linalg.norm(queries, axis=1, keepdims=True)
        scores = scores / jnp.maximum(qn * jnp.sqrt(norms)[None, :], 1e-30)
    scores = jnp.where(valid[None, :], scores, -jnp.inf)
    local_scores, local_idx = lax.top_k(scores, k)  # (q, k) per shard
    shard = lax.axis_index(axis)
    # contiguous row sharding: shard s owns global rows [s * cap_local, (s+1) * cap_local)
    global_idx = shard * data.shape[0] + local_idx
    all_scores = lax.all_gather(local_scores, axis, axis=1)  # (q, n_shards, k)
    all_idx = lax.all_gather(global_idx, axis, axis=1)
    q = queries.shape[0]
    flat_scores = all_scores.reshape(q, -1)
    flat_idx = all_idx.reshape(q, -1)
    top_scores, pos = lax.top_k(flat_scores, k)
    return top_scores, jnp.take_along_axis(flat_idx, pos, axis=1)


class ShardedKNNStore(SlotIngestMixin):
    """Keyed dense vector store row-sharded over a mesh axis.

    Host API matches :class:`pathway_tpu.ops.knn.DenseKNNStore` (add/remove/search_batch)
    so the engine's external-index operator can swap it in when a mesh is configured;
    the staged-slot ingest comes from the shared :class:`SlotIngestMixin`.
    """

    def __init__(
        self,
        mesh: Mesh,
        dim: int,
        metric: str = "l2sq",
        axis: str = "data",
        initial_capacity: int = 1024,
    ):
        assert metric in ("l2sq", "cos", "ip")
        self.mesh = mesh
        self.axis = axis
        self.dim = dim
        self.metric = metric
        self.n_shards = mesh.shape[axis]
        # capacity divisible by n_shards so every shard holds capacity // n rows
        self.capacity = -(-initial_capacity // self.n_shards) * self.n_shards
        self._row_sharding = NamedSharding(mesh, P(axis, None))
        self._vec_sharding = NamedSharding(mesh, P(axis))
        self._data = jax.device_put(
            jnp.zeros((self.capacity, dim), dtype=jnp.float32), self._row_sharding
        )
        self._valid = jax.device_put(
            jnp.zeros((self.capacity,), dtype=bool), self._vec_sharding
        )
        self._norms = jax.device_put(
            jnp.zeros((self.capacity,), dtype=jnp.float32), self._vec_sharding
        )
        self.slot_of: Dict[Any, int] = {}
        self.key_of: Dict[int, Any] = {}
        self._free: List[int] = _interleaved_free_list(0, self.capacity, self.n_shards)
        self._staged_vecs: List[np.ndarray] = []
        self._staged_slots: List[int] = []
        self._staged_invalid: List[int] = []
        self._update = jax.jit(
            _apply_updates,
            donate_argnums=(0, 1, 2),
            out_shardings=(self._row_sharding, self._vec_sharding, self._vec_sharding),
        )
        self._search = None  # built lazily (depends on k/metric statics)

    def __len__(self) -> int:
        return len(self.slot_of)


    def _grow(self, target: int | None = None) -> None:
        self._flush()
        old = self.capacity
        self.capacity = pow2_target(old, target)
        extra = self.capacity - old
        self._data = jax.device_put(
            jnp.concatenate([self._data, jnp.zeros((extra, self.dim), jnp.float32)]),
            self._row_sharding,
        )
        self._valid = jax.device_put(
            jnp.concatenate([self._valid, jnp.zeros((extra,), bool)]), self._vec_sharding
        )
        self._norms = jax.device_put(
            jnp.concatenate([self._norms, jnp.zeros((extra,), jnp.float32)]),
            self._vec_sharding,
        )
        self._free = _interleaved_free_list(old, self.capacity, self.n_shards) + self._free

    def _flush(self) -> None:
        if not (self._staged_slots or self._staged_invalid):
            return
        if self._staged_slots:
            set_slots = np.array(self._staged_slots, dtype=np.int32)
            set_vecs = np.stack(self._staged_vecs).astype(np.float32)
        else:
            set_slots = np.zeros((0,), dtype=np.int32)
            set_vecs = np.zeros((0, self.dim), dtype=np.float32)
        still_invalid = [s for s in set(self._staged_invalid) if s not in self.key_of]
        inv_slots = np.array(sorted(still_invalid), dtype=np.int32)
        set_slots, set_vecs, _ = pad_pow2(set_slots, set_vecs)
        inv_slots, _, _ = pad_pow2(inv_slots)
        self._data, self._valid, self._norms = self._update(
            self._data,
            self._valid,
            self._norms,
            jnp.asarray(set_slots),
            jnp.asarray(set_vecs),
            jnp.asarray(inv_slots),
        )
        self._staged_slots, self._staged_vecs, self._staged_invalid = [], [], []

    # -- search --

    def search_batch(
        self, queries: np.ndarray, k: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        self._flush()
        if isinstance(queries, jax.Array):
            # device-resident queries feed the sharded kernel without a host bounce
            queries = queries.astype(jnp.float32).reshape(-1, self.dim)
        else:
            queries = np.asarray(queries, dtype=np.float32).reshape(-1, self.dim)
        cap_local = self.capacity // self.n_shards
        k_eff = max(1, min(k, cap_local))
        fn = shard_map_compat(
            functools.partial(
                _local_search, k=k_eff, metric=self.metric, axis=self.axis
            ),
            mesh=self.mesh,
            in_specs=(P(self.axis, None), P(self.axis), P(self.axis), P()),
            out_specs=(P(), P()),
        )
        top_scores, top_idx = jax.jit(fn)(
            self._data, self._valid, self._norms, jnp.asarray(queries)
        )
        scores = np.asarray(top_scores)
        idx = np.asarray(top_idx)
        return scores, idx, np.isfinite(scores)


def _axis_devices(mesh: Mesh, axis: str) -> List[Any]:
    """One representative device per position along ``axis`` (index 0 of every
    other mesh axis)."""
    arr = np.asarray(mesh.devices)
    ax = list(mesh.axis_names).index(axis)
    arr = np.moveaxis(arr, ax, 0)
    return list(arr.reshape(arr.shape[0], -1)[:, 0])


class ShardedIvfKnnStore:
    """Row-partitioned IVF-Flat over a mesh axis: one :class:`IvfKnnStore` per
    shard, each pinned to its own device (centroids, inverted lists, and the
    fused probe→gather→score kernel all run shard-local), with the per-shard
    top-k candidates merged into the global top-k — the same all-gather top-k
    merge contract as :class:`ShardedKNNStore`, performed host-side because the
    per-shard IVF state (assignments, CSR) is host-managed.

    Keys route round-robin to shards (the reference's key-hash balance), and
    global slot ids interleave as ``local_slot * n_shards + shard`` so the
    engine's ``key_of`` contract is preserved."""

    def __init__(
        self,
        mesh: Mesh,
        dim: int,
        metric: str = "l2sq",
        axis: str = "data",
        initial_capacity: int = 1024,
        n_clusters: int = 64,
        n_probe: int = 8,
        dtype: Any = None,
        tiered: bool = False,
        quant: "str | None" = None,
    ):
        from pathway_tpu.ops.knn_ivf import IvfKnnStore
        from pathway_tpu.ops.knn_quant import quant_mode

        # quantized blocks live in the tiered sub-stores only — the flat
        # per-shard IvfKnnStore path stays fp32, so the resolved mode must
        # say so (descriptor mode checks compare against this property)
        self._quant = quant_mode(quant) if tiered else "off"

        devices = _axis_devices(mesh, axis)
        self.mesh = mesh
        self.axis = axis
        self.dim = dim
        self.metric = metric
        self.n_shards = len(devices)
        self.tiered = bool(tiered)
        per_shard_cap = max(16, -(-initial_capacity // self.n_shards))
        if tiered:
            # one tiered sub-store per shard device, the per-chip HBM budget
            # split evenly (each shard manages its own hot set / prefetch /
            # background rebuild — the swap stays shard-local, riding each
            # shard's own commit boundary)
            from pathway_tpu.ops.knn_tiers import TieredIvfKnnStore, hbm_budget_bytes

            budget = hbm_budget_bytes()
            per_shard_budget = budget // self.n_shards if budget else 0
            self.stores: List[Any] = [
                TieredIvfKnnStore(
                    dim,
                    metric=metric,
                    initial_capacity=per_shard_cap,
                    n_clusters=n_clusters,
                    n_probe=n_probe,
                    device=dev,
                    hbm_budget_bytes=per_shard_budget,
                    quant=self._quant,
                )
                for dev in devices
            ]
        else:
            kwargs: dict = {} if dtype is None else {"dtype": dtype}
            self.stores = [
                IvfKnnStore(
                    dim,
                    metric=metric,
                    initial_capacity=per_shard_cap,
                    n_clusters=n_clusters,
                    n_probe=n_probe,
                    device=dev,
                    **kwargs,
                )
                for dev in devices
            ]
        self.slot_of: Dict[Any, int] = {}
        self.key_of: Dict[int, Any] = {}
        self._shard_of: Dict[Any, int] = {}
        self._rr = 0

    def __len__(self) -> int:
        return len(self.slot_of)

    def _shard_for(self, key: Any) -> int:
        shard = self._shard_of.get(key)
        if shard is None:
            shard = self._rr
            self._rr = (self._rr + 1) % self.n_shards
            self._shard_of[key] = shard
        return shard

    def _register(self, key: Any, shard: int) -> None:
        old = self.slot_of.pop(key, None)
        if old is not None:
            self.key_of.pop(old, None)
        gid = self.stores[shard].slot_of[key] * self.n_shards + shard
        self.slot_of[key] = gid
        self.key_of[gid] = key

    def add(self, key: Any, vector: np.ndarray) -> None:
        shard = self._shard_for(key)
        self.stores[shard].add(key, vector)
        self._register(key, shard)

    def add_many(self, keys: List[Any], vectors: np.ndarray) -> None:
        vectors = np.asarray(vectors, dtype=np.float32).reshape(len(keys), self.dim)
        by_shard: Dict[int, List[int]] = {}
        for i, key in enumerate(keys):
            by_shard.setdefault(self._shard_for(key), []).append(i)
        for shard, idxs in by_shard.items():
            self.stores[shard].add_many([keys[i] for i in idxs], vectors[idxs])
            for i in idxs:
                self._register(keys[i], shard)

    def remove(self, key: Any) -> None:
        shard = self._shard_of.pop(key, None)
        if shard is None:
            return
        self.stores[shard].remove(key)
        gid = self.slot_of.pop(key, None)
        if gid is not None:
            self.key_of.pop(gid, None)

    def _flush(self) -> None:
        for store in self.stores:
            store._flush()

    def search_batch(
        self, queries: Any, k: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        from pathway_tpu.ops.knn import topk_rows

        queries = np.asarray(queries, dtype=np.float32).reshape(-1, self.dim)
        k_eff = max(1, k)
        nq = queries.shape[0]
        parts_s: List[np.ndarray] = []
        parts_i: List[np.ndarray] = []

        def globalize(s: np.ndarray, i: np.ndarray, shard: int) -> None:
            gi = np.where(i >= 0, i * self.n_shards + shard, -1)
            if s.shape[1] < k_eff:
                pad = k_eff - s.shape[1]
                s = np.pad(s, ((0, 0), (0, pad)), constant_values=-np.inf)
                gi = np.pad(gi, ((0, 0), (0, pad)), constant_values=-1)
            parts_s.append(s[:, :k_eff])
            parts_i.append(gi[:, :k_eff])

        if jax.default_backend() == "cpu" or self.tiered:
            # host BLAS path per shard — host-bound, nothing to overlap (the
            # tiered sub-stores dispatch their own hot-block device GEMMs and
            # prefetch staging inside search_batch)
            for shard, store in enumerate(self.stores):
                s, i, _v = store.search_batch(queries, k_eff)
                globalize(s, i, shard)
        else:
            # launch EVERY shard's fused kernel before fetching any result:
            # dispatch is async, so the per-shard searches overlap across their
            # devices and batch latency is max-over-shards, not the sum
            launched = [
                store._search_device_launch(queries, k_eff)
                if store._prepare_search()
                else None
                for store in self.stores
            ]
            for shard, handle in enumerate(launched):
                if handle is None:
                    globalize(
                        np.full((nq, k_eff), -np.inf, dtype=np.float32),
                        np.full((nq, k_eff), -1, dtype=np.int64),
                        shard,
                    )
                else:
                    s, i = jax.device_get(handle)
                    globalize(s, i.astype(np.int64), shard)
        scores, idx = topk_rows(
            np.concatenate(parts_s, axis=1), np.concatenate(parts_i, axis=1), k_eff
        )
        return scores, idx, np.isfinite(scores)

    @property
    def quant(self) -> str:
        return self._quant

    def quant_state(self) -> Dict[str, Any]:
        """Aggregated quantization sidecar snapshot across shards — each
        sub-store's per-cluster scales keyed by ``"shard:cluster"`` so the
        descriptor contract stays flat while shard-local recalibration
        history survives the round-trip."""
        if self._quant == "off" or not self.tiered:
            return {"mode": "off"}
        clusters: Dict[str, Any] = {}
        for shard, store in enumerate(self.stores):
            state = store.quant_state()
            if state.get("mode") == "off":
                continue
            for cid, entry in state.get("clusters", {}).items():
                clusters[f"{shard}:{cid}"] = entry
        return {"mode": self._quant, "dtype": "int8", "clusters": clusters}

    def export_rows(self) -> Tuple[List[Any], np.ndarray]:
        """Every live (key, vector) pair across all shards — the rebuildable-
        descriptor contract shared with the single-chip stores."""
        keys: List[Any] = []
        parts: List[np.ndarray] = []
        for store in self.stores:
            shard_keys, shard_vecs = store.export_rows()
            keys.extend(shard_keys)
            if len(shard_keys):
                parts.append(np.asarray(shard_vecs, dtype=np.float32))
        if not parts:
            return keys, np.zeros((0, self.dim), dtype=np.float32)
        return keys, np.concatenate(parts)


def _interleaved_free_list(start: int, stop: int, n_shards: int) -> List[int]:
    """Free slots ordered so successive pops cycle shards (pop takes from the end)."""
    span = stop - start
    per_shard = span // n_shards
    order = [
        start + shard * per_shard + i
        for i in range(per_shard)
        for shard in range(n_shards)
    ]
    order.extend(range(start + per_shard * n_shards, stop))
    return order[::-1]


def _apply_updates(
    data: jax.Array,
    valid: jax.Array,
    norms: jax.Array,
    set_slots: jax.Array,
    set_vecs: jax.Array,
    inv_slots: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    data = data.at[set_slots].set(set_vecs, mode="drop")
    norms = norms.at[set_slots].set(jnp.sum(set_vecs * set_vecs, axis=1), mode="drop")
    valid = valid.at[set_slots].set(True, mode="drop")
    valid = valid.at[inv_slots].set(False, mode="drop")
    return data, valid, norms
