"""Supervised cluster launcher — the ``pathway_tpu spawn`` parent process.

Parity target: timely/differential's supervised-worker model (a worker failure
is a handled EVENT, not a hang) — and the r4 torture lesson that recovery by
"kill everything and restart from the journal" works, automated here so the
operator no longer is the supervisor.

The spawn parent launches one child per rank and then watches two signals:

- **exit codes** — a nonzero or signal-killed child is a cluster failure
  (surviving ranks fail loudly themselves via the typed
  ``PeerShutdownError``/``PeerTimeoutError`` barrier errors in
  ``parallel/cluster.py``); a rank that exits 0 while its peers keep running
  past the drain grace is a failure too — lockstep shutdown lands clean exits
  together, so a lone straggler means the program quit one rank early and the
  cluster is incomplete;
- **heartbeat staleness** — each worker's commit loop writes a per-rank status
  file (``write_status``) under ``PATHWAY_SUPERVISE_DIR``; a rank whose status
  goes stale while its process is alive is wedged and gets killed. The same
  payload backs the worker's ``/healthz`` endpoint, so the supervisor and
  external probes share one liveness signal.

On failure, the supervisor escalates down a three-rung ladder:

- **surgical single-rank restart** (``--restart-mode surgical``, the default
  with ``--max-restarts`` > 0 and more than one rank): only the dead rank is
  relaunched, with ``PATHWAY_RESTART_COUNT`` bumped, ``PATHWAY_CLUSTER_EPOCH``
  advanced, and ``PATHWAY_CLUSTER_REJOIN=1``; survivors quiesce at the mesh's
  epoch fence instead of dying (``parallel/cluster.py``), take the
  replacement's re-dial, and recover bounded-time: survivors undo only the
  interrupted commit in place (incremental rewind) or fall back to replaying
  their journal tail, while the replacement cold-starts from the latest
  cluster checkpoint manifest + journal tail (``engine/runner.py``) — seven
  healthy workers of a ``spawn -n 8`` keep their processes, sockets, and
  warmed state, and rejoin latency stays flat however long the run has been
  up. A rejoin that does not converge within
  ``PATHWAY_SUPERVISOR_REJOIN_DEADLINE_S`` (default: the mesh fence timeout
  + 30 s) gets its replacement shot and escalates down the ladder;
- **restarts the cluster** — when surgical rejoin is off or itself fails
  (second concurrent death, dropped rejoin handshake, fence timeout, rejoin
  deadline) and the budget remains: survivors are torn down and all ranks
  relaunch with ``PATHWAY_RESTART_COUNT`` bumped; the restarted workers
  restore the latest cluster checkpoint (when one was committed) and replay
  the union of journaled commit ids past it in lockstep (the engine's resume
  path), i.e. a cluster-wide rollback-resume from the last fully journaled
  commit; or
- **tears down loudly** — persistence off, no reports, or budget exhausted:
  every survivor is terminated and a per-rank post-mortem (exit cause, last
  commit, epoch at death, heartbeat age, who killed it) goes to stderr, and
  the exit code is nonzero. Never a hang.

Both restart rungs require persistence on (the journal is the rollback
substrate); each relaunch — surgical or full — consumes one unit of the
``--max-restarts`` budget.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from pathway_tpu.internals.config import env_float as _env_float


def _default_stale_after() -> float:
    """Status files refresh once per commit, and a commit may legitimately sit
    inside an exchange barrier for the mesh's full deadline — so the wedge
    bound must EXCEED the barrier timeout or slow-but-healthy clusters get
    killed (and, restarted, deterministically killed again)."""
    return _env_float("PATHWAY_BARRIER_TIMEOUT_S", 300.0) + 60.0


# a rank that never reports at all (wedged before its first commit — e.g. a
# deadlock during import or a giant journal load) gets a separate, generous
# startup deadline; 0 disables
DEFAULT_STARTUP_GRACE_S = 600.0

# after a failure is detected, give the surviving ranks a moment to fail on
# their OWN typed barrier errors (PeerShutdownError/PeerTimeoutError propagate
# within the socket-close latency) before SIGTERMing them — post-mortems then
# record real exit causes, not "terminated by supervisor"
DEFAULT_DRAIN_S = 10.0

_STATUS_PREFIX = "rank-"
_STATUS_SUFFIX = ".status.json"


def status_path(supervise_dir: str, rank: int) -> str:
    return os.path.join(supervise_dir, f"{_STATUS_PREFIX}{rank}{_STATUS_SUFFIX}")


def write_status(
    supervise_dir: str,
    rank: int,
    *,
    commit: int,
    persistence: bool,
    peers: "Dict[str, float] | None" = None,
    epoch: int = 0,
    state: str = "running",
    restarts: int = 0,
    last_rejoin_s: "float | None" = None,
    checkpoint_commit: "int | None" = None,
    journal_tail_frames: "int | None" = None,
    extra: "Dict[str, Any] | None" = None,
) -> None:
    """Atomically publish one worker's liveness record. Called from the commit
    loop (throttled there), so recency == the loop is actually turning; a
    background thread here would defeat wedge detection. The fence path also
    publishes (``state`` = "fencing"/"rejoining") so a quiesced survivor stays
    visibly healthy and the supervisor can time the rejoin."""
    payload = {
        "pid": os.getpid(),
        "rank": rank,
        "commit": commit,
        "persistence": bool(persistence),
        "peers": peers or {},
        "epoch": int(epoch),
        "state": state,
        "restarts": int(restarts),
        "last_rejoin_s": last_rejoin_s,
        # recovery-SLO fields (coordinated checkpoints): what the next rejoin
        # would cost — its checkpoint base and the journal tail past it
        "checkpoint_commit": checkpoint_commit,
        "journal_tail_frames": journal_tail_frames,
        # elastic-membership fields (membership_state, current/target worker
        # counts, commit/refusal markers, mismatch reports) ride here
        **(extra or {}),
        "ts": time.time(),
    }
    path = status_path(supervise_dir, rank)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except OSError:
        # liveness reporting must never kill the worker (dir vanished mid-teardown)
        try:
            os.unlink(tmp)
        except OSError:
            pass


def read_statuses(supervise_dir: str, n: int) -> Dict[int, dict]:
    out: Dict[int, dict] = {}
    for rank in range(n):
        try:
            with open(status_path(supervise_dir, rank)) as f:
                out[rank] = json.load(f)
        except (OSError, ValueError):
            continue
    return out


def describe_exit(code: "int | None") -> str:
    if code is None:
        return "running"
    if code < 0:
        try:
            name = signal.Signals(-code).name
        except ValueError:
            name = str(-code)
        return f"killed by signal {name}"
    return f"exit code {code}"


class Supervisor:
    """Launch, monitor, and (with persistence) restart a spawn cluster."""

    def __init__(
        self,
        *,
        processes: int,
        threads: int,
        first_port: int,
        program: str,
        arguments: "List[str] | tuple",
        env_base: Dict[str, str],
        max_restarts: int = 0,
        restart_mode: str = "surgical",
        stale_after_s: "float | None" = None,
        poll_interval_s: float = 0.2,
        scale_plan: "List[dict] | None" = None,
        control_port: "int | None" = None,
        autoscale: "bool | None" = None,
        replicas: "int | None" = None,
        replica_feed: "str | None" = None,
    ):
        if restart_mode not in ("surgical", "all"):
            raise ValueError(
                f"restart_mode must be 'surgical' or 'all', got {restart_mode!r}"
            )
        self.n = processes
        self.threads = threads
        self.first_port = first_port
        self.program = program
        self.arguments = list(arguments)
        self.env_base = env_base
        self.max_restarts = max_restarts
        self.restart_mode = restart_mode
        # monotonically increasing mesh incarnation: bumped on EVERY relaunch
        # (surgical or full) and handed to children via PATHWAY_CLUSTER_EPOCH;
        # survivors of a surgical restart adopt it from the rejoin handshake
        self.cluster_epoch = 0
        # (rank, started_at, target_epoch) while a surgical rejoin is in
        # flight; a second failure in this window degrades to restart-all
        self._rejoining: "Optional[tuple]" = None
        self.last_rejoin_s: "float | None" = None
        # hard bound on a surgical rejoin: past it the replacement is killed
        # and recovery escalates to restart-all. Defaults past the mesh fence
        # timeout so parked survivors fail typed FIRST (deterministic order);
        # tests/operators set it low to fail a wedged rejoin fast. 0 disables.
        self.rejoin_deadline_s = _env_float(
            "PATHWAY_SUPERVISOR_REJOIN_DEADLINE_S",
            _env_float("PATHWAY_FENCE_TIMEOUT_S", 180.0) + 30.0,
        )
        if stale_after_s is None:
            stale_after_s = _env_float(
                "PATHWAY_SUPERVISOR_STALE_S", _default_stale_after()
            )
        self.stale_after_s = stale_after_s
        self.startup_grace_s = _env_float(
            "PATHWAY_SUPERVISOR_STARTUP_S", DEFAULT_STARTUP_GRACE_S
        )
        self.poll_interval_s = poll_interval_s
        self.restarts_used = 0
        self.handles: List[subprocess.Popen] = []
        self._terminated_by_us: "set[int]" = set()
        self._killed_for_staleness: "set[int]" = set()
        self._clean_exit_at: Dict[int, float] = {}  # rank -> first seen rc==0
        self._supervise_dir: Optional[str] = None
        # elastic membership (parallel/membership.py): scale requests arrive
        # from --scale / PATHWAY_SCALE_PLAN entries or the control endpoint,
        # become a DIRECTIVE file the workers agree on at a commit boundary,
        # and (for a grow) joiner processes launched into the live mesh
        if scale_plan is None:
            raw = os.environ.get("PATHWAY_SCALE_PLAN")
            try:
                scale_plan = list(json.loads(raw)) if raw else []
            except ValueError:
                self._log(f"ignoring malformed PATHWAY_SCALE_PLAN: {raw!r}")
                scale_plan = []
        self.scale_plan = [dict(e) for e in scale_plan]
        self._scale_generation = 0
        #: (directive, started_at) while a membership transition is in flight
        self._transition: "Optional[tuple]" = None
        self._drained_ranks: "set[int]" = set()  # leavers that exited cleanly
        self.membership_deadline_s = _env_float(
            "PATHWAY_MEMBERSHIP_DEADLINE_S",
            _env_float("PATHWAY_FENCE_TIMEOUT_S", 180.0) + 60.0,
        )
        self.last_reshard_s: "float | None" = None
        self._control_port = control_port
        #: actual bound port once the endpoint is up (--control-port 0 lets
        #: the OS pick; tests read this)
        self.control_port: "Optional[int]" = None
        self._control_listener: "Optional[socket.socket]" = None
        self._scale_requests: List[int] = []
        self._scale_lock = threading.Lock()
        self._last_statuses: Dict[int, dict] = {}
        # closed-loop autoscaler (parallel/autoscaler.py): samples the
        # workers' status-file signals each poll and drives request_scale
        # through the SAME directive path as the operator surfaces — capacity
        # follows load with no human in the loop
        from pathway_tpu.parallel.autoscaler import (
            AutoscaleController,
            AutoscalePolicy,
            autoscale_enabled,
        )

        if autoscale is None:
            autoscale = autoscale_enabled()
        self.autoscaler: "Optional[AutoscaleController]" = (
            AutoscaleController(AutoscalePolicy.from_env(), processes)
            if autoscale
            else None
        )
        self._signal_carry: "Optional[tuple]" = None
        self._last_autoscale_sample = 0.0
        self._autoscaler_flap_logged = False
        self._autoscaler_written_gen = -1
        # read-replica serving fleet (parallel/replica.py): query-plane
        # processes launched NEXT TO the ingest ranks, following the replica
        # feed. A replica death is an event the fleet heals from its own
        # relaunch budget — it never consumes the ingest restart budget and
        # never fails the cluster
        if replicas is None:
            replicas = int(_env_float("PATHWAY_REPLICAS", 0))
        self.replicas = int(replicas)
        self.replica_feed = replica_feed or os.environ.get("PATHWAY_REPLICA_FEED")
        self.replica_fleet: "Optional[Any]" = None

    def _surgical_enabled(self) -> bool:
        # n == 1 has no survivors to keep alive — surgical degenerates to
        # restart-all there, so don't bother with the rejoin machinery
        return self.restart_mode == "surgical" and self.max_restarts > 0 and self.n > 1

    def _child_env(self, process_id: int) -> Dict[str, str]:
        env = self.env_base.copy()
        env["PATHWAY_THREADS"] = str(self.threads)
        env["PATHWAY_PROCESSES"] = str(self.n)
        env["PATHWAY_FIRST_PORT"] = str(self.first_port)
        env["PATHWAY_PROCESS_ID"] = str(process_id)
        env["PATHWAY_RUN_ID"] = self._run_id
        env["PATHWAY_SUPERVISE_DIR"] = self._supervise_dir
        env["PATHWAY_RESTART_COUNT"] = str(self.restarts_used)
        env["PATHWAY_CLUSTER_EPOCH"] = str(self.cluster_epoch)
        if self._surgical_enabled():
            # workers fence-and-wait on a peer death instead of dying typed
            env["PATHWAY_RESTART_MODE"] = "surgical"
        return env

    # -- lifecycle -------------------------------------------------------------

    def _log(self, msg: str) -> None:
        print(f"pathway supervisor: {msg}", file=sys.stderr, flush=True)

    def _launch(self) -> None:
        assert self._supervise_dir is not None
        # stale status files from the previous incarnation must not trip the
        # staleness monitor against freshly launched ranks
        for rank in range(self.n):
            try:
                os.unlink(status_path(self._supervise_dir, rank))
            except OSError:
                pass
        self._run_id = str(uuid.uuid4())
        self.handles = []
        self._terminated_by_us = set()
        self._killed_for_staleness = set()
        self._clean_exit_at = {}
        self._drained_ranks = set()
        self._launched_at = time.monotonic()
        for process_id in range(self.n):
            self.handles.append(
                subprocess.Popen(
                    [self.program, *self.arguments], env=self._child_env(process_id)
                )
            )

    def _relaunch_rank(self, rank: int) -> None:
        """Surgical restart: relaunch ONLY the dead rank, with the bumped
        restart count, the next cluster epoch, and the rejoin flag — the
        replacement dials back into the survivors' open listeners instead of
        rewiring the whole mesh."""
        assert self._supervise_dir is not None
        try:
            os.unlink(status_path(self._supervise_dir, rank))
        except OSError:
            pass
        self._terminated_by_us.discard(rank)
        self._killed_for_staleness.discard(rank)
        self._clean_exit_at.pop(rank, None)
        # a fresh startup-grace window for the replacement (and a conservative
        # staleness holiday for fencing survivors, who publish status anyway)
        self._launched_at = time.monotonic()
        env = self._child_env(rank)
        env["PATHWAY_CLUSTER_REJOIN"] = "1"
        self.handles[rank] = subprocess.Popen(
            [self.program, *self.arguments], env=env
        )

    # -- elastic membership ----------------------------------------------------

    def _start_control_endpoint(self) -> None:
        """Tiny line-protocol control endpoint: operators (or an external
        autoscaler) drive the running cluster without restarting it.

        Commands (one per connection, newline-terminated):

        - ``scale N``  -> ``ok`` (request queued; the directive path decides)
        - ``status``   -> one JSON line: topology, membership state,
          transition/rejoin flags, autoscale-controller state + last decision
        - anything else answers ``err <reason>`` — a malformed command is
          never silently dropped."""
        if self._control_port is None:
            return
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", self._control_port))
        listener.listen(8)
        self.control_port = listener.getsockname()[1]
        self._control_listener = listener

        def handle(line: str) -> bytes:
            parts = line.split()
            if not parts:
                return b"err empty command (try: scale N | status)\n"
            if parts[0] == "scale":
                if len(parts) != 2:
                    return b"err usage: scale N\n"
                try:
                    target = int(parts[1])
                except ValueError:
                    return (
                        f"err scale target must be an integer, got "
                        f"{parts[1]!r}\n".encode()
                    )
                with self._scale_lock:
                    self._scale_requests.append(target)
                return b"ok\n"
            if parts[0] == "status":
                return (
                    json.dumps(self._control_status(), sort_keys=True) + "\n"
                ).encode()
            return f"err unknown command {parts[0]!r}\n".encode()

        def serve() -> None:
            while True:
                try:
                    conn, _addr = listener.accept()
                except OSError:
                    return  # listener closed (teardown)
                try:
                    conn.settimeout(5.0)
                    line = b""
                    while not line.endswith(b"\n") and len(line) < 256:
                        chunk = conn.recv(256)
                        if not chunk:
                            break
                        line += chunk
                    conn.sendall(handle(line.decode("utf-8", "replace").strip()))
                except OSError:
                    pass
                finally:
                    try:
                        conn.close()
                    except OSError:
                        pass

        threading.Thread(
            target=serve, daemon=True, name="pathway:supervisor-control"
        ).start()

    def _control_status(self) -> Dict[str, Any]:
        """Read-only snapshot for the ``status`` control command."""
        statuses = self._last_statuses
        return {
            "n": self.n,
            "cluster_epoch": self.cluster_epoch,
            "restarts_used": self.restarts_used,
            "transition_in_flight": self._transition is not None,
            "rejoining": self._rejoining is not None,
            "membership_state": {
                str(rank): s.get("membership_state")
                for rank, s in sorted(statuses.items())
            },
            "autoscaler": (
                self.autoscaler.as_dict() if self.autoscaler is not None else None
            ),
        }

    def request_scale(self, target_n: int, origin: str = "operator") -> bool:
        """Issue a MEMBERSHIP_CHANGE directive (and launch joiners for a
        grow). Returns False when the request is invalid or one is already
        in flight. ``origin`` attributes the decision ("operator" surfaces vs
        the "autoscaler" loop) for refusal feedback and post-mortems."""
        from pathway_tpu.parallel.membership import (
            MembershipDirective,
            write_directive,
        )

        if self._transition is not None:
            self._log(
                f"scale request to n={target_n} ignored: a membership "
                "transition is already in flight"
            )
            return False
        if self._rejoining is not None:
            self._log(
                f"scale request to n={target_n} deferred: a surgical rejoin "
                "is in flight (re-request once the cluster is stable)"
            )
            return False
        if target_n == self.n:
            return False
        if target_n < 2 or self.n < 2:
            self._log(
                f"scale request to n={target_n} refused: elastic membership "
                "needs a live mesh on both sides (n >= 2)"
            )
            return False
        assert self._supervise_dir is not None
        self._scale_generation += 1
        self.cluster_epoch += 1
        directive = MembershipDirective(
            self._scale_generation, target_n, self.cluster_epoch, self.n,
            origin=origin,
        )
        write_directive(self._supervise_dir, directive)
        self._transition = (directive, time.monotonic())
        self._drained_ranks = set()
        self._log(
            f"membership change requested: n={self.n} -> n={target_n} "
            f"(generation {directive.generation}, epoch {directive.epoch})"
        )
        if target_n > self.n:
            for rank in range(self.n, target_n):
                self.handles.append(self._launch_joiner(rank, directive))
        return True

    def _launch_joiner(self, rank: int, directive: Any) -> subprocess.Popen:
        env = self._child_env(rank)
        env["PATHWAY_PROCESSES"] = str(directive.target_n)
        env["PATHWAY_CLUSTER_EPOCH"] = str(directive.epoch)
        env["PATHWAY_MEMBERSHIP_JOIN"] = "1"
        env["PATHWAY_MEMBERSHIP_FROM"] = str(directive.from_n)
        # a reused joiner rank index must not inherit a previous incarnation's
        # kill attribution (a refused transition terminated it by design)
        self._terminated_by_us.discard(rank)
        self._killed_for_staleness.discard(rank)
        self._clean_exit_at.pop(rank, None)
        self._drained_ranks.discard(rank)
        try:
            os.unlink(status_path(self._supervise_dir, rank))
        except OSError:
            pass
        self._log(f"launching joiner rank {rank} (target n={directive.target_n})")
        return subprocess.Popen([self.program, *self.arguments], env=env)

    def _drive_autoscaler(self, statuses: Dict[int, dict]) -> None:
        """One control-loop tick: aggregate the workers' published signals,
        let the controller decide, and issue the decision through the SAME
        directive path the operator surfaces use. The controller's damping
        (cooldowns, hysteresis, refusal backoff, flap lock) lives in
        ``parallel/autoscaler.py``; this method only feeds and obeys it."""
        ctrl = self.autoscaler
        if ctrl is None or self._supervise_dir is None:
            return
        now = time.monotonic()
        if now - self._last_autoscale_sample < ctrl.policy.sample_period_s:
            return
        self._last_autoscale_sample = now
        from pathway_tpu.parallel.autoscaler import aggregate_signals, write_state

        signals, self._signal_carry = aggregate_signals(
            statuses, self._signal_carry, now, self.n
        )
        if self._rejoining is not None or self._transition is not None:
            # the recovery ladder / an in-flight transition owns the cluster
            signals.stable = False
        target = ctrl.sample(now, signals)
        if target is not None:
            if self.request_scale(target, origin="autoscaler"):
                ctrl.on_issued(target, now)
                decision = ctrl.last_decision()
                self._log(
                    f"autoscaler: scaling n={signals.current_n or self.n} -> "
                    f"n={target} ({decision.reason if decision else 'decision'})"
                )
            else:
                ctrl.on_deferred(now)
        if ctrl.flap_locked and not self._autoscaler_flap_logged:
            self._autoscaler_flap_logged = True
            decision = ctrl.last_decision()
            self._log(
                "autoscaler FLAP-LOCKED: holding at n="
                f"{self.n} and alerting instead of oscillating — "
                f"{decision.reason if decision else ''} (resize manually via "
                "the control endpoint if the load pattern is real)"
            )
        # export controller state for the workers' /healthz mirror + triage —
        # only when it CHANGED (the generation exists to detect exactly this;
        # steady "watching" must not cost a file write per sample forever)
        if ctrl.generation != self._autoscaler_written_gen:
            write_state(self._supervise_dir, ctrl, now)
            self._autoscaler_written_gen = ctrl.generation

    def _poll_scale_requests(self, statuses: Dict[int, dict]) -> None:
        """Feed pending control-endpoint requests and due scale-plan entries
        into :meth:`request_scale`. Plan entries are only consumed when the
        request was actually issued (a rejoin-in-flight defers them)."""
        if self._rejoining is None:
            with self._scale_lock:
                requests, self._scale_requests = self._scale_requests, []
            for target in requests:
                self.request_scale(target)
        if (
            self._transition is not None
            or self._rejoining is not None
            or not self.scale_plan
        ):
            return
        max_commit = max(
            (int(s.get("commit", 0) or 0) for s in statuses.values()), default=0
        )
        entry = self.scale_plan[0]
        if max_commit >= int(entry.get("after_commit", 0)):
            self.scale_plan.pop(0)
            self.request_scale(int(entry["n"]))

    def _watch_transition(self, statuses: Dict[int, dict]) -> "Optional[tuple]":
        """Track an in-flight membership transition: adopt the new topology
        on convergence, unwind a refusal, or shoot a wedged transition past
        the deadline. Returns a failure tuple only for the wedged case."""
        from pathway_tpu.parallel.membership import clear_directive

        if self._transition is None:
            return None
        directive, started_at = self._transition
        # a REFUSED transition (non-reshardable graph/sources) is not a
        # failure: unwind and keep the cluster running at its current size
        for rank, s in statuses.items():
            refused = s.get("membership_refused")
            if refused and int(refused[0]) == directive.generation:
                self._log(
                    f"membership change to n={directive.target_n} refused by "
                    f"rank {rank}: {refused[1]}"
                )
                if self.autoscaler is not None and directive.origin == "autoscaler":
                    # typed refusal feedback: the controller backs off this
                    # direction instead of hammering the transition path
                    self.autoscaler.on_refused(
                        directive.target_n, str(refused[1]), time.monotonic()
                    )
                    refusal = self.autoscaler.last_refusal
                    self._log(
                        f"autoscaler: {type(refusal).__name__}: {refusal}"
                    )
                for jr in range(directive.from_n, len(self.handles)):
                    handle = self.handles[jr]
                    if handle.poll() is None:
                        self._terminated_by_us.add(jr)
                        try:
                            handle.terminate()
                        except OSError:
                            pass
                        try:
                            handle.wait(timeout=10)
                        except subprocess.TimeoutExpired:
                            handle.kill()
                            handle.wait()
                del self.handles[directive.from_n:]
                clear_directive(self._supervise_dir)
                self._transition = None
                return None
        # convergence: every member of the NEW topology is stable at the
        # directive's epoch, and every leaver exited cleanly (drained)
        members_done = all(
            rank in statuses
            and int(statuses[rank].get("epoch", 0) or 0) >= directive.epoch
            and statuses[rank].get("membership_state") == "stable"
            and int(statuses[rank].get("current_workers", 0) or 0)
            == directive.target_n
            for rank in range(directive.target_n)
        )
        leavers_done = all(
            self.handles[rank].poll() == 0
            for rank in range(directive.target_n, len(self.handles))
        )
        if members_done and leavers_done:
            self.last_reshard_s = time.monotonic() - started_at
            for rank in range(directive.target_n, len(self.handles)):
                self._drained_ranks.discard(rank)
                self._clean_exit_at.pop(rank, None)
                try:
                    os.unlink(status_path(self._supervise_dir, rank))
                except OSError:
                    pass
            del self.handles[directive.target_n:]
            self.n = directive.target_n
            clear_directive(self._supervise_dir)
            self._transition = None
            if self.autoscaler is not None:
                self.autoscaler.on_complete(self.n, time.monotonic())
            self._log(
                f"membership change complete: cluster is n={self.n} at epoch "
                f"{directive.epoch} ({self.last_reshard_s:.1f}s)"
            )
            return None
        if (
            self.membership_deadline_s > 0
            and time.monotonic() - started_at > self.membership_deadline_s
        ):
            return (
                0,
                f"membership transition to n={directive.target_n} did not "
                f"converge within {self.membership_deadline_s:.0f}s "
                "(PATHWAY_MEMBERSHIP_DEADLINE_S)",
            )
        return None

    def _drain(self) -> None:
        """Briefly wait for survivors to exit on their own typed errors."""
        deadline = time.monotonic() + _env_float(
            "PATHWAY_SUPERVISOR_DRAIN_S", DEFAULT_DRAIN_S
        )
        while time.monotonic() < deadline:
            if all(h.poll() is not None for h in self.handles):
                return
            time.sleep(0.05)

    def _terminate_all(self) -> None:
        for rank, handle in enumerate(self.handles):
            if handle.poll() is None:
                self._terminated_by_us.add(rank)
                try:
                    handle.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + 10
        for handle in self.handles:
            try:
                handle.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    handle.kill()
                except OSError:
                    pass
                handle.wait()

    def _drive_replica_fleet(self) -> None:
        """One replica-fleet tick inside the watch loop: reap+relaunch dead
        replicas (their post-mortems print immediately — a replica death is
        handled, not fatal) and drive the fleet's own autoscaler."""
        fleet = self.replica_fleet
        if fleet is None:
            return
        for line in fleet.watch_once():
            self._log(f"replica fleet: {line}")
        fleet.autoscale_tick()

    def _watch(self) -> "Optional[tuple]":
        """Block until the cluster finishes or fails.

        Returns None when every rank exited 0; otherwise ``(rank, reason)`` for
        the first observed failure (nonzero/signal exit, or a wedged rank the
        supervisor had to kill for heartbeat staleness)."""
        assert self._supervise_dir is not None
        while True:
            any_alive = False
            statuses = read_statuses(self._supervise_dir, len(self.handles))
            self._last_statuses = statuses
            up_for = time.monotonic() - self._launched_at
            self._drive_autoscaler(statuses)
            self._drive_replica_fleet()
            self._poll_scale_requests(statuses)
            wedged_transition = self._watch_transition(statuses)
            if wedged_transition is not None:
                # a membership transition that will not converge: shoot the
                # whole cluster and let run() recover down the ladder
                for rank, handle in enumerate(self.handles):
                    if handle.poll() is None:
                        self._kill_wedged(rank, handle)
                return wedged_transition
            if self._rejoining is not None:
                rejoin_rank, started_at, target_epoch = self._rejoining
                if len(statuses) == self.n and all(
                    int(s.get("epoch", 0) or 0) >= target_epoch
                    for s in statuses.values()
                ):
                    self.last_rejoin_s = time.monotonic() - started_at
                    self._log(
                        f"rank {rejoin_rank} rejoined the cluster at epoch "
                        f"{target_epoch} in {self.last_rejoin_s:.1f}s"
                    )
                    self._rejoining = None
                elif (
                    self.rejoin_deadline_s > 0
                    and time.monotonic() - started_at > self.rejoin_deadline_s
                ):
                    # a wedged rejoin must not strand the fenced survivors for
                    # the full fence/staleness bounds: shoot the replacement
                    # and let run() escalate to restart-all (checkpoint+journal
                    # rollback-resume), the next rung down the recovery ladder
                    self._kill_wedged(rejoin_rank, self.handles[rejoin_rank])
                    return (
                        rejoin_rank,
                        f"rejoin did not converge within "
                        f"{self.rejoin_deadline_s:.0f}s "
                        "(PATHWAY_SUPERVISOR_REJOIN_DEADLINE_S); replacement "
                        "killed as wedged",
                    )
            for rank, handle in enumerate(self.handles):
                rc = handle.poll()
                if rc is None:
                    any_alive = True
                    status = statuses.get(rank)
                    if status is not None:
                        age = time.time() - status.get("ts", 0)
                        if (
                            self.stale_after_s > 0
                            and age > self.stale_after_s
                            and up_for > self.stale_after_s
                        ):
                            self._kill_wedged(rank, handle)
                            return (
                                rank,
                                f"heartbeat stale ({age:.0f}s); killed as wedged",
                            )
                    elif self.startup_grace_s > 0 and up_for > self.startup_grace_s:
                        # never reported at all: wedged before its first commit
                        self._kill_wedged(rank, handle)
                        return (
                            rank,
                            f"no status report within {self.startup_grace_s:.0f}s "
                            "of launch; killed as wedged at startup",
                        )
                elif rc != 0:
                    return (rank, describe_exit(rc))
                elif self._is_expected_drain(rank, statuses):
                    # a scale-down leaver exiting 0 after its handoff is the
                    # PLANNED outcome, not a cluster failure
                    if rank not in self._drained_ranks:
                        self._drained_ranks.add(rank)
                        self._log(
                            f"rank {rank} drained for scale-down (handoff "
                            "durable, journal shard compacted) and exited "
                            "cleanly"
                        )
                else:
                    self._clean_exit_at.setdefault(rank, time.monotonic())
            if not any_alive:
                return None
            # a rank that exited 0 while its peers keep running is a cluster
            # event too: lockstep shutdown means clean exits land together, so
            # a lone rc==0 straggler (rank-conditional sys.exit in the program)
            # would otherwise strand fenced survivors for the full fence
            # timeout waiting on a replacement that never launches. The drain
            # window absorbs the normal millisecond exit stagger.
            grace = _env_float("PATHWAY_SUPERVISOR_DRAIN_S", DEFAULT_DRAIN_S)
            for rank, first_seen in self._clean_exit_at.items():
                if self._is_expected_drain(rank, statuses):
                    continue  # scale-down leaver: planned exit
                if time.monotonic() - first_seen > grace:
                    return (
                        rank,
                        "exited 0 while peers kept running — the cluster is "
                        "incomplete",
                    )
            time.sleep(self.poll_interval_s)

    def _is_expected_drain(self, rank: int, statuses: Dict[int, dict]) -> bool:
        """Clean exit of a rank >= the in-flight shrink target, or a rank
        whose last status reports it drained: planned, not a failure."""
        if rank in self._drained_ranks:
            return True
        status = statuses.get(rank, {})
        if status.get("membership_state") == "drained":
            return True
        if self._transition is not None:
            directive = self._transition[0]
            return directive.target_n < directive.from_n and rank >= directive.target_n
        return False

    def _kill_wedged(self, rank: int, handle: subprocess.Popen) -> None:
        """Stall-kill: SIGTERM first with a short grace so the worker's
        flight-recorder SIGTERM hook can dump its black box (a rank wedged in
        a barrier ``Condition.wait`` still runs Python signal handlers), then
        SIGKILL — the wedge bound already expired, this must not hang."""
        self._terminated_by_us.add(rank)
        self._killed_for_staleness.add(rank)
        try:
            handle.terminate()
        except OSError:
            pass
        try:
            handle.wait(
                timeout=_env_float("PATHWAY_SUPERVISOR_TERM_GRACE_S", 2.0)
            )
            return
        except subprocess.TimeoutExpired:
            pass  # truly wedged (stuck in C); no dump will come
        try:
            handle.kill()
        except OSError:
            pass
        handle.wait()

    def _adapt_topology_after_failure(self, statuses: Dict[int, dict]) -> None:
        """Pick the worker count the next restart-all must use. The
        membership manifest is a transition's atomic commit point: once any
        rank reported it committed (or a relaunched rank hit the store's
        typed :class:`MembershipMismatchError` and published the manifest's
        count), recovery MUST run at the new topology — the old ranks'
        checkpoints were superseded by the handoff fragments."""
        from pathway_tpu.parallel.membership import clear_directive

        adopted: "Optional[int]" = None
        if self._transition is not None and self.autoscaler is not None:
            # a crash raced the directive: the recovery ladder owns the
            # cluster — the controller holds until it reports stable again
            self.autoscaler.on_aborted("transition aborted by a failure", time.monotonic())
        if self._transition is not None:
            directive, _started = self._transition
            if any(
                s.get("membership_committed") == directive.generation
                for s in statuses.values()
            ):
                adopted = directive.target_n
            self._transition = None
            clear_directive(self._supervise_dir)
            self._log(
                "in-flight membership transition aborted by the failure; "
                + (
                    f"its manifest committed — recovering at n={adopted}"
                    if adopted is not None
                    else f"its manifest never committed — recovering at n={self.n}"
                )
            )
        for s in statuses.values():
            mw = s.get("manifest_workers")
            if mw:
                # a relaunched child refused the store typed: the manifest
                # names the authoritative count
                adopted = int(mw)
        if adopted is not None and adopted != self.n:
            self._log(
                f"adapting to the committed membership topology: n={self.n} "
                f"-> n={adopted}"
            )
            self.n = adopted

    # -- reporting -------------------------------------------------------------

    def _flight_dump_line(self, rank: int) -> "Optional[str]":
        """Locate rank's flight-recorder dump and render the one-line summary
        (last commit, slowest operator, pending barrier). Dumps written into
        the supervise dir are about to be rmtree'd with it, so those are
        preserved to the system temp dir first — a post-mortem that points at
        a deleted file is useless."""
        flight_dir = os.environ.get("PATHWAY_FLIGHT_RECORDER_DIR") or self._supervise_dir
        if flight_dir is None:
            return None
        path = os.path.join(flight_dir, f"flight-rank-{rank}.json")
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return None
        if self._supervise_dir is not None and path.startswith(self._supervise_dir):
            kept = os.path.join(
                tempfile.gettempdir(),
                f"pathway-flight-{self._run_id}-rank-{rank}.json",
            )
            try:
                shutil.copyfile(path, kept)
                path = kept
            except OSError:
                pass
        from pathway_tpu.engine.profile import flight_summary_line

        return f"flight recorder {path}: {flight_summary_line(payload)}"

    def _post_mortem(self, failure: tuple, statuses: Dict[int, dict], why_final: str) -> None:
        failed_rank, reason = failure
        self._log(f"cluster FAILED — rank {failed_rank}: {reason}")
        now = time.time()
        for rank, handle in enumerate(self.handles):
            status = statuses.get(rank)
            rc = handle.poll()
            parts = [describe_exit(rc)]
            # attribute the kill: operators triaging a post-mortem need to know
            # whether the supervisor shot this rank or something external
            # (chaos plan, OOM killer, an operator's kill -9) got it first —
            # and a scale-down leaver's clean exit is PLANNED, not a crash
            if rank in self._drained_ranks or (
                status is not None and status.get("membership_state") == "drained"
            ):
                parts.append("drained for scale-down (planned exit, handoff durable)")
            if rank in self._killed_for_staleness:
                parts.append("killed by supervisor for staleness")
            elif rank in self._terminated_by_us:
                parts.append("terminated by supervisor")
            elif rc is not None and rc < 0:
                parts.append("signal was external (chaos plan or operator)")
            if status is not None:
                parts.append(f"last commit {status.get('commit')}")
                parts.append(f"epoch {status.get('epoch', 0)} at death")
                parts.append(f"heartbeat {now - status.get('ts', now):.1f}s ago")
                parts.append(
                    "persistence on" if status.get("persistence") else "persistence off"
                )
                if status.get("state") not in (None, "running"):
                    parts.append(f"state {status.get('state')}")
                # what a recovery of this rank would cost: checkpoint base +
                # journal tail past it (no checkpoint -> full-history replay)
                if status.get("checkpoint_commit") is not None:
                    tail = status.get("journal_tail_frames")
                    parts.append(
                        f"last cluster checkpoint at commit "
                        f"{status['checkpoint_commit']}"
                        + (f" (+{tail} journal tail frame(s))" if tail is not None else "")
                    )
                elif status.get("persistence"):
                    parts.append("no cluster checkpoint (full-journal recovery)")
                # a refused scale names WHAT refused: the node kind(s) the
                # preflight could not re-partition, not a generic mismatch
                refused_nodes = status.get("membership_refusals") or []
                if refused_nodes:
                    kinds = sorted(
                        {str(r.get("kind", "?")) for r in refused_nodes}
                    )
                    first = refused_nodes[0].get("reason", "")
                    parts.append(
                        "preflight refused node kind(s) "
                        + "/".join(kinds)
                        + (f": {first}" if first else "")
                    )
            else:
                parts.append("no status report")
            flight = self._flight_dump_line(rank)
            if flight is not None:
                parts.append(flight)
            self._log(f"  post-mortem rank {rank}: " + ", ".join(parts))
        if self.autoscaler is not None:
            # the controller's side of the story: its state, the last
            # decision, and any TYPED refusal (AutoscaleRefusedError) so a
            # scale-up the preflight vote refused is triaged from here
            ctrl = self.autoscaler
            bits = [f"state {ctrl.state}", f"n={ctrl.current_n}"]
            decision = ctrl.last_decision()
            if decision is not None:
                bits.append(
                    f"last decision {decision.kind} -> n={decision.target_n} "
                    f"({decision.reason})"
                )
            if ctrl.last_refusal is not None:
                bits.append(
                    f"{type(ctrl.last_refusal).__name__}: {ctrl.last_refusal}"
                )
            self._log("  post-mortem autoscaler: " + ", ".join(bits))
        if self.replica_fleet is not None:
            # replica-kind processes are attributed DISTINCTLY from ranks:
            # exit cause, last applied commit, staleness at death — and their
            # flight dumps were preserved past supervise-dir cleanup
            fleet = self.replica_fleet
            fleet.watch_once()
            for line in fleet.post_mortems:
                self._log(f"  post-mortem {line}")
            for rid, st in sorted(fleet.statuses().items()):
                staleness = st.get("staleness_s")
                self._log(
                    f"  post-mortem replica {rid}: {st.get('state')}, "
                    f"applied commit {st.get('applied_commit')}, staleness "
                    + (
                        "unknown"
                        if staleness is None
                        else f"{float(staleness):.3f}s"
                    )
                )
            scaler = fleet.autoscaler_line()
            if scaler is not None:
                self._log(f"  post-mortem {scaler}")
        # where the dying run actually spent its time: merge whatever trace
        # files + flight-dump trace partials the ranks left behind and name
        # the critical-path span (engine/tracing.py one-liner)
        trace_dir = (
            os.environ.get("PATHWAY_FLIGHT_RECORDER_DIR")
            or self._supervise_dir
        )
        if trace_dir is not None:
            try:
                from pathway_tpu.engine.tracing import critical_path_line

                cp = critical_path_line(trace_dir)
            except Exception:
                cp = None
            if cp is not None:
                self._log(f"  post-mortem critical path: {cp}")
        self._log(f"not restarting: {why_final}")

    # -- entry point -----------------------------------------------------------

    def run(self) -> int:
        """Supervise until clean completion (0) or final failure (nonzero)."""
        self._supervise_dir = tempfile.mkdtemp(prefix="pathway-supervise-")
        try:
            self._start_control_endpoint()
            self._launch()
            if self.replicas > 0 and self.replica_feed:
                from pathway_tpu.parallel.replica import ReplicaFleet

                self.replica_fleet = ReplicaFleet(
                    feed_root=self.replica_feed,
                    supervise_dir=self._supervise_dir,
                    run_id=self._run_id,
                    n=self.replicas,
                    base_env=self.env_base,
                )
                self.replica_fleet.start()
            elif self.replicas > 0:
                self._log(
                    f"--replicas {self.replicas} requested but no replica "
                    "feed is configured (PATHWAY_REPLICA_FEED); the fleet "
                    "would have nothing to bootstrap from — not launching"
                )
            while True:
                failure = self._watch()
                if failure is None:
                    return 0
                failed_rank = failure[0]
                statuses = read_statuses(self._supervise_dir, len(self.handles))
                # restart only when the journal can actually restore the work:
                # every reporting rank ran with persistence on (a rank that died
                # before its first commit simply has no report and no journal
                # entries to lose — the others' journals still replay)
                persistence_on = bool(statuses) and all(
                    s.get("persistence") for s in statuses.values()
                )
                if (
                    self._surgical_enabled()
                    and persistence_on
                    and self.restarts_used < self.max_restarts
                    # a failure while a rejoin is still in flight (second
                    # concurrent death, dead replacement, dropped handshake)
                    # means surgical recovery is not converging: fall through
                    # to restart-all
                    and self._rejoining is None
                    # a death DURING a membership transition cannot be healed
                    # rank-surgically — the topology itself is in flight:
                    # restart-all at whichever topology the membership
                    # manifest committed (adapted below)
                    and self._transition is None
                    and self.handles[failed_rank].poll() is not None
                ):
                    self.restarts_used += 1
                    self.cluster_epoch += 1
                    self._rejoining = (
                        failed_rank,
                        time.monotonic(),
                        self.cluster_epoch,
                    )
                    self._log(
                        f"rank {failed_rank} died ({failure[1]}); surgically "
                        f"relaunching rank {failed_rank} only (attempt "
                        f"{self.restarts_used}/{self.max_restarts}, epoch "
                        f"{self.cluster_epoch}) — survivors hold at the epoch "
                        "fence"
                    )
                    self._relaunch_rank(failed_rank)
                    continue
                self._drain()
                statuses = read_statuses(self._supervise_dir, len(self.handles))
                persistence_on = bool(statuses) and all(
                    s.get("persistence") for s in statuses.values()
                )
                self._terminate_all()
                self._adapt_topology_after_failure(statuses)
                if not persistence_on:
                    self._post_mortem(
                        failure,
                        statuses,
                        "persistence is off (or no rank reported); the journal "
                        "cannot restore lost state — rerun with a persistence "
                        "backend to enable failover",
                    )
                    return self._exit_code(failure)
                if self.restarts_used >= self.max_restarts:
                    self._post_mortem(
                        failure,
                        statuses,
                        f"restart budget exhausted ({self.restarts_used} used, "
                        f"--max-restarts {self.max_restarts})",
                    )
                    return self._exit_code(failure)
                if self._rejoining is not None:
                    self._log(
                        f"surgical rejoin of rank {self._rejoining[0]} failed "
                        f"({failure[1]} on rank {failed_rank}); falling back to "
                        "restart-all"
                    )
                    self._rejoining = None
                self.restarts_used += 1
                self.cluster_epoch += 1
                last_commit = max(
                    (s.get("commit", 0) for s in statuses.values()), default=0
                )
                self._log(
                    f"rank {failure[0]} died ({failure[1]}); restarting the cluster "
                    f"(attempt {self.restarts_used}/{self.max_restarts}), rolling "
                    f"back to the last fully journaled commit (≤ {last_commit})"
                )
                self._launch()
        finally:
            if self.replica_fleet is not None:
                # flight dumps are preserved to the temp dir inside stop(),
                # BEFORE the supervise dir (their home) is rmtree'd below
                try:
                    self.replica_fleet.stop()
                except Exception as exc:
                    self._log(f"replica fleet: stop failed during teardown: {exc}")
            self._terminate_all()
            if self._control_listener is not None:
                try:
                    self._control_listener.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    self._control_listener.close()
                except OSError:
                    pass
            if self._supervise_dir is not None:
                shutil.rmtree(self._supervise_dir, ignore_errors=True)

    def _exit_code(self, failure: tuple) -> int:
        codes = [h.returncode for h in self.handles if h.returncode not in (None, 0)]
        for code in codes:
            if 0 < code < 256:
                return code
        return 1
