"""Closed-loop autoscaler: capacity follows load without an operator.

ROADMAP item 1(a): PR 11 made the cluster elastic (``spawn --scale N`` /
``--control-port scale N``) but a human still had to notice overload and type
the command. This module closes the loop: a supervisor-resident controller
samples the signals the workers already publish through their status files
(ingest rate, shed counters, barrier-wait seconds, commit-duration p99,
brownout rung), computes a target worker count through a DAMPED policy, and
drives it through the existing membership-directive path
(:meth:`~pathway_tpu.parallel.supervisor.Supervisor.request_scale`).

The controller state machine was modeled FIRST (``autoscaler_model`` in
``internals/protocol_models.py``, the PR-9 discipline) and the invariants
proven there are the contract this module implements:

- **never two concurrent transitions** — a decision is only issued while no
  membership transition (and no surgical rejoin) is in flight;
- **cooldown respected** — the cooldown window is measured from the last
  issued transition in ANY direction (its length chosen by the new
  decision's direction), so consecutive transitions can never land closer
  than the shorter window, however noisy the signals;
- **refusal never retried within its backoff** — a scale-up the preflight
  vote REFUSED (non-reshardable graph) is typed, recorded, and retried at
  most once per ``refusal_backoff_s`` window instead of hammering the
  transition path;
- **shed-before-scale** — an overload-driven scale-up only fires after the
  brownout ladder (``engine/brownout.py``) has been engaged for
  ``shed_first_s`` (cheap degradation is spent before an expensive reshard
  pause);
- **wrong-safe recovery** — a transition that dies mid-flight defers to the
  PR-2/3/6/11 recovery ladder; the controller resumes only after the cluster
  reports ``running`` at a committed topology.

A **flap counter** watches decision reversals (up followed by down or vice
versa within ``flap_window_s``): after ``flap_reversals`` of them the
controller locks into *hold-and-alert* — no further transitions, a loud log
line, and the lock visible in ``/healthz`` (the supervisor exports controller
state to ``autoscaler.json`` in the supervise dir; workers mirror it).

The controller itself is PURE — time and signals are injected, it owns no
threads or locks — so the model, the unit tests, and the supervisor's poll
loop all drive the same code.

Env knobs (all prefixed ``PATHWAY_AUTOSCALE``):

====================================  =========  ===============================
``PATHWAY_AUTOSCALE``                 ``off``    ``on`` enables the loop
``PATHWAY_AUTOSCALE_MIN``             2          floor worker count
``PATHWAY_AUTOSCALE_MAX``             8          ceiling worker count
``PATHWAY_AUTOSCALE_ROWS_PER_WORKER`` 500        target ingest rows/s per worker
``PATHWAY_AUTOSCALE_SAMPLE_S``        1.0        control-loop sample period
``PATHWAY_AUTOSCALE_BAND``            0.25       hysteresis band around target
``PATHWAY_AUTOSCALE_UP_SAMPLES``      3          consecutive samples above band
``PATHWAY_AUTOSCALE_DOWN_SAMPLES``    6          consecutive samples below band
``PATHWAY_AUTOSCALE_UP_COOLDOWN_S``   20         min gap between scale-ups
``PATHWAY_AUTOSCALE_DOWN_COOLDOWN_S`` 45         min gap between scale-ins
``PATHWAY_AUTOSCALE_REFUSAL_BACKOFF_S`` 120      refused-direction backoff
``PATHWAY_AUTOSCALE_FLAP_WINDOW_S``   300        reversal-counting window
``PATHWAY_AUTOSCALE_FLAP_REVERSALS``  3          reversals before flap-lock
``PATHWAY_AUTOSCALE_SHED_FIRST_S``    3          brownout dwell before
                                                 overload-driven scale-up
====================================  =========  ===============================
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from pathway_tpu.internals.config import env_float as _env_float

#: controller state file in the supervise dir — workers mirror it into
#: ``/healthz`` and the flight recorder so flap-locks and decisions are
#: visible from inside the cluster, not only in the supervisor's log
STATE_FILE = "autoscaler.json"


def autoscale_enabled() -> bool:
    return os.environ.get("PATHWAY_AUTOSCALE", "off").lower() in (
        "on", "1", "true", "yes",
    )


def replica_autoscale_enabled() -> bool:
    """The read-replica fleet (``parallel/replica.py``) scales through the
    SAME damped controller, gated separately — query capacity and ingest
    capacity are independent axes."""
    return os.environ.get("PATHWAY_REPLICA_AUTOSCALE", "off").lower() in (
        "on", "1", "true", "yes",
    )


class AutoscaleRefusedError(RuntimeError):
    """A controller-issued scale-up was REFUSED by the cluster's preflight
    capability vote (non-reshardable graph state). Typed so supervisor
    post-mortems and tests can triage the refusal without string matching;
    carries the refused target and the workers' reason."""

    def __init__(self, target_n: int, reason: str):
        self.target_n = int(target_n)
        self.reason = reason
        super().__init__(
            f"autoscaler scale-up to n={target_n} refused by the preflight "
            f"vote: {reason} — backing off instead of retrying (the graph "
            "cannot be resharded; see the membership follow-ons in ROADMAP)"
        )


@dataclass
class AutoscalePolicy:
    """Damping parameters of the control loop (see module docstring)."""

    min_workers: int = 2
    max_workers: int = 8
    rows_per_worker: float = 500.0
    sample_period_s: float = 1.0
    band: float = 0.25
    up_samples: int = 3
    down_samples: int = 6
    up_cooldown_s: float = 20.0
    down_cooldown_s: float = 45.0
    refusal_backoff_s: float = 120.0
    flap_window_s: float = 300.0
    flap_reversals: int = 3
    shed_first_s: float = 3.0

    @classmethod
    def from_env(cls) -> "AutoscalePolicy":
        return cls(
            min_workers=int(_env_float("PATHWAY_AUTOSCALE_MIN", 2)),
            max_workers=int(_env_float("PATHWAY_AUTOSCALE_MAX", 8)),
            rows_per_worker=_env_float("PATHWAY_AUTOSCALE_ROWS_PER_WORKER", 500.0),
            sample_period_s=_env_float("PATHWAY_AUTOSCALE_SAMPLE_S", 1.0),
            band=_env_float("PATHWAY_AUTOSCALE_BAND", 0.25),
            up_samples=int(_env_float("PATHWAY_AUTOSCALE_UP_SAMPLES", 3)),
            down_samples=int(_env_float("PATHWAY_AUTOSCALE_DOWN_SAMPLES", 6)),
            up_cooldown_s=_env_float("PATHWAY_AUTOSCALE_UP_COOLDOWN_S", 20.0),
            down_cooldown_s=_env_float("PATHWAY_AUTOSCALE_DOWN_COOLDOWN_S", 45.0),
            refusal_backoff_s=_env_float(
                "PATHWAY_AUTOSCALE_REFUSAL_BACKOFF_S", 120.0
            ),
            flap_window_s=_env_float("PATHWAY_AUTOSCALE_FLAP_WINDOW_S", 300.0),
            flap_reversals=int(_env_float("PATHWAY_AUTOSCALE_FLAP_REVERSALS", 3)),
            shed_first_s=_env_float("PATHWAY_AUTOSCALE_SHED_FIRST_S", 3.0),
        )

    @classmethod
    def replica_from_env(cls) -> "AutoscalePolicy":
        """Replica-fleet flavor of the same controller: ``rows_per_worker``
        reads as target QUERIES/s per replica, and the cooldowns are short —
        launching a replica is a cheap cold start from the feed, not a
        reshard pause, and a staleness shed is its overload signal (so
        ``shed_first_s`` is 0: a shedding fleet scales immediately)."""
        return cls(
            min_workers=int(_env_float("PATHWAY_REPLICA_AUTOSCALE_MIN", 1)),
            max_workers=int(_env_float("PATHWAY_REPLICA_AUTOSCALE_MAX", 4)),
            rows_per_worker=_env_float("PATHWAY_REPLICA_AUTOSCALE_QPS", 200.0),
            sample_period_s=_env_float("PATHWAY_REPLICA_AUTOSCALE_SAMPLE_S", 1.0),
            band=_env_float("PATHWAY_REPLICA_AUTOSCALE_BAND", 0.25),
            up_samples=int(_env_float("PATHWAY_REPLICA_AUTOSCALE_UP_SAMPLES", 3)),
            down_samples=int(
                _env_float("PATHWAY_REPLICA_AUTOSCALE_DOWN_SAMPLES", 8)
            ),
            up_cooldown_s=_env_float("PATHWAY_REPLICA_AUTOSCALE_UP_COOLDOWN_S", 5.0),
            down_cooldown_s=_env_float(
                "PATHWAY_REPLICA_AUTOSCALE_DOWN_COOLDOWN_S", 30.0
            ),
            refusal_backoff_s=_env_float(
                "PATHWAY_REPLICA_AUTOSCALE_REFUSAL_BACKOFF_S", 60.0
            ),
            flap_window_s=_env_float(
                "PATHWAY_REPLICA_AUTOSCALE_FLAP_WINDOW_S", 120.0
            ),
            flap_reversals=int(
                _env_float("PATHWAY_REPLICA_AUTOSCALE_FLAP_REVERSALS", 3)
            ),
            shed_first_s=_env_float("PATHWAY_REPLICA_AUTOSCALE_SHED_FIRST_S", 0.0),
        )


@dataclass
class AutoscaleSignals:
    """One aggregated sample of the cluster's load signals."""

    ingest_rate: float = 0.0  # cluster-wide rows/s over the sample window
    shed_rate: float = 0.0  # embed.shed + rest.shed increments/s
    barrier_frac: float = 0.0  # barrier-wait seconds per wall second per rank
    commit_p99_s: float = 0.0  # worst rank's commit-duration p99
    brownout_level: int = 0  # deepest engaged brownout rung across ranks
    stable: bool = True  # every member running/stable at one topology
    current_n: int = 0  # live worker count per the status files


def aggregate_signals(
    statuses: Dict[int, dict],
    prev: "Optional[tuple]",
    now: float,
    current_n: int,
) -> "tuple[AutoscaleSignals, tuple]":
    """Fold per-rank status files into one :class:`AutoscaleSignals` sample.

    Rate signals are deltas of the cumulative counters each worker publishes
    under its ``autoscale`` status key (``engine/profile.py:
    autoscale_signals``) against the previous sample's totals — ``prev`` is
    the opaque carry returned by the last call (None on the first)."""
    input_rows = 0.0
    shed = 0.0
    barrier_s = 0.0
    commit_p99 = 0.0
    brownout = 0
    stable = bool(statuses)
    for rank in range(current_n):
        status = statuses.get(rank)
        if status is None:
            stable = False
            continue
        if status.get("membership_state") not in (None, "stable"):
            stable = False
        if status.get("state") not in (None, "running"):
            stable = False
        sig = status.get("autoscale") or {}
        input_rows += float(sig.get("input_rows") or 0.0)
        shed += float(sig.get("shed") or 0.0)
        barrier_s += float(sig.get("barrier_wait_s") or 0.0)
        commit_p99 = max(commit_p99, float(sig.get("commit_p99_s") or 0.0))
        brownout = max(brownout, int(sig.get("brownout_level") or 0))
    carry = (now, input_rows, shed, barrier_s)
    if prev is None:
        return (
            AutoscaleSignals(
                stable=stable, current_n=current_n, brownout_level=brownout,
                commit_p99_s=commit_p99,
            ),
            carry,
        )
    prev_now, prev_rows, prev_shed, prev_barrier = prev
    dt = max(1e-6, now - prev_now)
    # a restarted/resharded worker resets its counters: clamp deltas at 0 so
    # one relaunch cannot read as a negative (or absurd) rate
    return (
        AutoscaleSignals(
            ingest_rate=max(0.0, input_rows - prev_rows) / dt,
            shed_rate=max(0.0, shed - prev_shed) / dt,
            barrier_frac=max(0.0, barrier_s - prev_barrier)
            / dt
            / max(1, current_n),
            commit_p99_s=commit_p99,
            brownout_level=brownout,
            stable=stable,
            current_n=current_n,
        ),
        carry,
    )


@dataclass
class AutoscaleDecision:
    """One issued (or refused/locked) controller decision, for the log."""

    at: float
    kind: str  # "scale_up" | "scale_down" | "flap_lock" | "refusal_backoff"
    target_n: int
    reason: str


class AutoscaleController:
    """The damped control loop (pure: time and signals are injected).

    Drive it with :meth:`sample` once per poll; it returns a target worker
    count exactly when a transition should be issued, else None. Feed the
    transition's outcome back through :meth:`on_issued` / :meth:`on_refused`
    / :meth:`on_complete` / :meth:`on_aborted` — the controller will not issue
    again until the cluster is stable at a committed topology."""

    def __init__(self, policy: AutoscalePolicy, initial_n: int):
        self.policy = policy
        self.current_n = int(initial_n)
        self.state = "watching"  # watching|transition_in_flight|flap_locked
        self.flap_locked = False
        self.decisions: List[AutoscaleDecision] = []
        self.last_refusal: "Optional[AutoscaleRefusedError]" = None
        self.generation = 0  # bumps on every state/decision change (healthz)
        self._above_streak = 0
        self._below_streak = 0
        # the last issued transition in ANY direction: the cooldown window is
        # measured from here (its LENGTH is per the new decision's direction),
        # so two transitions can never land closer than the shorter window —
        # the exact consecutive-directive invariant autoscaler_model proves
        self._last_issue_at: "Optional[float]" = None
        self._refused_until: "Optional[float]" = None
        self._brownout_since: "Optional[float]" = None
        self._in_flight_target: "Optional[int]" = None
        self._await_stable = False
        self._last_signals: "Optional[AutoscaleSignals]" = None

    # -- the control loop ------------------------------------------------------

    def sample(self, now: float, signals: AutoscaleSignals) -> "Optional[int]":
        """One control-loop tick. Returns the target worker count to issue a
        MEMBERSHIP_CHANGE for, or None (hold)."""
        policy = self.policy
        self._last_signals = signals
        if signals.current_n:
            self.current_n = signals.current_n
        if self.flap_locked:
            return None
        if self._in_flight_target is not None:
            return None  # max one transition in flight, by construction
        if self._await_stable or not signals.stable:
            # a transition died mid-flight (or the cluster is mid-recovery):
            # the recovery ladder owns the cluster until every member reports
            # running at one committed topology
            if signals.stable:
                self._await_stable = False
                self._bump()
            else:
                return None
        # track how long the brownout ladder has been engaged (shed-first)
        if signals.brownout_level > 0 or signals.shed_rate > 0:
            if self._brownout_since is None:
                self._brownout_since = now
        else:
            self._brownout_since = None
        # -- desired size from the rate signal (requests-per-replica policy) --
        capacity = self.current_n * policy.rows_per_worker
        if signals.ingest_rate > capacity * (1.0 + policy.band):
            self._above_streak += 1
            self._below_streak = 0
        elif signals.ingest_rate < capacity * (1.0 - policy.band):
            self._below_streak += 1
            self._above_streak = 0
        else:
            self._above_streak = 0
            self._below_streak = 0
        overload = (
            signals.shed_rate > 0
            and self._brownout_since is not None
            and now - self._brownout_since >= policy.shed_first_s
        )
        target: "Optional[int]" = None
        direction: "Optional[str]" = None
        if self._above_streak >= policy.up_samples or overload:
            desired = self._desired_for_rate(signals.ingest_rate)
            target = max(desired, self.current_n + 1)
            direction = "up"
        elif self._below_streak >= policy.down_samples:
            desired = self._desired_for_rate(signals.ingest_rate)
            if desired < self.current_n:
                target = desired
                direction = "down"
        if target is None or direction is None:
            return None
        target = max(self.policy.min_workers, min(self.policy.max_workers, target))
        if target == self.current_n:
            return None
        # -- damping: cooldowns, refusal backoff, flap lock -------------------
        cooldown = (
            policy.up_cooldown_s if direction == "up" else policy.down_cooldown_s
        )
        if (
            self._last_issue_at is not None
            and now - self._last_issue_at < cooldown
        ):
            return None
        if (
            direction == "up"
            and self._refused_until is not None
            and now < self._refused_until
        ):
            # typed backoff: a refused scale-up retries at most once per
            # backoff window, never in a storm against the preflight vote
            return None
        if self._flap_check(now, direction):
            return None
        kind = "scale_up" if direction == "up" else "scale_down"
        reason = (
            f"overload (shed_rate={signals.shed_rate:.1f}/s, brownout rung "
            f"{signals.brownout_level})"
            if direction == "up" and overload and self._above_streak < policy.up_samples
            else (
                f"ingest {signals.ingest_rate:.0f} rows/s vs capacity "
                f"{capacity:.0f} ({self.current_n} x "
                f"{policy.rows_per_worker:.0f})"
            )
        )
        self.decisions.append(AutoscaleDecision(now, kind, target, reason))
        self._above_streak = 0
        self._below_streak = 0
        return target

    def _desired_for_rate(self, rate: float) -> int:
        import math

        per = max(1e-9, self.policy.rows_per_worker)
        desired = math.ceil(rate / per)
        return max(self.policy.min_workers, min(self.policy.max_workers, desired))

    def _flap_check(self, now: float, direction: str) -> bool:
        """True when issuing ``direction`` now would be (or already is) a
        flap-lock: count direction REVERSALS among recent issued decisions."""
        window = [
            d
            for d in self.decisions
            if d.kind in ("scale_up", "scale_down")
            and now - d.at <= self.policy.flap_window_s
        ]
        dirs = [("up" if d.kind == "scale_up" else "down") for d in window]
        dirs.append(direction)
        reversals = sum(1 for a, b in zip(dirs, dirs[1:]) if a != b)
        if reversals >= self.policy.flap_reversals:
            self.flap_locked = True
            self.state = "flap_locked"
            self.decisions.append(
                AutoscaleDecision(
                    now,
                    "flap_lock",
                    self.current_n,
                    f"{reversals} direction reversal(s) within "
                    f"{self.policy.flap_window_s:.0f}s — holding at "
                    f"n={self.current_n} until an operator intervenes",
                )
            )
            self._bump()
            return True
        return False

    # -- transition feedback ---------------------------------------------------

    def on_issued(self, target_n: int, now: float) -> None:
        """The supervisor accepted the decision and wrote the directive."""
        self._in_flight_target = int(target_n)
        self.state = "transition_in_flight"
        self._last_issue_at = now
        self._bump()

    def on_deferred(self, now: float) -> None:
        """The supervisor could not issue the decision right now (a surgical
        rejoin in flight, a race with a just-started transition): drop the
        recorded decision so a deferral never counts against the flap window."""
        if self.decisions and self.decisions[-1].kind in (
            "scale_up", "scale_down",
        ):
            self.decisions.pop()

    def on_refused(self, target_n: int, reason: str, now: float) -> None:
        """The preflight vote refused the transition: record the TYPED
        refusal, arm the backoff, and stop retrying inside it."""
        self.last_refusal = AutoscaleRefusedError(target_n, reason)
        self._refused_until = now + self.policy.refusal_backoff_s
        self._in_flight_target = None
        self.state = "watching"
        self.decisions.append(
            AutoscaleDecision(
                now,
                "refusal_backoff",
                int(target_n),
                f"preflight refused: {reason[:160]} — next attempt not before "
                f"{self.policy.refusal_backoff_s:.0f}s",
            )
        )
        self._bump()

    def on_complete(self, new_n: int, now: float) -> None:
        self.current_n = int(new_n)
        self._in_flight_target = None
        self.state = "flap_locked" if self.flap_locked else "watching"
        self._bump()

    def on_aborted(self, reason: str, now: float) -> None:
        """The transition died mid-flight (crash racing the directive): the
        recovery ladder owns the cluster now; hold until it reports stable."""
        self._in_flight_target = None
        self._await_stable = True
        self.state = "flap_locked" if self.flap_locked else "watching"
        self._bump()

    def _bump(self) -> None:
        self.generation += 1

    # -- reporting -------------------------------------------------------------

    def last_decision(self) -> "Optional[AutoscaleDecision]":
        return self.decisions[-1] if self.decisions else None

    def as_dict(self, now: "float | None" = None) -> Dict[str, Any]:
        """Observability export. ``now`` must be the same injected clock the
        controller is driven with (falls back to ``time.monotonic()``, the
        supervisor's clock) — the backoff-remaining field is computed against
        it."""
        if now is None:
            now = time.monotonic()
        last = self.last_decision()
        signals = self._last_signals
        return {
            "state": self.state,
            "generation": self.generation,
            "current_n": self.current_n,
            "flap_locked": self.flap_locked,
            "in_flight_target": self._in_flight_target,
            "awaiting_stable": self._await_stable,
            # seconds REMAINING in the refusal backoff (operator-readable),
            # not the raw monotonic deadline
            "refused_until_in_s": (
                None
                if self._refused_until is None
                else round(max(0.0, self._refused_until - now), 1)
            ),
            "last_refusal": (
                None
                if self.last_refusal is None
                else {
                    "target_n": self.last_refusal.target_n,
                    "reason": str(self.last_refusal)[:240],
                    "type": type(self.last_refusal).__name__,
                }
            ),
            "last_decision": (
                None
                if last is None
                else {
                    "at": last.at,
                    "kind": last.kind,
                    "target_n": last.target_n,
                    "reason": last.reason,
                }
            ),
            "signals": (
                None
                if signals is None
                else {
                    "ingest_rate": round(signals.ingest_rate, 1),
                    "shed_rate": round(signals.shed_rate, 2),
                    "barrier_frac": round(signals.barrier_frac, 4),
                    "commit_p99_s": round(signals.commit_p99_s, 4),
                    "brownout_level": signals.brownout_level,
                    "stable": signals.stable,
                }
            ),
        }


# -- state-file plumbing (supervisor writes, workers mirror) -------------------


def state_path(supervise_dir: str) -> str:
    return os.path.join(supervise_dir, STATE_FILE)


def write_state(
    supervise_dir: str,
    controller: AutoscaleController,
    now: "float | None" = None,
) -> None:
    """Atomically export the controller state for the workers' ``/healthz``
    mirror (and operator triage while the cluster is live). ``now`` is the
    controller's driving clock (see :meth:`AutoscaleController.as_dict`)."""
    path = state_path(supervise_dir)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(controller.as_dict(now), f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def read_state(supervise_dir: "str | None") -> "Optional[Dict[str, Any]]":
    if not supervise_dir:
        return None
    try:
        with open(state_path(supervise_dir)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
