"""Key-hash exchange: the dataflow ``Exchange`` pact on a device mesh.

The reference routes rows to workers by the low bits of the 128-bit key
(``src/engine/dataflow/shard.rs:15-20``) over timely's TCP/shared-memory channels. Here the
same routing becomes an on-device bucketed ``all_to_all`` over ICI: rows are bucketed by
``shard = key.lo & (n_shards - 1)``, padded to a fixed per-bucket capacity (XLA static
shapes), and exchanged in one collective. Host-side connectors instead pre-route with
:func:`shard_of_keys` before device upload (cheaper when data is already on the host).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from pathway_tpu.parallel.mesh import shard_map_compat
from jax.sharding import Mesh, PartitionSpec as P

from pathway_tpu.internals.keys import KEY_DTYPE, shard_of


def shard_of_keys(keys: np.ndarray, n_shards: int) -> np.ndarray:
    """Host-side routing: worker/shard id per key (low bits, reference parity)."""
    return shard_of(keys, n_shards)


def _bucket_counts(shard_ids: jax.Array, n_shards: int) -> jax.Array:
    return jnp.sum(shard_ids[None, :] == jnp.arange(n_shards)[:, None], axis=1)


@partial(jax.jit, static_argnames=("n_shards", "capacity"))
def bucket_rows(
    key_lo: jax.Array, values: jax.Array, n_shards: int, capacity: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Group rows by destination shard into fixed-capacity buckets.

    Returns ``(bucketed_values (n_shards, capacity, ...), valid (n_shards, capacity),
    dropped_count)``. Rows beyond ``capacity`` for a bucket are counted as dropped — the
    caller sizes capacity from the host-side batch so this is a correctness assert, not a
    data-loss path.
    """
    shard_ids = (key_lo & (n_shards - 1)).astype(jnp.int32)
    order = jnp.argsort(shard_ids, stable=True)
    sorted_ids = shard_ids[order]
    sorted_vals = values[order]
    # position of each row within its bucket
    pos_in_bucket = jnp.arange(len(key_lo)) - jnp.searchsorted(
        sorted_ids, sorted_ids, side="left"
    )
    ok = pos_in_bucket < capacity
    flat_slot = sorted_ids * capacity + pos_in_bucket
    out = jnp.zeros((n_shards * capacity,) + values.shape[1:], dtype=values.dtype)
    out = out.at[jnp.where(ok, flat_slot, n_shards * capacity - 1)].set(
        jnp.where(ok.reshape((-1,) + (1,) * (values.ndim - 1)), sorted_vals, 0),
        mode="drop",
    )
    valid = jnp.zeros((n_shards * capacity,), dtype=bool)
    valid = valid.at[jnp.where(ok, flat_slot, 0)].set(ok, mode="drop")
    dropped = jnp.sum(~ok)
    return (
        out.reshape((n_shards, capacity) + values.shape[1:]),
        valid.reshape(n_shards, capacity),
        dropped,
    )


def exchange_by_key(
    mesh: Mesh,
    key_lo: jax.Array,
    values: jax.Array,
    *,
    axis: str = "data",
    capacity: int | None = None,
) -> tuple[jax.Array, jax.Array]:
    """All-to-all exchange of rows to their key-owning shard along a mesh axis.

    ``key_lo``/``values`` are sharded on their leading (row) axis over ``axis``. Each
    device buckets its local rows by destination, then one ``all_to_all`` delivers every
    bucket to its owner. Returns ``(values, valid)`` with leading row axis still sharded
    over ``axis`` — each shard now holds only rows it owns (padded; see ``valid``).
    """
    n_shards = mesh.shape[axis]
    if capacity is None:
        capacity = max(1, values.shape[0])  # conservative: all local rows → one bucket

    def local(k_lo: jax.Array, vals: jax.Array) -> tuple[jax.Array, jax.Array]:
        bucketed, valid, _ = bucket_rows(k_lo, vals, n_shards, capacity)
        recv = jax.lax.all_to_all(bucketed, axis, 0, 0, tiled=False)
        recv_valid = jax.lax.all_to_all(valid, axis, 0, 0, tiled=False)
        return (
            recv.reshape((n_shards * capacity,) + vals.shape[1:]),
            recv_valid.reshape(n_shards * capacity),
        )

    spec_in = P(axis, *([None] * (values.ndim - 1)))
    return shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(P(axis), spec_in),
        out_specs=(spec_in, P(axis)),
    )(key_lo, values)
