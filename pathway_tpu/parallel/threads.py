"""In-process worker threads — the ``PATHWAY_THREADS`` lane.

Parity: the reference runs N timely worker threads per process over a
shared-memory allocator (``src/engine/dataflow/config.rs:63-70``,
``external/timely-dataflow/communication/src/initialize.rs:25-31``): every
worker runs the dataflow and rows hash-route to their key's owner.

Two entry points:

- ``run_shared_graph`` — the TRANSPARENT lane ``pw.run`` takes when
  ``PATHWAY_THREADS > 1``: one user-built graph, N ``GraphRunner``s over an
  in-memory ``ThreadExchange``. Sources ingest on rank 0, stateful operators
  partition by key across all ranks (the spawn cluster policies, unchanged),
  and outputs centralize back on rank 0 — so sink callbacks stay
  single-threaded and outputs are exactly the single-thread run's. The user
  program does not change at all.

- ``run_threads`` — the explicit spawn-like lane: the program runs once per
  worker on its own thread with a PRIVATE parse graph and a worker-rank
  config, partitioning its own inputs like a spawned process would.

The GIL note — measured, not hoped: large numpy ufuncs and ctypes kernels
release the GIL, but the engine's per-commit columnar plumbing (delta
slicing, group-index upkeep, many small array ops) holds it, so on CPython
worker threads deliver CONCURRENCY with exact cluster semantics, not CPU
parallelism (an 800k-row groupby measured ~1x at -t 4). The reference's
thread speedup comes from Rust having no GIL; this engine's parallel lanes
for throughput are ``spawn -n`` processes (same exchange protocol over TCP)
and the TPU mesh for device-bound work. Threads are still the right tool for
latency isolation (serving while ingesting) and for tests of cluster
semantics without process overhead.
"""

from __future__ import annotations

import threading
from dataclasses import replace
from typing import Any, Callable, Dict, List

from pathway_tpu.internals import config as config_mod
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.parallel.cluster import (
    PeerShutdownError,
    PeerTimeoutError,
    ThreadExchangeHub,
    set_thread_exchange,
)


def _launch(n: int, worker_body: Callable[[int], Any], hub: ThreadExchangeHub) -> List[Any]:
    results: List[Any] = [None] * n
    errors: List[tuple] = []

    def worker(rank: int) -> None:
        base = config_mod.PathwayConfig.from_env()
        config_mod.set_thread_config(
            replace(base, threads=1, processes=n, process_id=rank)
        )
        set_thread_exchange(hub, rank)
        try:
            results[rank] = worker_body(rank)
        except BaseException as exc:  # a dead worker must unblock its peers
            errors.append((rank, exc))
            hub.close()
        finally:
            set_thread_exchange(None)
            config_mod.set_thread_config(None)

    threads = [
        threading.Thread(target=worker, args=(rank,), name=f"pathway:worker-{rank}")
        for rank in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        # prefer the ROOT CAUSE: once one worker dies and closes the hub, its
        # peers fail with secondary ConnectionErrors — raising one of those
        # (e.g. lowest rank) would bury the actual failing operator
        def is_secondary(e: tuple) -> bool:
            # a typed peer-wait error anywhere on the exception CHAIN (engine
            # trace wrappers preserve __cause__/__context__) marks a worker that
            # died WAITING on a dead peer — never match message text: a user
            # UDF's TimeoutError phrasing must not bury the real failure
            exc: "BaseException | None" = e[1]
            seen: set[int] = set()
            while exc is not None and id(exc) not in seen:
                if isinstance(exc, (PeerShutdownError, PeerTimeoutError)):
                    return True
                seen.add(id(exc))
                if exc.__cause__ is not None:
                    exc = exc.__cause__
                elif not exc.__suppress_context__:
                    # honor `raise ... from None`: a worker that HANDLED a peer
                    # error and deliberately raised its own is primary
                    exc = exc.__context__
                else:
                    exc = None
            return False

        primary = [e for e in errors if not is_secondary(e)] or errors
        rank, exc = min(primary, key=lambda e: e[0])
        raise RuntimeError(f"worker thread {rank} failed: {exc!r}") from exc
    return results


def run_shared_graph(graph: Any, n: int, run_kwargs: Dict[str, Any]) -> None:
    """N runners over ONE already-built graph (the ``pw.run`` fan-out)."""
    from pathway_tpu.engine.runner import GraphRunner

    hub = ThreadExchangeHub(n)
    hub.shared_inputs = True

    def body(rank: int) -> None:
        kw = dict(run_kwargs)
        if rank != 0:
            # one dashboard, one http endpoint, one set of sinks: rank 0's
            kw["monitoring_level"] = None
            kw["with_http_server"] = False
        GraphRunner(graph).run(**kw)

    _launch(n, body, hub)


def run_threads(program: Callable[[], Any], n: int) -> List[Any]:
    """Run ``program`` once per worker on ``n`` threads, exchanging like
    ``spawn -n n``. Returns the per-worker return values (rank order).

    ``program`` plays the role of the spawned script: it builds its graph and
    calls ``pw.run()`` itself. Inside it, ``get_pathway_config().process_id``
    is the worker rank and ``.processes`` is ``n`` — partition inputs by rank
    exactly as a spawned process would (readers with parallel partition
    sharding do this automatically).
    """
    if n <= 1:
        return [program()]
    hub = ThreadExchangeHub(n)

    def body(rank: int) -> Any:
        G.enter_thread_graph()
        try:
            return program()
        finally:
            G.exit_thread_graph()

    return _launch(n, body, hub)
