"""Mesh-sharded groupby-reduce: the engine's grouped aggregation over the exchange pact.

The reference shards every ``reduce`` by routing rows to the worker owning the group key
(``src/engine/dataflow/shard.rs:15-20``; exchange inside DD's ``reduce``). Here the same
routing is one ``shard_map``: each device buckets its local rows by destination shard
(low bits of the group key), one ``all_to_all`` delivers the buckets over ICI, every
shard segment-sums the rows it owns, and a ``psum`` assembles the global per-group sums
(non-owned segments contribute zero, so the psum is also the ownership merge).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from pathway_tpu.parallel.mesh import shard_map_compat
from jax.sharding import Mesh, PartitionSpec as P

from pathway_tpu.parallel.exchange import bucket_rows


@partial(
    jax.jit,
    static_argnames=("mesh", "axis", "n_shards", "capacity", "num_segments"),
)
def _sharded_segment_sum_impl(
    key_lo: jax.Array,
    seg_ids: jax.Array,
    values: jax.Array,
    *,
    mesh: Mesh,
    axis: str,
    n_shards: int,
    capacity: int,
    num_segments: int,
) -> jax.Array:
    def local(k_lo: jax.Array, segs: jax.Array, vals: jax.Array) -> jax.Array:
        b_vals, valid, _ = bucket_rows(k_lo, vals, n_shards, capacity)
        b_segs, _, _ = bucket_rows(k_lo, segs, n_shards, capacity)
        rv = lax.all_to_all(b_vals, axis, 0, 0, tiled=False)
        rs = lax.all_to_all(b_segs, axis, 0, 0, tiled=False)
        rvalid = lax.all_to_all(valid, axis, 0, 0, tiled=False)
        vals_f = rv.reshape(-1)
        segs_f = rs.reshape(-1)
        ok = rvalid.reshape(-1)
        contrib = jnp.where(ok, vals_f, jnp.zeros((), dtype=vals_f.dtype))
        local_sum = jax.ops.segment_sum(
            contrib, jnp.where(ok, segs_f, 0), num_segments=num_segments
        )
        return lax.psum(local_sum, axis)

    return shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(),
    )(key_lo, seg_ids, values)


def sharded_segment_sum(
    mesh: Mesh,
    key_lo: np.ndarray,
    seg_ids: np.ndarray,
    values: np.ndarray,
    num_segments: int,
    axis: str = "data",
) -> np.ndarray:
    """Sum ``values`` per segment with rows exchanged to their key-owning shard first.

    Host-side entry: pads the batch so rows split evenly over the axis, runs the
    exchange + local reduce + psum on the mesh, returns the (num_segments,) host array.
    """
    n_shards = mesh.shape[axis]
    n = len(values)
    # pad rows and segment count to powers of two so varying per-commit batch sizes
    # and touched-group counts reuse one compiled collective program
    padded_local = 1 << max(0, (-(-n // n_shards) - 1).bit_length())
    padded_n = padded_local * n_shards
    padded_m = 1 << max(0, (num_segments - 1).bit_length())
    pad = padded_n - n
    if pad:
        key_lo = np.concatenate([key_lo, np.zeros(pad, dtype=key_lo.dtype)])
        seg_ids = np.concatenate([seg_ids, np.zeros(pad, dtype=seg_ids.dtype)])
        values = np.concatenate([values, np.zeros(pad, dtype=values.dtype)])
    out = _sharded_segment_sum_impl(
        jnp.asarray(key_lo.astype(np.uint32)),
        jnp.asarray(seg_ids.astype(np.int32)),
        jnp.asarray(values.astype(np.float32)),
        mesh=mesh,
        axis=axis,
        n_shards=n_shards,
        capacity=padded_local,
        num_segments=padded_m,
    )
    return np.asarray(out)[:num_segments]
