"""Distributed training step for the flagship sentence encoder.

The reference ships a frozen torch model (``xpacks/llm/embedders.py:270`` — inference only);
a TPU-native framework owns the training loop too: in-batch contrastive (InfoNCE) fine-tuning
of :class:`pathway_tpu.models.encoder.SentenceEncoder`, jit'd once over a ``(data, model)``
mesh. Parallelism is declared, not hand-written: params carry Megatron TP shardings
(:mod:`pathway_tpu.parallel.sharding`), the batch shards over ``data``, and XLA inserts the
all-reduces (TP) and the cross-device similarity matmul collectives (DP global in-batch
negatives) from the constraints.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pathway_tpu.models.encoder import EncoderConfig, SentenceEncoder
from pathway_tpu.parallel.sharding import (
    batch_sharding,
    encoder_param_sharding,
    replicated,
)


def contrastive_loss(anchor: jax.Array, positive: jax.Array, temperature: float) -> jax.Array:
    """InfoNCE with in-batch negatives; embeddings are already L2-normalized."""
    logits = anchor @ positive.T / temperature  # (B, B) — global across data shards
    labels = jnp.arange(anchor.shape[0])
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


class ContrastiveTrainer:
    """Owns params/optimizer state placed on a mesh; one jit'd train step.

    ``batch`` = dict of (B, S) int32 arrays: ``input_ids``, ``attention_mask``,
    ``positive_ids``, ``positive_mask`` — anchor/positive text pairs.
    """

    def __init__(
        self,
        mesh: Mesh,
        config: Optional[EncoderConfig] = None,
        learning_rate: float = 2e-5,
        temperature: float = 0.05,
        seed: int = 0,
    ):
        self.mesh = mesh
        self.config = config or EncoderConfig()
        self.model = SentenceEncoder(self.config)
        self.temperature = temperature
        self.tx = optax.adamw(learning_rate)

        ids = jnp.zeros((1, 8), dtype=jnp.int32)
        host_params = self.model.init(jax.random.PRNGKey(seed), ids, jnp.ones_like(ids))
        self.param_sharding = encoder_param_sharding(host_params["params"], mesh)
        self.params = jax.tree.map(
            jax.device_put, host_params["params"], self.param_sharding
        )
        # optimizer state mirrors the param tree's sharding; scalar counts replicate
        self.opt_state = jax.jit(
            self.tx.init, out_shardings=self._opt_shardings(self.params)
        )(self.params)
        self._step = self._build_step()

    def _opt_shardings(self, params: Any) -> Any:
        shape = jax.eval_shape(self.tx.init, params)
        by_shape = {
            (leaf.shape, str(leaf.dtype)): sharding
            for leaf, sharding in zip(
                jax.tree.leaves(jax.eval_shape(lambda p: p, params)),
                jax.tree.leaves(self.param_sharding),
            )
        }

        def pick(leaf: Any) -> NamedSharding:
            # moment tensors share param shapes → same sharding; scalars replicate
            return by_shape.get((leaf.shape, str(leaf.dtype)), replicated(self.mesh))

        return jax.tree.map(pick, shape)

    def _build_step(self) -> Any:
        model, temperature = self.model, self.temperature
        data_sharding = batch_sharding(self.mesh)
        batch_shardings = {
            "input_ids": data_sharding,
            "attention_mask": data_sharding,
            "positive_ids": data_sharding,
            "positive_mask": data_sharding,
        }

        def loss_fn(params: Any, batch: dict) -> jax.Array:
            anchor = model.apply(
                {"params": params}, batch["input_ids"], batch["attention_mask"]
            )
            positive = model.apply(
                {"params": params}, batch["positive_ids"], batch["positive_mask"]
            )
            return contrastive_loss(anchor, positive, temperature)

        def step(params: Any, opt_state: Any, batch: dict) -> tuple[Any, Any, jax.Array]:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return jax.jit(
            step,
            in_shardings=(
                self.param_sharding,
                self._opt_shardings(self.params),
                batch_shardings,
            ),
            out_shardings=(
                self.param_sharding,
                self._opt_shardings(self.params),
                replicated(self.mesh),
            ),
            donate_argnums=(0, 1),
        )

    def train_step(self, batch: dict) -> float:
        batch = {k: jnp.asarray(np.asarray(v, dtype=np.int32)) for k, v in batch.items()}
        self.params, self.opt_state, loss = self._step(self.params, self.opt_state, batch)
        return float(loss)

    def encode(self, input_ids: Any, attention_mask: Any) -> jax.Array:
        return jax.jit(
            lambda p, i, m: self.model.apply({"params": p}, i, m),
            in_shardings=(self.param_sharding, batch_sharding(self.mesh), batch_sharding(self.mesh)),
        )(self.params, jnp.asarray(input_ids), jnp.asarray(attention_mask))
