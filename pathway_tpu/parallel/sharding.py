"""Sharding rules: parameter trees, batches, keyed table state.

The reference shards *rows* by the low bits of the 128-bit key (``src/engine/dataflow/
shard.rs:15-20``) and never shards *compute* (no DNN exists there). We keep row sharding
(see :mod:`exchange`) and add Megatron-style tensor parallelism for the encoder:

- attention q/k/v kernels shard over the head axis, the out-projection over heads in;
- MLP intermediate shards column-wise, output row-wise (one all-reduce per block, inserted
  by XLA from the sharding constraints — we never hand-write the collective);
- token embeddings shard over the vocab axis; norms/biases-on-the-reduced-axis replicate.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path regex, PartitionSpec) — first match wins; fallback is replication.
_ENCODER_RULES: tuple[tuple[str, P], ...] = (
    (r"word_embeddings/embedding", P("model", None)),
    (r"position_embeddings/embedding", P(None, None)),
    (r"token_type_embeddings/embedding", P(None, None)),
    (r"attention/(query|key|value)/kernel", P(None, "model", None)),
    (r"attention/(query|key|value)/bias", P("model", None)),
    (r"attention/out/kernel", P("model", None, None)),
    (r"attention/out/bias", P(None)),
    (r"intermediate/kernel", P(None, "model")),
    (r"intermediate/bias", P("model")),
    (r"output/kernel", P("model", None)),
    (r"output/bias", P(None)),
)


def _spec_for_path(path: str) -> P:
    for pattern, spec in _ENCODER_RULES:
        if re.search(pattern, path):
            return spec
    return P()  # replicate (norms, anything unmatched)


def _path_str(key_path: Any) -> str:
    parts = []
    for entry in key_path:
        name = getattr(entry, "key", None)
        if name is None:
            name = getattr(entry, "name", str(entry))
        parts.append(str(name))
    return "/".join(parts)


def encoder_param_specs(params: Mapping[str, Any]) -> Any:
    """PartitionSpec tree matching the encoder param tree."""
    return jax.tree_util.tree_map_with_path(
        lambda kp, _: _spec_for_path(_path_str(kp)), params
    )


def encoder_param_sharding(params: Mapping[str, Any], mesh: Mesh) -> Any:
    """NamedSharding tree for the encoder params on ``mesh``."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), encoder_param_specs(params)
    )


def batch_sharding(mesh: Mesh, *, sequence_parallel: bool = False) -> NamedSharding:
    """(batch, seq) arrays: batch over ``data``; optionally seq over ``model`` (sp)."""
    return NamedSharding(mesh, P("data", "model" if sequence_parallel else None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Place a (host or single-device) param tree onto the mesh per the TP rules."""
    shardings = encoder_param_sharding(params, mesh)
    return jax.tree.map(jax.device_put, params, shardings)
