"""Cross-process exchange backend — the reference's ``CommunicationConfig::Cluster``.

The reference scales past one process with timely's TCP allocator: every worker runs
the same dataflow and rows hash-route to their key's owner
(``src/engine/dataflow/config.rs:73-84``,
``external/timely-dataflow/communication/src/initialize.rs:25-31``, shard routing
``src/engine/dataflow/shard.rs:15-20``). Here the equivalent is a full-mesh TCP
exchange between the ``pathway_tpu spawn -n N`` processes: key-partitioned stateful
operators (groupby, join) partition each commit's input delta by the low bits of the
routing key and swap partitions all-to-all, so every group/join key lives on exactly
one owner process and global aggregates are exact. Commits run in lockstep — each
exchange is a barrier — mirroring timely's bulk-synchronous progress model (and the
mesh collectives the same operators use across TPU chips, ``groupby_sharded.py``).

Environment contract (set by ``pathway_tpu spawn``): ``PATHWAY_PROCESSES``,
``PATHWAY_PROCESS_ID``, ``PATHWAY_FIRST_PORT``; addresses default to
``127.0.0.1:first_port+i`` like the reference (``dataflow/config.rs:111-114``).
"""

from __future__ import annotations

import pickle
import random
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from pathway_tpu.internals.config import env_float as _env_float

# control frame: liveness beacon, never enters the inbox (and never counts
# toward the chaos harness's per-peer data-frame streams)
HEARTBEAT_TAG = b"\x00hb"


class ClusterExchange:
    """Full-mesh, length-prefixed-frame TCP exchange between spawn processes.

    Frames are tagged; ``exchange_parts`` is an all-to-all barrier: it sends one
    payload per peer under a tag and blocks until the same tag arrived from every
    peer. Deterministic tag sequences (commit id x node id x purpose) keep the
    processes in lockstep without a coordinator.

    Failure model (the supervised-runtime contract): every peer link carries
    heartbeat frames, every barrier wait has a deadline, and a dead or wedged
    peer surfaces as a typed ``PeerShutdownError`` (its socket closed) or
    ``PeerTimeoutError`` (barrier deadline / heartbeat staleness) instead of an
    infinite ``Condition.wait`` — a SIGKILLed worker fails its survivors loudly
    within the deadline, never hangs them. Knobs (env):

    - ``PATHWAY_BARRIER_TIMEOUT_S`` — per-barrier recv deadline (default 300);
    - ``PATHWAY_HEARTBEAT_INTERVAL_S`` — beacon period (default 1.0);
    - ``PATHWAY_HEARTBEAT_TIMEOUT_S`` — staleness bound while waiting on a peer
      (default 60; 0 disables);
    - ``PATHWAY_CONNECT_TIMEOUT_S`` — connect budget PER PEER dialed, and
      again for the dial-in accept join (default 60; worst-case wiring time
      for rank r is ``(n - r) x`` this bound);
    - ``PATHWAY_EXCHANGE_INBOX_FRAMES`` — per-peer inbox bound (default 1024);
      a full inbox parks the reader thread (TCP backpressure), it never grows
      without bound when one process runs ahead of its peers.
    """

    _HDR = struct.Struct("<II")  # tag_len, payload_len

    def __init__(self, n_processes: int, process_id: int, first_port: int):
        self.n = n_processes
        self.me = process_id
        self.first_port = first_port
        self._conns: Dict[int, socket.socket] = {}
        self._send_locks: Dict[int, threading.Lock] = {}
        self._inbox: Dict[tuple, bytes] = {}  # (peer, tag) -> payload
        self._inbox_count: Dict[int, int] = {}  # buffered frames per peer
        self._cv = threading.Condition()
        self._closed = False
        self._dead: Dict[int, str] = {}  # peer -> reason its link died
        self._last_heard: Dict[int, float] = {}
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self.barrier_timeout_s = _env_float("PATHWAY_BARRIER_TIMEOUT_S", 300.0)
        self.heartbeat_interval_s = _env_float("PATHWAY_HEARTBEAT_INTERVAL_S", 1.0)
        self.heartbeat_timeout_s = _env_float("PATHWAY_HEARTBEAT_TIMEOUT_S", 60.0)
        self._inbox_limit = max(
            1, int(_env_float("PATHWAY_EXCHANGE_INBOX_FRAMES", 1024))
        )
        from pathway_tpu.internals.chaos import get_chaos

        self._chaos = get_chaos()
        self._connect_all()
        now = time.monotonic()
        for peer in self._conns:
            self._last_heard[peer] = now
            self._inbox_count[peer] = 0
        for peer, conn in self._conns.items():
            t = threading.Thread(
                target=self._reader, args=(peer, conn), daemon=True,
                name=f"pathway:cluster-rx-{peer}",
            )
            t.start()
        if self.heartbeat_interval_s > 0:
            # one beacon thread PER PEER: a send stalled on one backpressured
            # link (full socket buffer) must not starve beacons to the others —
            # that would read as a false cluster-wide wedge
            for peer in self._conns:
                threading.Thread(
                    target=self._heartbeat_loop, args=(peer,), daemon=True,
                    name=f"pathway:cluster-hb-{peer}",
                ).start()

    # -- wiring --------------------------------------------------------------

    def _connect_all(self) -> None:
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", self.first_port + self.me))
        listener.listen(self.n)
        self._listener = listener

        accepted: Dict[int, socket.socket] = {}
        accept_errors: List[BaseException] = []

        def accept_loop() -> None:
            try:
                for _ in range(self.me):  # lower-ranked peers dial us
                    conn, _addr = listener.accept()
                    peer = int.from_bytes(self._recv_exact(conn, 4), "little")
                    accepted[peer] = conn
            except BaseException as exc:  # surfaced after join: silent partial
                accept_errors.append(exc)  # wiring would drop peers' data

        acceptor = threading.Thread(target=accept_loop, daemon=True)
        acceptor.start()
        connect_budget = _env_float("PATHWAY_CONNECT_TIMEOUT_S", 60.0)
        try:
            # dial every higher-ranked peer, with exponential backoff + jitter:
            # peers may not be up yet, and N processes hammering one listener at
            # a fixed 50 ms period synchronize into accept-queue bursts
            rng = random.Random((self.me << 16) ^ self.first_port)
            for peer in range(self.me + 1, self.n):
                deadline = time.monotonic() + connect_budget
                delay = 0.05
                while True:
                    try:
                        s = socket.create_connection(
                            ("127.0.0.1", self.first_port + peer), timeout=5
                        )
                        break
                    except OSError:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise PeerTimeoutError(
                                f"cluster process {self.me} could not reach peer "
                                f"{peer} on port {self.first_port + peer} within "
                                f"{connect_budget:.0f}s"
                            )
                        time.sleep(
                            min(remaining, delay * (1.0 + 0.25 * rng.random()))
                        )
                        delay = min(delay * 2, 2.0)
                # back to fully blocking: create_connection's dial timeout must
                # not linger on the socket, or every later sendall/recv on this
                # link spuriously times out after 5s of quiet (SO_SNDTIMEO and
                # the recv-side deadlines own timeout behavior from here on)
                s.settimeout(None)
                s.sendall(self.me.to_bytes(4, "little"))
                self._conns[peer] = s
            acceptor.join(timeout=connect_budget)
            if acceptor.is_alive():
                raise PeerTimeoutError(
                    f"cluster process {self.me} timed out waiting for dial-ins"
                )
            if accept_errors:
                raise ConnectionError(
                    f"cluster process {self.me} failed accepting dial-ins"
                ) from accept_errors[0]
            if len(accepted) != self.me:
                raise ConnectionError(
                    f"cluster process {self.me} expected {self.me} dial-ins, got "
                    f"{sorted(accepted)}"
                )
        except BaseException:
            # failed wiring must not strand fds: a stranded listener wedges the
            # retry (and the restarted rank) on "Address already in use"
            for s in list(self._conns.values()) + list(accepted.values()):
                try:
                    s.close()
                except OSError:
                    pass
            self._conns.clear()
            try:
                listener.close()
            except OSError:
                pass
            self._listener = None
            raise
        self._conns.update(accepted)
        for peer, conn in self._conns.items():
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self.barrier_timeout_s > 0:
                # send-side deadline (SO_SNDTIMEO is send-ONLY, so the reader
                # thread's blocking recv is untouched): a peer that stopped
                # reading must surface as a typed error from _send, not hang
                # sendall forever once the TCP buffers fill — _recv's deadlines
                # can't fire if we never get there
                conn.setsockopt(
                    socket.SOL_SOCKET,
                    socket.SO_SNDTIMEO,
                    struct.pack(
                        "ll",
                        int(self.barrier_timeout_s),
                        int(self.barrier_timeout_s % 1 * 1_000_000),
                    ),
                )
            self._send_locks[peer] = threading.Lock()

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("cluster peer closed the connection")
            buf += chunk
        return buf

    def _reader(self, peer: int, conn: socket.socket) -> None:
        try:
            while True:
                hdr = self._recv_exact(conn, self._HDR.size)
                tag_len, payload_len = self._HDR.unpack(hdr)
                tag = self._recv_exact(conn, tag_len)
                payload = self._recv_exact(conn, payload_len) if payload_len else b""
                with self._cv:
                    self._last_heard[peer] = time.monotonic()
                    if tag == HEARTBEAT_TAG:
                        self._cv.notify_all()
                        continue
                    # bounded inbox: park until the consumer drains (the unread
                    # backlog itself proves the peer is alive, so keep the
                    # heartbeat clock fresh while parked — the peer's beacons
                    # queue behind the data we are not reading)
                    while (
                        self._inbox_count[peer] >= self._inbox_limit
                        and not self._closed
                    ):
                        self._last_heard[peer] = time.monotonic()
                        self._cv.wait(timeout=0.2)
                    if self._closed:
                        return
                    self._inbox[(peer, tag)] = payload
                    self._inbox_count[peer] += 1
                    self._cv.notify_all()
        except (ConnectionError, OSError) as exc:
            with self._cv:
                self._dead.setdefault(peer, str(exc) or type(exc).__name__)
                self._cv.notify_all()

    def _send(self, peer: int, tag: bytes, payload: bytes) -> None:
        conn = self._conns[peer]
        frame = self._HDR.pack(len(tag), len(payload)) + tag + payload
        if self._chaos is not None and tag != HEARTBEAT_TAG:
            action = self._chaos.frame_action(self.me, peer)
            if action.kind == "drop":
                return  # peer's barrier deadline turns this into PeerTimeoutError
            if action.kind == "delay":
                time.sleep(action.delay_s)
            elif action.kind == "truncate":
                # torn write + dead link, as a crash mid-send would leave it
                with self._send_locks[peer]:
                    try:
                        conn.sendall(frame[: max(1, len(frame) // 2)])
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                with self._cv:
                    self._dead.setdefault(peer, "chaos: link truncated")
                    self._cv.notify_all()
                return
        try:
            with self._send_locks[peer]:
                conn.sendall(frame)
        except OSError as exc:
            timed_out = isinstance(exc, (socket.timeout, BlockingIOError))
            with self._cv:
                # the stream may have a torn partial frame on it now — the
                # link is unusable either way, so the peer is dead to us
                self._dead.setdefault(peer, str(exc) or type(exc).__name__)
                self._cv.notify_all()
            if timed_out:
                raise PeerTimeoutError(
                    f"cluster process {self.me} send of {tag!r} to peer {peer} "
                    f"stalled past the {self.barrier_timeout_s:.0f}s deadline "
                    "— peer stopped reading"
                ) from exc
            raise PeerShutdownError(
                f"cluster process {self.me} failed sending {tag!r} to peer "
                f"{peer}: {exc}"
            ) from exc

    def _recv(self, peer: int, tag: bytes, timeout: Optional[float] = None) -> bytes:
        if timeout is None:
            timeout = self.barrier_timeout_s
        deadline = time.monotonic() + timeout
        with self._cv:
            while (peer, tag) not in self._inbox:
                if peer in self._dead:
                    raise PeerShutdownError(
                        f"cluster peer {peer} disconnected while process "
                        f"{self.me} waited for {tag!r}: {self._dead[peer]}"
                    )
                if self._closed:
                    raise PeerShutdownError(
                        f"cluster exchange closed while waiting for {tag!r} "
                        f"from peer {peer}"
                    )
                now = time.monotonic()
                heard = self._last_heard.get(peer)
                if (
                    self.heartbeat_timeout_s > 0
                    # without beacons, silence between barriers is normal —
                    # staleness is only meaningful while heartbeats flow
                    and self.heartbeat_interval_s > 0
                    and heard is not None
                    and now - heard > self.heartbeat_timeout_s
                ):
                    raise PeerTimeoutError(
                        f"cluster peer {peer} heartbeat is {now - heard:.1f}s "
                        f"stale (> {self.heartbeat_timeout_s:.0f}s) while process "
                        f"{self.me} waited for {tag!r} — peer is wedged"
                    )
                remaining = deadline - now
                if remaining <= 0:
                    raise PeerTimeoutError(
                        f"cluster process {self.me} timed out after "
                        f"{timeout:.0f}s waiting for {tag!r} from peer {peer}"
                    )
                self._cv.wait(timeout=min(remaining, 0.5))
            payload = self._inbox.pop((peer, tag))
            self._inbox_count[peer] -= 1
            self._cv.notify_all()  # unpark a backpressured reader
            return payload

    # -- liveness -------------------------------------------------------------

    def _heartbeat_loop(self, peer: int) -> None:
        while not self._stop.wait(self.heartbeat_interval_s):
            if self._closed or peer in self._dead:
                return
            try:
                self._send(peer, HEARTBEAT_TAG, b"")
            except (PeerShutdownError, OSError):
                return  # _send already recorded the death

    def heartbeat_ages(self) -> Dict[int, float]:
        """Seconds since each peer was last heard from (any frame). The shared
        liveness signal: served by ``/healthz`` and written to the supervisor's
        per-rank status file."""
        now = time.monotonic()
        with self._cv:
            return {peer: now - t for peer, t in self._last_heard.items()}

    def dead_peers(self) -> Dict[int, str]:
        with self._cv:
            return dict(self._dead)

    # -- collectives ----------------------------------------------------------

    def exchange_parts(self, tag: bytes, parts: Dict[int, bytes]) -> Dict[int, bytes]:
        """All-to-all: send ``parts[peer]`` to each peer, receive theirs. Barrier.

        Raises :class:`PeerShutdownError` when a peer's link died, or
        :class:`PeerTimeoutError` when a peer missed the barrier deadline or
        went heartbeat-stale — never blocks forever on a dead peer."""
        for peer in self._conns:
            self._send(peer, tag, parts.get(peer, b""))
        return {peer: self._recv(peer, tag) for peer in self._conns}

    def allgather(self, tag: bytes, value: Any) -> List[Any]:
        """Every process contributes ``value``; all receive the full list (by rank)."""
        blob = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        got = self.exchange_parts(tag, {p: blob for p in self._conns})
        out: List[Any] = [None] * self.n
        out[self.me] = value
        for peer, payload in got.items():
            out[peer] = pickle.loads(payload)
        return out

    def close(self) -> None:
        self._stop.set()
        with self._cv:
            self._closed = True
            self._cv.notify_all()  # release parked readers and waiting recvs
        for conn in self._conns.values():
            try:
                conn.close()
            except OSError:
                pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    # -- delta routing ---------------------------------------------------------

    def exchange_delta(self, tag: bytes, delta: Any, route_keys: np.ndarray) -> Any:
        """Hash-route a commit's delta rows to their owner process and merge what
        this process owns (reference shard routing, ``shard.rs:15-20``): owner =
        key.lo % n. Returns the merged delta (own partition + received rows)."""
        from pathway_tpu.engine.columnar import Delta
        from pathway_tpu.internals.keys import shard_of

        owners = shard_of(route_keys, self.n)
        parts: Dict[int, bytes] = {}
        for peer in range(self.n):
            if peer == self.me:
                continue
            rows = np.nonzero(owners == peer)[0]
            if len(rows):
                sub = delta.select(rows)
                parts[peer] = pickle.dumps(
                    (sub.keys, sub.diffs, sub.columns, sub.neu),
                    protocol=pickle.HIGHEST_PROTOCOL,
                )
            else:
                parts[peer] = b""
        received = self.exchange_parts(tag, parts)
        mine = delta.select(np.nonzero(owners == self.me)[0])
        merged = [mine]
        for peer in sorted(received):
            payload = received[peer]
            if payload:
                keys, diffs, columns, neu = pickle.loads(payload)
                merged.append(Delta(keys, diffs, columns, neu=neu))
        if len(merged) == 1:
            return mine
        return Delta.concat(merged, list(delta.columns))

    @staticmethod
    def _pack(delta: Any) -> bytes:
        return pickle.dumps(
            (delta.keys, delta.diffs, delta.columns, delta.neu),
            protocol=pickle.HIGHEST_PROTOCOL,
        )

    def exchange_to_root(self, tag: bytes, delta: Any) -> Any:
        """Centralize: every process ships its whole delta to process 0 (the
        reference routes temporal-behavior input to one worker,
        ``time_column.rs:48-51``). Process 0 returns the rank-ordered merge;
        everyone else returns an empty delta. Barrier."""
        from pathway_tpu.engine.columnar import Delta

        columns = list(delta.columns)
        parts: Dict[int, bytes] = {p: b"" for p in self._conns}
        if self.me != 0 and len(delta):
            parts[0] = self._pack(delta)
        received = self.exchange_parts(tag, parts)
        if self.me != 0:
            return Delta.empty(columns)
        merged = [delta]
        for peer in sorted(received):
            payload = received[peer]
            if payload:
                keys, diffs, cols, neu = pickle.loads(payload)
                merged.append(Delta(keys, diffs, cols, neu=neu))
        if len(merged) == 1:
            return delta
        return Delta.concat(merged, columns)

    def broadcast_merge(self, tag: bytes, delta: Any) -> Any:
        """Replicate: every process contributes its delta; ALL processes return the
        same rank-ordered merge (replicated-state operators, e.g. the external
        index's data side — every process holds the full index, queries answer
        locally). Barrier."""
        from pathway_tpu.engine.columnar import Delta

        columns = list(delta.columns)
        blob = self._pack(delta) if len(delta) else b""
        received = self.exchange_parts(tag, {p: blob for p in self._conns})
        by_rank: List[Any] = [None] * self.n
        by_rank[self.me] = delta
        for peer, payload in received.items():
            if payload:
                keys, diffs, cols, neu = pickle.loads(payload)
                by_rank[peer] = Delta(keys, diffs, cols, neu=neu)
        merged = [d for d in by_rank if d is not None and len(d)]
        if not merged:
            return Delta.empty(columns)
        if len(merged) == 1:
            return merged[0]
        return Delta.concat(merged, columns)


class ThreadExchangeHub:
    """Shared mailbox for the in-process worker-thread exchange: the timely
    shared-memory allocator's slot, where ``spawn -n``'s TCP mesh is its
    process allocator (``external/timely-dataflow/communication/src/initialize.rs:25-31``
    distinguishes exactly these two)."""

    def __init__(self, n: int):
        self.n = n
        self.boxes: Dict[tuple, bytes] = {}  # (dst, src, tag) -> payload
        self.cv = threading.Condition()
        self.closed = False
        # transparent-threads mode (one shared graph): sources ingest on rank 0
        # and outputs centralize there; compute partitions across all ranks
        self.shared_inputs = False

    def close(self) -> None:
        with self.cv:
            self.closed = True
            self.cv.notify_all()


class PeerShutdownError(ConnectionError):
    """A peer worker shut down while this worker waited on it — a SECONDARY
    failure (the peer's own exception is the root cause)."""


class PeerTimeoutError(TimeoutError):
    """Timed out waiting on a peer worker — secondary, like
    :class:`PeerShutdownError` (typed so failure triage classifies by
    ``isinstance`` instead of matching message text)."""


def _freeze_delta(payload: Any) -> Any:
    """Mark a delta's arrays read-only before handing the LIVE object to peer
    threads: the zero-serialization lane shares one address space, and the
    engine-wide convention that deltas are never mutated in place is otherwise
    unenforced — a violation must fail fast in the mutating worker, not corrupt
    its peers nondeterministically."""
    if payload is None:
        return payload
    for arr in (payload.keys, payload.diffs, *payload.columns.values()):
        if isinstance(arr, np.ndarray):
            arr.setflags(write=False)
    return payload


class ThreadExchange(ClusterExchange):
    """``ClusterExchange``'s collectives and delta routing over an in-memory
    transport: worker THREADS in one process instead of spawned processes.
    All the lockstep/barrier semantics are inherited — only ``_send``/``_recv``
    change (a dict handoff under one condition variable; no sockets, no
    serializing between address spaces beyond the pickle the routing layer
    already does)."""

    def __init__(self, hub: ThreadExchangeHub, me: int):
        # deliberately NOT calling super().__init__ — no sockets to wire
        self.n = hub.n
        self.me = me
        self._hub = hub
        self._conns = {p: None for p in range(hub.n) if p != me}  # peer ranks
        # same barrier-deadline knob as the TCP lane (no heartbeats here: a
        # thread peer cannot vanish silently, only wedge — which this catches)
        self.barrier_timeout_s = _env_float("PATHWAY_BARRIER_TIMEOUT_S", 300.0)

    def _send(self, peer: int, tag: bytes, payload: Any) -> None:
        if payload is not None and hasattr(payload, "columns"):
            _freeze_delta(payload)  # object handoff: enforce the no-mutation contract
        with self._hub.cv:
            self._hub.boxes[(peer, self.me, tag)] = payload
            self._hub.cv.notify_all()

    def _recv(self, peer: int, tag: bytes, timeout: Optional[float] = None) -> bytes:
        if timeout is None:
            timeout = self.barrier_timeout_s
        deadline = time.monotonic() + timeout
        key = (self.me, peer, tag)
        with self._hub.cv:
            while key not in self._hub.boxes:
                if self._hub.closed:
                    raise PeerShutdownError(
                        f"worker thread {peer} shut down while waiting for {tag!r}"
                    )
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise PeerTimeoutError(
                        f"worker thread {self.me} timed out waiting for {tag!r} "
                        f"from worker {peer}"
                    )
                self._hub.cv.wait(timeout=min(remaining, 1.0))
            return self._hub.boxes.pop(key)

    def close(self) -> None:
        self._hub.close()

    def heartbeat_ages(self) -> Dict[int, float]:
        return {}  # one address space: a peer thread cannot vanish silently

    def dead_peers(self) -> Dict[int, str]:
        return {}

    @property
    def shared_inputs(self) -> bool:
        return self._hub.shared_inputs

    # -- zero-serialization delta collectives --------------------------------
    # Worker threads share one address space: deltas cross the exchange as
    # OBJECT handoffs (the partition slice the routing already makes), not
    # pickled bytes. This is the in-memory allocator's whole advantage — the
    # TCP lane pays serialization because it must, this lane must not.

    def exchange_delta(self, tag: bytes, delta: Any, route_keys: np.ndarray) -> Any:
        from pathway_tpu.engine.columnar import Delta
        from pathway_tpu.internals.keys import shard_of

        owners = shard_of(route_keys, self.n)
        for peer in self._conns:
            rows = np.nonzero(owners == peer)[0]
            self._send(peer, tag, delta.select(rows) if len(rows) else None)
        mine = delta.select(np.nonzero(owners == self.me)[0])
        merged = [mine]
        for peer in sorted(self._conns):
            part = self._recv(peer, tag)
            if part is not None and len(part):
                merged.append(part)
        if len(merged) == 1:
            return mine
        return Delta.concat(merged, list(delta.columns))

    def exchange_to_root(self, tag: bytes, delta: Any) -> Any:
        from pathway_tpu.engine.columnar import Delta

        columns = list(delta.columns)
        if self.me != 0:
            self._send(0, tag, delta if len(delta) else None)
            for peer in self._conns:
                if peer != 0:
                    self._send(peer, tag, None)
        else:
            for peer in self._conns:
                self._send(peer, tag, None)
        received = {peer: self._recv(peer, tag) for peer in self._conns}
        if self.me != 0:
            return Delta.empty(columns)
        merged = [delta]
        for peer in sorted(received):
            part = received[peer]
            if part is not None and len(part):
                merged.append(part)
        if len(merged) == 1:
            return delta
        return Delta.concat(merged, columns)

    def broadcast_merge(self, tag: bytes, delta: Any) -> Any:
        from pathway_tpu.engine.columnar import Delta

        columns = list(delta.columns)
        payload = delta if len(delta) else None
        for peer in self._conns:
            self._send(peer, tag, payload)
        by_rank: List[Any] = [None] * self.n
        by_rank[self.me] = delta if len(delta) else None
        for peer in self._conns:
            by_rank[peer] = self._recv(peer, tag)
        merged = [d for d in by_rank if d is not None and len(d)]
        if not merged:
            return Delta.empty(columns)
        if len(merged) == 1:
            return merged[0]
        return Delta.concat(merged, columns)


_thread_ctx = threading.local()


def in_thread_worker() -> bool:
    """True on a thread already bound to a worker exchange (prevents nested
    fan-out when a worker's own ``pw.run`` consults PATHWAY_THREADS)."""
    return getattr(_thread_ctx, "hub", None) is not None


def set_thread_exchange(hub: "ThreadExchangeHub | None", me: int = 0) -> None:
    """Bind this thread to a worker-thread exchange (``run_threads`` launcher);
    None unbinds."""
    _thread_ctx.hub = hub
    _thread_ctx.me = me
    _thread_ctx.exchange = None


_cluster: Optional[ClusterExchange] = None
_cluster_tried = False


def get_cluster() -> Optional[ClusterExchange]:
    """Process-wide exchange, created from the spawn env on first use; None when
    running single-process. Worker threads bound to a ThreadExchangeHub get
    their in-memory exchange instead."""
    global _cluster, _cluster_tried
    hub = getattr(_thread_ctx, "hub", None)
    if hub is not None:
        ex = getattr(_thread_ctx, "exchange", None)
        if ex is None:
            ex = ThreadExchange(hub, _thread_ctx.me)
            _thread_ctx.exchange = ex
        return ex
    if _cluster_tried:
        return _cluster
    from pathway_tpu.internals.config import get_pathway_config

    cfg = get_pathway_config()
    n = int(getattr(cfg, "processes", 1) or 1)
    if n <= 1:
        _cluster_tried = True
        return None
    # mark as tried only on SUCCESS: a failed wiring attempt must raise again on
    # retry, never silently degrade to single-process partial results
    cluster = ClusterExchange(
        n, int(getattr(cfg, "process_id", 0) or 0), int(getattr(cfg, "first_port", 10000) or 10000)
    )
    _cluster = cluster
    _cluster_tried = True
    return _cluster
