"""Cross-process exchange backend — the reference's ``CommunicationConfig::Cluster``.

The reference scales past one process with timely's TCP allocator: every worker runs
the same dataflow and rows hash-route to their key's owner
(``src/engine/dataflow/config.rs:73-84``,
``external/timely-dataflow/communication/src/initialize.rs:25-31``, shard routing
``src/engine/dataflow/shard.rs:15-20``). Here the equivalent is a full-mesh TCP
exchange between the ``pathway_tpu spawn -n N`` processes: key-partitioned stateful
operators (groupby, join) partition each commit's input delta by the low bits of the
routing key and swap partitions all-to-all, so every group/join key lives on exactly
one owner process and global aggregates are exact. Commits run in lockstep — each
exchange is a barrier — mirroring timely's bulk-synchronous progress model (and the
mesh collectives the same operators use across TPU chips, ``groupby_sharded.py``).

Environment contract (set by ``pathway_tpu spawn``): ``PATHWAY_PROCESSES``,
``PATHWAY_PROCESS_ID``, ``PATHWAY_FIRST_PORT``; addresses default to
``127.0.0.1:first_port+i`` like the reference (``dataflow/config.rs:111-114``).
"""

from __future__ import annotations

import os
import pickle
import random
import socket
import struct
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from pathway_tpu.engine.profile import get_flight_recorder as _flight_recorder
from pathway_tpu.engine.tracing import (
    current_context as _trace_current,
    format_trace_header as _format_trace_header,
    get_tracer as _get_tracer,
    parse_trace_header as _parse_trace_header,
)
from pathway_tpu.engine.telemetry import (
    stage_add as _stage_add,
    stage_add_many as _stage_add_many,
)
from pathway_tpu.internals.config import env_float as _env_float

# control frame: liveness beacon, never enters the inbox (and never counts
# toward the chaos harness's per-peer data-frame streams)
HEARTBEAT_TAG = b"\x00hb"
# control frame: "these ranks died — quiesce at the epoch fence" (payload is a
# pickled sorted list of dead ranks; exempt from chaos like heartbeats so the
# recovery protocol itself stays deterministic under frame-fault plans)
FENCE_TAG = b"\x00fence"
# rejoin hello, sent by a relaunched rank dialing back into a live cluster:
# magic + rank(4, little) + epoch(4, little)
_REJOIN_MAGIC = b"PWRJ"
# membership hello, sent by a JOINER of an elastic grow transition dialing
# the existing members (and lower-ranked fellow joiners):
# magic + rank(4, little) + epoch(4, little) + target_n(4, little)
_MEMBER_MAGIC = b"PWMB"
# sanity bound on hello ranks: parked dial-ins are validated again at
# install, but a garbage rank must not grow the pending map unboundedly
_MAX_RANK = 4096


class ClusterExchange:
    """Full-mesh, length-prefixed-frame TCP exchange between spawn processes.

    Frames are tagged; ``exchange_parts`` is an all-to-all barrier: it sends one
    payload per peer under a tag and blocks until the same tag arrived from every
    peer. Deterministic tag sequences (commit id x node id x purpose) keep the
    processes in lockstep without a coordinator.

    Failure model (the supervised-runtime contract): every peer link carries
    heartbeat frames, every barrier wait has a deadline, and a dead or wedged
    peer surfaces as a typed ``PeerShutdownError`` (its socket closed) or
    ``PeerTimeoutError`` (barrier deadline / heartbeat staleness) instead of an
    infinite ``Condition.wait`` — a SIGKILLed worker fails its survivors loudly
    within the deadline, never hangs them. Knobs (env):

    - ``PATHWAY_BARRIER_TIMEOUT_S`` — per-barrier recv deadline (default 300);
    - ``PATHWAY_HEARTBEAT_INTERVAL_S`` — beacon period (default 1.0);
    - ``PATHWAY_HEARTBEAT_TIMEOUT_S`` — staleness bound while waiting on a peer
      (default 60; 0 disables);
    - ``PATHWAY_CONNECT_TIMEOUT_S`` — connect budget PER PEER dialed, and
      again for the dial-in accept join (default 60; worst-case wiring time
      for rank r is ``(n - r) x`` this bound);
    - ``PATHWAY_EXCHANGE_INBOX_FRAMES`` — per-peer inbox bound (default 1024);
      a full inbox parks the reader thread (TCP backpressure), it never grows
      without bound when one process runs ahead of its peers.

    Epoch fencing (surgical single-rank restart): every frame header carries
    the cluster epoch (``PATHWAY_CLUSTER_EPOCH``, bumped by the supervisor on
    every relaunch). When a rank dies, survivors broadcast a ``FENCE`` control
    frame, abort their in-flight barriers with :class:`ClusterFenceError`, and
    quiesce in :meth:`await_rejoin`; the supervisor relaunches ONLY the dead
    rank with ``PATHWAY_CLUSTER_REJOIN=1`` and the next epoch, and that
    replacement dials back into every survivor's still-open listener. On
    install the survivors adopt the new epoch and drop every stale-epoch data
    frame (in the inbox and still in flight on the wire) instead of letting it
    corrupt post-rejoin barriers that reuse the same commit tags. Knobs:
    ``PATHWAY_FENCE_TIMEOUT_S`` — how long a fenced survivor waits for the
    replacement to re-dial before giving up typed (default 180).
    """

    _HDR = struct.Struct("<III")  # tag_len, payload_len, cluster_epoch

    #: real socket mesh supports the fence/rejoin protocol (the in-process
    #: ThreadExchange does not — a thread peer cannot be relaunched)
    supports_rejoin = True

    def __init__(self, n_processes: int, process_id: int, first_port: int):
        self.n = n_processes
        self.me = process_id
        self.first_port = first_port
        self._conns: Dict[int, socket.socket] = {}
        self._conn_gen: Dict[int, int] = {}  # bumped when a peer link is replaced
        self._send_locks: Dict[int, threading.Lock] = {}
        self._inbox: Dict[tuple, bytes] = {}  # (peer, tag) -> payload
        self._inbox_count: Dict[int, int] = {}  # buffered frames per peer
        self._cv = threading.Condition()
        self._closed = False
        self._dead: Dict[int, str] = {}  # peer -> reason its link died
        self._last_heard: Dict[int, float] = {}
        # EWMA of peer_wall - local_wall per peer, estimated from the wall
        # stamp every heartbeat beacon carries (the trace merger aligns
        # per-rank span files with these; see clock_offsets())
        self._clock_offsets: Dict[int, float] = {}
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self.epoch = max(0, int(_env_float("PATHWAY_CLUSTER_EPOCH", 0)))
        self._rejoin_mode = os.environ.get("PATHWAY_CLUSTER_REJOIN") == "1"
        # elastic membership: a JOINER process of a grow transition
        # (PATHWAY_MEMBERSHIP_JOIN=1, PATHWAY_MEMBERSHIP_FROM=<old n>) wires
        # into the live mesh and waits for the members' install; existing
        # members park joiner hellos (which may arrive before their engines
        # have even read the directive) until apply_membership installs them
        self._membership_join = os.environ.get("PATHWAY_MEMBERSHIP_JOIN") == "1"
        self._membership_from = max(
            0, int(_env_float("PATHWAY_MEMBERSHIP_FROM", 0))
        )
        self._pending_rejoin: Dict[int, tuple] = {}  # rank -> (socket, epoch)
        self._fence_dead: "set[int]" = set()  # ranks peers told us died
        self._fence_pending = False
        # frames from an epoch we have not adopted YET: a survivor that
        # installed the rejoin first may talk to us before our own install
        # (parked here, delivered at install — dropping them would wedge the
        # post-rejoin replay until the barrier deadline)
        self._future_inbox: Dict[tuple, tuple] = {}  # (peer, tag) -> (payload, epoch)
        self.stale_frames_dropped = 0
        # incremental-rewind serve log: per-commit ring of every barrier this
        # rank sent (tag -> per-peer parts), in order. A fenced survivor that
        # rewound only the interrupted commit SERVES a replacement's tail
        # replay from this log instead of resetting + replaying its own
        # journal — the replayed commits regenerate the same deterministic tag
        # sequence, so replaying the logged parts is indistinguishable from
        # recomputing them. Bounded by PATHWAY_UNDO_RING_DEPTH commits and
        # pruned at every coordinated checkpoint (replays never reach behind
        # the manifest commit).
        self._commit_log: "OrderedDict[int, List[tuple]]" = OrderedDict()
        self._commit_log_open: Optional[int] = None
        self.commit_log_depth = max(
            0, int(_env_float("PATHWAY_UNDO_RING_DEPTH", 64))
        )
        self.barrier_timeout_s = _env_float("PATHWAY_BARRIER_TIMEOUT_S", 300.0)
        self.heartbeat_interval_s = _env_float("PATHWAY_HEARTBEAT_INTERVAL_S", 1.0)
        self.heartbeat_timeout_s = _env_float("PATHWAY_HEARTBEAT_TIMEOUT_S", 60.0)
        self.fence_timeout_s = _env_float("PATHWAY_FENCE_TIMEOUT_S", 180.0)
        self._inbox_limit = max(
            1, int(_env_float("PATHWAY_EXCHANGE_INBOX_FRAMES", 1024))
        )
        from pathway_tpu.internals.chaos import get_chaos

        self._chaos = get_chaos()
        if self._membership_join and self.n > 1:
            self._connect_membership()
        elif self._rejoin_mode and self.n > 1:
            self._connect_rejoin()
        else:
            self._connect_all()
        now = time.monotonic()
        for peer in self._conns:
            self._last_heard[peer] = now
            self._inbox_count[peer] = 0
            self._conn_gen[peer] = 0
        for peer, conn in self._conns.items():
            self._start_reader(peer, conn)
        if self.heartbeat_interval_s > 0:
            # one beacon thread PER PEER: a send stalled on one backpressured
            # link (full socket buffer) must not starve beacons to the others —
            # that would read as a false cluster-wide wedge
            for peer in self._conns:
                self._start_heartbeat(peer)
        # the listener stays open for the cluster's lifetime: a surgically
        # relaunched rank rejoins by dialing it (parked until the engine
        # reaches the fence and installs the link)
        if self._listener is not None:
            threading.Thread(
                target=self._rejoin_acceptor, daemon=True,
                name="pathway:cluster-rejoin-accept",
            ).start()

    def _start_reader(self, peer: int, conn: socket.socket) -> None:
        threading.Thread(
            target=self._reader, args=(peer, conn), daemon=True,
            name=f"pathway:cluster-rx-{peer}",
        ).start()

    def _start_heartbeat(self, peer: int) -> None:
        threading.Thread(
            target=self._heartbeat_loop, args=(peer, self._conn_gen.get(peer, 0)),
            daemon=True, name=f"pathway:cluster-hb-{peer}",
        ).start()

    # -- wiring --------------------------------------------------------------

    def _connect_all(self) -> None:
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", self.first_port + self.me))
        listener.listen(self.n)
        self._listener = listener

        accepted: Dict[int, socket.socket] = {}
        accept_errors: List[BaseException] = []

        def accept_loop() -> None:
            try:
                for _ in range(self.me):  # lower-ranked peers dial us
                    conn, _addr = listener.accept()
                    peer = int.from_bytes(self._recv_exact(conn, 4), "little")
                    accepted[peer] = conn
            except BaseException as exc:  # surfaced after join: silent partial
                accept_errors.append(exc)  # wiring would drop peers' data

        acceptor = threading.Thread(target=accept_loop, daemon=True)
        acceptor.start()
        connect_budget = _env_float("PATHWAY_CONNECT_TIMEOUT_S", 60.0)
        try:
            rng = random.Random((self.me << 16) ^ self.first_port)
            for peer in range(self.me + 1, self.n):
                s = self._dial_peer(peer, connect_budget, rng)
                s.sendall(self.me.to_bytes(4, "little"))
                self._conns[peer] = s
            acceptor.join(timeout=connect_budget)
            if acceptor.is_alive():
                raise PeerTimeoutError(
                    f"cluster process {self.me} timed out waiting for dial-ins"
                )
            if accept_errors:
                raise ConnectionError(
                    f"cluster process {self.me} failed accepting dial-ins"
                ) from accept_errors[0]
            if len(accepted) != self.me:
                raise ConnectionError(
                    f"cluster process {self.me} expected {self.me} dial-ins, got "
                    f"{sorted(accepted)}"
                )
        except BaseException:
            # failed wiring must not strand fds: a stranded listener wedges the
            # retry (and the restarted rank) on "Address already in use"
            for s in list(self._conns.values()) + list(accepted.values()):
                try:
                    s.close()
                except OSError:
                    pass
            self._conns.clear()
            try:
                listener.close()
            except OSError:
                pass
            self._listener = None
            raise
        self._conns.update(accepted)
        for peer, conn in self._conns.items():
            self._tune_socket(conn)
            self._send_locks[peer] = threading.Lock()

    def _dial_peer(
        self, peer: int, connect_budget: float, rng: random.Random
    ) -> socket.socket:
        """Dial one peer with exponential backoff + jitter: the peer may not be
        up yet, and N processes hammering one listener at a fixed 50 ms period
        synchronize into accept-queue bursts. Raises :class:`PeerTimeoutError`
        past the budget."""
        deadline = time.monotonic() + connect_budget
        delay = 0.05
        while True:
            try:
                s = socket.create_connection(
                    ("127.0.0.1", self.first_port + peer), timeout=5
                )
                break
            except OSError as exc:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise PeerTimeoutError(
                        f"cluster process {self.me} could not reach peer "
                        f"{peer} on port {self.first_port + peer} within "
                        f"{connect_budget:.0f}s"
                    ) from exc
                time.sleep(min(remaining, delay * (1.0 + 0.25 * rng.random())))
                delay = min(delay * 2, 2.0)
        # back to fully blocking: create_connection's dial timeout must not
        # linger on the socket, or every later sendall/recv on this link
        # spuriously times out after 5s of quiet (SO_SNDTIMEO and the
        # recv-side deadlines own timeout behavior from here on)
        s.settimeout(None)
        return s

    def _tune_socket(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self.barrier_timeout_s > 0:
            # send-side deadline (SO_SNDTIMEO is send-ONLY, so the reader
            # thread's blocking recv is untouched): a peer that stopped
            # reading must surface as a typed error from _send, not hang
            # sendall forever once the TCP buffers fill — _recv's deadlines
            # can't fire if we never get there
            conn.setsockopt(
                socket.SOL_SOCKET,
                socket.SO_SNDTIMEO,
                struct.pack(
                    "ll",
                    int(self.barrier_timeout_s),
                    int(self.barrier_timeout_s % 1 * 1_000_000),
                ),
            )

    def _connect_rejoin(self) -> None:
        """Relaunched-rank wiring: dial EVERY survivor's still-open listener and
        introduce ourselves with the rejoin hello (rank + new epoch). The
        survivors' acceptor threads park the links until their engines reach
        the epoch fence and install them — no accept phase on our side."""
        if self._chaos is not None and self._chaos.drop_rejoin(self.me):
            # deterministic fault injection: the rejoin handshake is "lost".
            # Failing the wiring loudly (instead of silently half-joining)
            # exercises the surgical -> restart-all escalation in the supervisor.
            raise PeerTimeoutError(
                f"chaos: rejoin handshake of rank {self.me} (epoch {self.epoch}) "
                "dropped by plan"
            )
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", self.first_port + self.me))
        listener.listen(self.n)
        self._listener = listener
        connect_budget = _env_float("PATHWAY_CONNECT_TIMEOUT_S", 60.0)
        hello = (
            _REJOIN_MAGIC
            + self.me.to_bytes(4, "little")
            + (self.epoch & 0xFFFFFFFF).to_bytes(4, "little")
        )
        rng = random.Random((self.me << 16) ^ self.first_port ^ self.epoch)
        try:
            for peer in range(self.n):
                if peer == self.me:
                    continue
                # a second dead rank (double failure) makes a survivor
                # unreachable: _dial_peer's typed timeout fails the rejoin
                # loudly so the supervisor degrades to restart-all
                s = self._dial_peer(peer, connect_budget, rng)
                s.sendall(hello)
                self._conns[peer] = s
        except BaseException:
            for s in list(self._conns.values()):
                try:
                    s.close()
                except OSError:
                    pass
            self._conns.clear()
            try:
                listener.close()
            except OSError:
                pass
            self._listener = None
            raise
        for peer, conn in self._conns.items():
            self._tune_socket(conn)
            self._send_locks[peer] = threading.Lock()

    def _membership_hello(self) -> bytes:
        return (
            _MEMBER_MAGIC
            + self.me.to_bytes(4, "little")
            + (self.epoch & 0xFFFFFFFF).to_bytes(4, "little")
            + self.n.to_bytes(4, "little")
        )

    def _connect_membership(self) -> None:
        """Joiner wiring for an elastic grow transition: ``self.n`` is the
        TARGET topology and ``self.epoch`` the transition's epoch. The joiner
        dials every existing member (ranks < PATHWAY_MEMBERSHIP_FROM) and
        every lower-ranked fellow joiner, and accepts dial-ins from
        higher-ranked joiners — members park our hello until their engines
        reach the membership quiesce point and install (``apply_membership``).
        """
        if self._chaos is not None:
            # deterministic fault injection: a joiner killed before it ever
            # installs — the headline join-side crash of the transition
            self._chaos.maybe_scale_kill(
                self.me, "scale_join_kill", epoch=self.epoch
            )
        if self._chaos is not None and self._chaos.scale_fault(
            "dropped_scale_handshake", self.me
        ):
            # deterministic fault injection: the joiner's hello is "lost" —
            # failing the wiring loudly exercises the supervisor's
            # joiner-relaunch / restart-all escalation
            raise PeerTimeoutError(
                f"chaos: membership handshake of joiner rank {self.me} "
                f"(epoch {self.epoch}) dropped by plan"
            )
        from_n = self._membership_from
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", self.first_port + self.me))
        listener.listen(self.n)
        self._listener = listener
        connect_budget = _env_float("PATHWAY_CONNECT_TIMEOUT_S", 60.0)
        higher_joiners = self.n - 1 - self.me
        accepted: Dict[int, socket.socket] = {}
        accept_errors: List[BaseException] = []

        def accept_loop() -> None:
            try:
                while len(accepted) < higher_joiners:
                    conn, _addr = listener.accept()
                    conn.settimeout(10.0)
                    hello = self._recv_exact(conn, len(_MEMBER_MAGIC) + 12)
                    conn.settimeout(None)
                    if not hello.startswith(_MEMBER_MAGIC):
                        conn.close()
                        continue
                    peer = int.from_bytes(hello[4:8], "little")
                    if not (self.me < peer < self.n):
                        conn.close()
                        continue
                    accepted[peer] = conn
            except BaseException as exc:  # surfaced after join
                accept_errors.append(exc)

        acceptor = threading.Thread(target=accept_loop, daemon=True)
        acceptor.start()
        hello = self._membership_hello()
        rng = random.Random((self.me << 16) ^ self.first_port ^ self.epoch)
        try:
            # every existing member (< from_n) and every lower-ranked joiner
            for peer in range(self.me):
                s = self._dial_peer(peer, connect_budget, rng)
                s.sendall(hello)
                self._conns[peer] = s
            if higher_joiners:
                acceptor.join(timeout=connect_budget)
                if acceptor.is_alive():
                    raise PeerTimeoutError(
                        f"joiner rank {self.me} timed out waiting for "
                        f"{higher_joiners} higher-ranked joiner dial-in(s) "
                        f"(got {sorted(accepted)})"
                    )
                if accept_errors:
                    raise ConnectionError(
                        f"joiner rank {self.me} failed accepting fellow "
                        "joiners"
                    ) from accept_errors[0]
        except BaseException:
            for s in list(self._conns.values()) + list(accepted.values()):
                try:
                    s.close()
                except OSError:
                    pass
            self._conns.clear()
            try:
                listener.close()
            except OSError:
                pass
            self._listener = None
            raise
        self._conns.update(accepted)
        for peer, conn in self._conns.items():
            self._tune_socket(conn)
            self._send_locks[peer] = threading.Lock()

    def _rejoin_acceptor(self) -> None:
        """Post-wiring accept loop: park dial-ins from relaunched ranks until
        the engine's fence path installs them (``await_rejoin``). Runs for the
        exchange's lifetime; exits when the listener closes."""
        listener = self._listener
        while not self._closed:
            try:
                conn, _addr = listener.accept()
            except OSError:
                return  # listener closed (teardown)
            try:
                conn.settimeout(10.0)
                hello = self._recv_exact(conn, len(_REJOIN_MAGIC) + 8)
                if hello.startswith(_MEMBER_MAGIC):
                    hello += self._recv_exact(conn, 4)  # + target_n
                conn.settimeout(None)
            except (ConnectionError, OSError):
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            rank = int.from_bytes(hello[4:8], "little")
            epoch = int.from_bytes(hello[8:12], "little")
            stale_conn: Optional[socket.socket] = None
            with self._cv:
                if hello.startswith(_MEMBER_MAGIC):
                    # joiner hello of an elastic grow: the rank may exceed the
                    # CURRENT n (that is the point) and may arrive before this
                    # member's engine has even read the directive — park it;
                    # apply_membership validates against the real target
                    ok = (
                        not self._closed
                        and 0 <= rank < _MAX_RANK
                        and rank != self.me
                        and epoch > self.epoch
                    )
                else:
                    ok = (
                        not self._closed
                        and hello.startswith(_REJOIN_MAGIC)
                        and 0 <= rank < self.n
                        and rank != self.me
                        # stale-epoch rejoins (a zombie replacement from an
                        # abandoned attempt) are refused, not installed
                        and epoch > self.epoch
                    )
                if ok:
                    old = self._pending_rejoin.pop(rank, None)
                    if old is not None:
                        stale_conn = old[0]
                    self._pending_rejoin[rank] = (conn, epoch)
                    self._cv.notify_all()
            if not ok:
                stale_conn = conn
            if stale_conn is not None:
                try:
                    stale_conn.close()
                except OSError:
                    pass

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("cluster peer closed the connection")
            buf += chunk
        return buf

    def _reader(self, peer: int, conn: socket.socket) -> None:
        try:
            while True:
                hdr = self._recv_exact(conn, self._HDR.size)
                tag_len, payload_len, frame_epoch = self._HDR.unpack(hdr)
                tag = self._recv_exact(conn, tag_len)
                payload = self._recv_exact(conn, payload_len) if payload_len else b""
                if tag == HEARTBEAT_TAG and payload:
                    # outside _cv: the tracer push takes its own lock and must
                    # not nest under the mesh condition
                    self._note_peer_clock(peer, payload)
                if tag != HEARTBEAT_TAG:
                    _stage_add_many({
                        f"exchange.peer{peer}.bytes_received": float(
                            self._HDR.size + tag_len + payload_len
                        ),
                        f"exchange.peer{peer}.frames_received": 1.0,
                    })
                with self._cv:
                    self._last_heard[peer] = time.monotonic()
                    if tag == HEARTBEAT_TAG:
                        # beacons prove liveness whatever the epoch — a peer
                        # mid-fence is alive, not stale
                        self._cv.notify_all()
                        continue
                    if tag == FENCE_TAG:
                        if frame_epoch >= self.epoch:
                            try:
                                ranks = pickle.loads(payload)
                            except Exception:
                                ranks = []
                            self._fence_dead.update(int(r) for r in ranks)
                            self._fence_pending = True
                            _stage_add("cluster.fences_received")
                            _flight_recorder().record_event(
                                "fence_received",
                                from_peer=peer,
                                dead_ranks=sorted(self._fence_dead),
                                epoch=self.epoch,
                            )
                            self._cv.notify_all()
                        continue
                    # bounded inbox: park until the consumer drains (the unread
                    # backlog itself proves the peer is alive, so keep the
                    # heartbeat clock fresh while parked — the peer's beacons
                    # queue behind the data we are not reading)
                    while (
                        self._inbox_count[peer] >= self._inbox_limit
                        and not self._closed
                        and frame_epoch >= self.epoch
                    ):
                        self._last_heard[peer] = time.monotonic()
                        self._cv.wait(timeout=0.2)
                    if self._closed:
                        return
                    if frame_epoch < self.epoch:
                        # stale-epoch data frame (sent before the sender
                        # fenced): DROPPED, never delivered — post-rejoin
                        # barriers replay the same commit tags, and a stale
                        # payload under a reused tag would silently corrupt
                        # them
                        self.stale_frames_dropped += 1
                        continue
                    if frame_epoch > self.epoch:
                        # a peer that installed the rejoin BEFORE us is already
                        # talking at the new epoch: park the frame (it still
                        # counts toward the inbox bound) and deliver it when
                        # our own install adopts that epoch — dropping it would
                        # lose a barrier part nobody retransmits
                        self._future_inbox[(peer, tag)] = (payload, frame_epoch)
                        self._inbox_count[peer] += 1
                        self._cv.notify_all()
                        continue
                    self._inbox[(peer, tag)] = payload
                    self._inbox_count[peer] += 1
                    self._cv.notify_all()
        except (ConnectionError, OSError) as exc:
            with self._cv:
                # a replaced link (rejoin installed a fresh socket for this
                # peer) dying late must not re-mark the NEW link dead
                if self._conns.get(peer) is conn:
                    self._dead.setdefault(peer, str(exc) or type(exc).__name__)
                self._cv.notify_all()

    def _send(self, peer: int, tag: bytes, payload: bytes) -> None:
        conn = self._conns.get(peer)
        if conn is None:
            # link removed by a membership shrink: a stale heartbeat thread
            # racing the install must simply stop, not KeyError
            return
        lock = self._send_locks.get(peer)
        if lock is None:
            return
        frame = (
            self._HDR.pack(len(tag), len(payload), self.epoch & 0xFFFFFFFF)
            + tag
            + payload
        )
        if self._chaos is not None and tag not in (HEARTBEAT_TAG, FENCE_TAG):
            action = self._chaos.frame_action(self.me, peer)
            if action.kind == "drop":
                return  # peer's barrier deadline turns this into PeerTimeoutError
            if action.kind == "delay":
                time.sleep(action.delay_s)
            elif action.kind == "truncate":
                # torn write + dead link, as a crash mid-send would leave it
                with lock:
                    try:
                        conn.sendall(frame[: max(1, len(frame) // 2)])
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                with self._cv:
                    self._dead.setdefault(peer, "chaos: link truncated")
                    self._cv.notify_all()
                return
        try:
            with lock:
                conn.sendall(frame)
            if tag != HEARTBEAT_TAG:
                # per-peer traffic accounting (heartbeats excluded — 1 Hz
                # beacons would drown the data-frame signal)
                _stage_add_many({
                    f"exchange.peer{peer}.bytes_sent": float(len(frame)),
                    f"exchange.peer{peer}.frames_sent": 1.0,
                })
        except OSError as exc:
            timed_out = isinstance(exc, (socket.timeout, BlockingIOError))
            with self._cv:
                # the stream may have a torn partial frame on it now — the
                # link is unusable either way, so the peer is dead to us
                # (unless the link was already replaced by a rejoin: a stale
                # heartbeat thread failing on the OLD socket must not poison
                # the freshly installed one)
                if self._conns.get(peer) is conn:
                    self._dead.setdefault(peer, str(exc) or type(exc).__name__)
                self._cv.notify_all()
            if timed_out:
                raise PeerTimeoutError(
                    f"cluster process {self.me} send of {tag!r} to peer {peer} "
                    f"stalled past the {self.barrier_timeout_s:.0f}s deadline "
                    "— peer stopped reading"
                ) from exc
            raise PeerShutdownError(
                f"cluster process {self.me} failed sending {tag!r} to peer "
                f"{peer}: {exc}"
            ) from exc

    def _recv(self, peer: int, tag: bytes, timeout: Optional[float] = None) -> bytes:
        if timeout is None:
            timeout = self.barrier_timeout_s
        deadline = time.monotonic() + timeout
        with self._cv:
            while (peer, tag) not in self._inbox:
                if self._fence_pending:
                    raise ClusterFenceError(
                        f"cluster peer requested an epoch fence (ranks "
                        f"{sorted(self._fence_dead)} died) while process "
                        f"{self.me} waited for {tag!r} at epoch {self.epoch}"
                    )
                if peer in self._dead:
                    raise PeerShutdownError(
                        f"cluster peer {peer} disconnected while process "
                        f"{self.me} waited for {tag!r}: {self._dead[peer]}"
                    )
                if self._closed:
                    raise PeerShutdownError(
                        f"cluster exchange closed while waiting for {tag!r} "
                        f"from peer {peer}"
                    )
                now = time.monotonic()
                heard = self._last_heard.get(peer)
                if (
                    self.heartbeat_timeout_s > 0
                    # without beacons, silence between barriers is normal —
                    # staleness is only meaningful while heartbeats flow
                    and self.heartbeat_interval_s > 0
                    and heard is not None
                    and now - heard > self.heartbeat_timeout_s
                ):
                    _stage_add("cluster.peer_stale_trips")
                    _flight_recorder().record_event(
                        "peer_stale",
                        peer=peer,
                        tag=tag.decode("utf-8", "replace"),
                        stale_s=round(now - heard, 3),
                    )
                    raise PeerTimeoutError(
                        f"cluster peer {peer} heartbeat is {now - heard:.1f}s "
                        f"stale (> {self.heartbeat_timeout_s:.0f}s) while process "
                        f"{self.me} waited for {tag!r} — peer is wedged"
                    )
                remaining = deadline - now
                if remaining <= 0:
                    _stage_add("cluster.barrier_timeouts")
                    _flight_recorder().record_event(
                        "barrier_timeout",
                        peer=peer,
                        tag=tag.decode("utf-8", "replace"),
                        timeout_s=timeout,
                    )
                    raise PeerTimeoutError(
                        f"cluster process {self.me} timed out after "
                        f"{timeout:.0f}s waiting for {tag!r} from peer {peer}"
                    )
                self._cv.wait(timeout=min(remaining, 0.5))
            payload = self._inbox.pop((peer, tag))
            self._inbox_count[peer] -= 1
            self._cv.notify_all()  # unpark a backpressured reader
            return payload

    # -- liveness -------------------------------------------------------------

    def _heartbeat_loop(self, peer: int, gen: int = 0) -> None:
        while not self._stop.wait(self.heartbeat_interval_s):
            with self._cv:
                # _dead/_conn_gen are _cv-owned state; reading them unlocked
                # raced the rejoin install (PWA103 — a torn read could keep a
                # stale beacon thread alive against a replaced link)
                stale = (
                    self._closed
                    or peer in self._dead
                    # the link was replaced by a rejoin; its NEW heartbeat
                    # thread owns the beacons now
                    or self._conn_gen.get(peer, 0) != gen
                )
            if stale:
                return
            try:
                # beacons carry the sender's wall clock: receivers estimate
                # per-peer clock offsets for the trace merger's alignment
                self._send(peer, HEARTBEAT_TAG, struct.pack("<d", time.time()))
            except (PeerShutdownError, OSError):
                return  # _send already recorded the death

    def _note_peer_clock(self, peer: int, payload: bytes) -> None:
        """A heartbeat beacon carried the sender's wall clock: EWMA the
        ``peer_wall - local_wall`` offset (biased by one-way latency — good to
        ~ms on a LAN, plenty to causally order cross-rank spans) and publish
        the table to the tracer so every flush's ``_meta`` carries it."""
        try:
            (sender_wall,) = struct.unpack("<d", payload)
        except struct.error:
            return  # malformed beacon: liveness already counted, skip the clock
        sample = sender_wall - time.time()
        with self._cv:
            prev = self._clock_offsets.get(peer)
            self._clock_offsets[peer] = (
                sample if prev is None else prev + 0.2 * (sample - prev)
            )
            offsets = dict(self._clock_offsets)
        _get_tracer().set_clock_offsets(offsets)

    def clock_offsets(self) -> Dict[int, float]:
        """Heartbeat-estimated ``peer_wall - local_wall`` seconds per peer
        (the trace merger aligns per-rank span files with these)."""
        with self._cv:
            return dict(self._clock_offsets)

    def heartbeat_ages(self) -> Dict[int, float]:
        """Seconds since each peer was last heard from (any frame). The shared
        liveness signal: served by ``/healthz`` and written to the supervisor's
        per-rank status file."""
        now = time.monotonic()
        with self._cv:
            return {peer: now - t for peer, t in self._last_heard.items()}

    def dead_peers(self) -> Dict[int, str]:
        with self._cv:
            return dict(self._dead)

    # -- epoch fence / surgical rejoin ----------------------------------------

    def begin_fence(self) -> None:
        """Tell every live peer this rank observed a death and is quiescing at
        the epoch fence. Peers abort their in-flight barriers with
        :class:`ClusterFenceError` within socket latency instead of sitting out
        the full barrier deadline. Best-effort: a peer whose link also died
        learns about the fence from its own typed error."""
        with self._cv:
            dead = sorted(set(self._dead) | self._fence_dead)
        _stage_add("cluster.fence_broadcasts")
        _flight_recorder().record_event(
            "fence_broadcast", dead_ranks=dead, epoch=self.epoch
        )
        payload = pickle.dumps(dead, protocol=pickle.HIGHEST_PROTOCOL)
        for peer in list(self._conns):
            if peer in dead:
                continue
            try:
                self._send(peer, FENCE_TAG, payload)
            except (PeerShutdownError, PeerTimeoutError, OSError):
                pass

    def await_rejoin(
        self,
        timeout: Optional[float] = None,
        on_wait: "Optional[Callable[[], None]]" = None,
    ) -> int:
        """Quiesce at the epoch fence until the supervisor's replacement
        rank(s) re-dial, then install the new link(s) and adopt their epoch.

        Returns the new cluster epoch. ``on_wait`` (if given) is called every
        poll interval WITHOUT the exchange lock held — the engine uses it to
        keep publishing liveness status so the supervisor's staleness monitor
        doesn't shoot a healthy, fenced survivor. Raises
        :class:`PeerTimeoutError` when no replacement arrives in time (second
        failure, exhausted restart budget — the caller escalates)."""
        if timeout is None:
            timeout = self.fence_timeout_s
        deadline = time.monotonic() + timeout
        while True:
            installed: Dict[int, tuple] = {}
            old_conns: List[socket.socket] = []
            with self._cv:
                # parked MEMBERSHIP hellos (rank >= n, a pending grow) are
                # not replacements: they stay parked for apply_membership
                replacements = {
                    r: v for r, v in self._pending_rejoin.items() if r < self.n
                }
                waiting = (set(self._dead) | self._fence_dead) - set(
                    replacements
                )
                if not waiting and replacements:
                    installed = replacements
                    for r in replacements:
                        self._pending_rejoin.pop(r, None)
                    new_epoch = max(e for (_c, e) in installed.values())
                    for rank, (conn, _e) in installed.items():
                        old = self._conns.get(rank)
                        if old is not None and old is not conn:
                            old_conns.append(old)
                        self._conns[rank] = conn
                        self._conn_gen[rank] = self._conn_gen.get(rank, 0) + 1
                        self._dead.pop(rank, None)
                        self._last_heard[rank] = time.monotonic()
                        # minted under _cv: _send reads this dict from
                        # heartbeat threads concurrently with the install
                        self._send_locks.setdefault(rank, threading.Lock())
                    # the aborted epoch's frames must never meet the replayed
                    # barriers that reuse their tags: purge the whole inbox
                    # (parked readers wake, re-check the epoch, and drop)
                    self.stale_frames_dropped += len(self._inbox)
                    self._inbox.clear()
                    for p in self._inbox_count:
                        self._inbox_count[p] = 0
                    # deliver frames peers already sent at the epoch we are
                    # adopting (they installed first and raced ahead of us)
                    future, self._future_inbox = self._future_inbox, {}
                    for (peer, tag), (payload, ep) in future.items():
                        if ep == new_epoch and peer in self._conns:
                            self._inbox[(peer, tag)] = payload
                            self._inbox_count[peer] = (
                                self._inbox_count.get(peer, 0) + 1
                            )
                        else:
                            self.stale_frames_dropped += 1
                    self._fence_dead.clear()
                    self._fence_pending = False
                    self.epoch = new_epoch
                    self._cv.notify_all()
                elif self._closed:
                    raise PeerShutdownError(
                        f"cluster exchange closed while process {self.me} "
                        "fenced for a rejoin"
                    )
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise PeerTimeoutError(
                            f"process {self.me} fenced at epoch {self.epoch} "
                            f"but no replacement rank re-dialed within "
                            f"{timeout:.0f}s (waiting on {sorted(waiting)})"
                        )
                    self._cv.wait(timeout=min(remaining, 0.25))
            if installed:
                for old in old_conns:
                    try:
                        old.close()
                    except OSError:
                        pass
                for rank, (conn, _e) in installed.items():
                    self._tune_socket(conn)
                    self._start_reader(rank, conn)
                    if self.heartbeat_interval_s > 0:
                        self._start_heartbeat(rank)
                _stage_add("cluster.rejoins_installed")
                _flight_recorder().record_event(
                    "rejoin_installed",
                    ranks=sorted(installed),
                    epoch=self.epoch,
                )
                return self.epoch
            if on_wait is not None:
                on_wait()

    # -- elastic membership (grow/shrink the live mesh) ------------------------

    def apply_membership(
        self,
        new_n: int,
        new_epoch: int,
        timeout: Optional[float] = None,
        on_wait: "Optional[Callable[[], None]]" = None,
    ) -> int:
        """Install the new topology on an EXISTING member at the membership
        quiesce point: wait for every joiner's parked dial-in (grow), or cut
        the draining ranks' links (shrink), then atomically adopt ``new_n``
        and ``new_epoch`` — purging the old epoch's inbox and delivering
        frames peers already sent at the new epoch (members that applied
        first race ahead exactly like staggered rejoin installs).

        Returns the new epoch. Raises :class:`PeerTimeoutError` when a
        joiner never dials in (killed/dropped handshake — the caller dies
        typed and the supervisor escalates)."""
        if timeout is None:
            timeout = self.fence_timeout_s
        deadline = time.monotonic() + timeout
        joiner_ranks = {r for r in range(new_n) if r >= self.n}
        while True:
            installed: Dict[int, tuple] = {}
            removed_conns: List[socket.socket] = []
            with self._cv:
                ready = {
                    r
                    for r, (_c, ep) in self._pending_rejoin.items()
                    if r in joiner_ranks and ep == new_epoch
                }
                if ready >= joiner_ranks:
                    for rank in sorted(joiner_ranks):
                        conn, _ep = self._pending_rejoin.pop(rank)
                        installed[rank] = (conn, new_epoch)
                        self._conns[rank] = conn
                        self._conn_gen[rank] = self._conn_gen.get(rank, 0) + 1
                        self._send_locks.setdefault(rank, threading.Lock())
                        self._last_heard[rank] = time.monotonic()
                        self._inbox_count.setdefault(rank, 0)
                    # shrink: cut the draining ranks' links (their readers see
                    # the conn replaced/absent and never mark them dead)
                    for rank in [r for r in self._conns if r >= new_n]:
                        removed_conns.append(self._conns.pop(rank))
                        self._conn_gen[rank] = self._conn_gen.get(rank, 0) + 1
                        self._send_locks.pop(rank, None)
                        self._last_heard.pop(rank, None)
                        self._inbox_count.pop(rank, None)
                        self._dead.pop(rank, None)
                        self._fence_dead.discard(rank)
                    # zombie hellos of abandoned attempts: refuse, never keep
                    for rank in [
                        r
                        for r, (_c, ep) in self._pending_rejoin.items()
                        if ep <= new_epoch
                    ]:
                        removed_conns.append(self._pending_rejoin.pop(rank)[0])
                    # the old epoch's frames must never meet the new
                    # topology's barriers (same discipline as a rejoin
                    # install): purge, then deliver parked new-epoch frames
                    self.stale_frames_dropped += len(self._inbox)
                    self._inbox.clear()
                    for p in self._inbox_count:
                        self._inbox_count[p] = 0
                    future, self._future_inbox = self._future_inbox, {}
                    for (peer, tag), (payload, ep) in future.items():
                        if ep == new_epoch and peer in self._conns:
                            self._inbox[(peer, tag)] = payload
                            self._inbox_count[peer] = (
                                self._inbox_count.get(peer, 0) + 1
                            )
                        else:
                            self.stale_frames_dropped += 1
                    self.n = new_n
                    self.epoch = new_epoch
                    self._cv.notify_all()
                elif self._closed:
                    raise PeerShutdownError(
                        f"cluster exchange closed while process {self.me} "
                        "waited to apply the membership change"
                    )
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise PeerTimeoutError(
                            f"process {self.me} waited {timeout:.0f}s for "
                            f"joiner rank(s) {sorted(joiner_ranks - ready)} "
                            f"to dial in at epoch {new_epoch} — membership "
                            "change cannot complete"
                        )
                    self._cv.wait(timeout=min(remaining, 0.25))
            if installed or not joiner_ranks:
                for conn in removed_conns:
                    try:
                        conn.close()
                    except OSError:
                        pass
                for rank, (conn, _e) in installed.items():
                    self._tune_socket(conn)
                    self._start_reader(rank, conn)
                    if self.heartbeat_interval_s > 0:
                        self._start_heartbeat(rank)
                _stage_add("cluster.membership_applied")
                _flight_recorder().record_event(
                    "membership_applied",
                    n=self.n,
                    epoch=self.epoch,
                    joined=sorted(installed),
                )
                return self.epoch
            if on_wait is not None:
                on_wait()

    def leave_membership(self) -> None:
        """A draining leaver's mesh teardown (after the final old-topology
        barrier): just the idempotent close — survivors have already stopped
        addressing this rank, and their readers ignore links no longer in
        ``_conns``."""
        _stage_add("cluster.membership_left")
        _flight_recorder().record_event(
            "membership_left", rank=self.me, epoch=self.epoch
        )
        self.close()

    # -- incremental-rewind serve log -----------------------------------------

    def begin_commit_log(self, commit_id: int) -> None:
        """Open the serve-log entry for one live commit: every barrier sent
        until :meth:`end_commit_log` is recorded under this id. Called from the
        single engine thread only."""
        if self.commit_log_depth <= 0:
            return
        self._commit_log.pop(commit_id, None)
        self._commit_log[commit_id] = []
        self._commit_log_open = commit_id

    def end_commit_log(self) -> None:
        """Seal the open entry (the commit completed) and evict the oldest
        entries past the depth bound."""
        self._commit_log_open = None
        while len(self._commit_log) > self.commit_log_depth:
            self._commit_log.popitem(last=False)

    def discard_open_commit_log(self) -> None:
        """Drop the in-flight entry: an interrupted commit's partial barrier
        stream must never be served (its tags will be regenerated live after
        the rewind)."""
        if self._commit_log_open is not None:
            self._commit_log.pop(self._commit_log_open, None)
            self._commit_log_open = None

    def commit_log_covers(self, commit_ids: "List[int]") -> bool:
        return all(cid in self._commit_log for cid in commit_ids)

    def serve_commit_log(self, commit_id: int) -> int:
        """Re-run every logged barrier of one commit with the ORIGINAL parts,
        discarding what peers send back (a serving survivor already holds the
        results in its live state). Returns the number of barriers served."""
        entries = self._commit_log.get(commit_id, ())
        for tag, parts in entries:
            self.exchange_parts(tag, parts)
        return len(entries)

    def prune_commit_log(self, through_commit: int) -> None:
        """Drop sealed entries ≤ ``through_commit`` (a durable checkpoint
        manifest guarantees no replay will ever reach behind it)."""
        for cid in [c for c in self._commit_log if c <= through_commit]:
            if cid != self._commit_log_open:
                del self._commit_log[cid]

    # -- collectives ----------------------------------------------------------

    def exchange_parts(self, tag: bytes, parts: Dict[int, bytes]) -> Dict[int, bytes]:
        """All-to-all: send ``parts[peer]`` to each peer, receive theirs. Barrier.

        Raises :class:`PeerShutdownError` when a peer's link died, or
        :class:`PeerTimeoutError` when a peer missed the barrier deadline or
        went heartbeat-stale — never blocks forever on a dead peer.

        Straggler attribution: the peer whose frame this process BLOCKED on
        longest arrived last (frames already inboxed cost ~0), so per-barrier
        wait seconds and a per-peer straggler count land in the stage
        counters; the flight recorder's ``note_barrier`` marks the tag in
        flight so a death mid-barrier names it in the dump."""
        recorder = _flight_recorder()
        if self._commit_log_open is not None:
            # live commit under the rewind contract: remember exactly what this
            # barrier sent, so a post-fence serve can replay it verbatim
            self._commit_log[self._commit_log_open].append((tag, dict(parts)))
        for peer in self._conns:
            self._send(peer, tag, parts.get(peer, b""))
        recorder.note_barrier(tag)
        t0 = time.perf_counter()
        out: Dict[int, bytes] = {}
        slowest_peer = -1
        slowest_wait = 0.0
        for peer in self._conns:
            w0 = time.perf_counter()
            out[peer] = self._recv(peer, tag)
            wait = time.perf_counter() - w0
            if wait > slowest_wait:
                slowest_wait = wait
                slowest_peer = peer
        barrier_wait = time.perf_counter() - t0
        updates = {
            "exchange.barriers": 1.0,
            "exchange.barrier_wait_s": barrier_wait,
        }
        if slowest_peer >= 0 and slowest_wait > 0.001:
            # only meaningful blocking attributes a straggler: an inboxed
            # frame's ~µs pop must not smear the attribution
            updates[f"exchange.straggler.peer{slowest_peer}"] = 1.0
            updates[f"exchange.peer{slowest_peer}.straggler_wait_s"] = slowest_wait
        _stage_add_many(updates)
        tracer = _get_tracer()
        if tracer.enabled and _trace_current() is not None:
            # a barrier inside a traced scope (the commit span's context-local
            # parent) becomes a child span carrying the SAME straggler
            # attribution the stage counters got — "barrier held 41 ms by
            # rank 3" in the merged critical path
            span = tracer.start(
                "barrier", f"barrier {tag.decode('utf-8', 'replace')}"
            )
            if span is not None:
                span.ts -= barrier_wait  # stamp the barrier's START
                span.ts_mono -= barrier_wait
                span.duration_s = max(barrier_wait, 1e-9)
                if slowest_peer >= 0 and slowest_wait > 0.001:
                    span.attrs["straggler_rank"] = slowest_peer
                    span.attrs["straggler_wait_s"] = slowest_wait
                tracer.finish(span)
        # cleared on SUCCESS only: when a recv raises (peer death, barrier
        # timeout) the mark must survive the unwind — the fence/crash dump's
        # summary names this tag as the pending barrier, and the next
        # successful barrier overwrites it anyway
        recorder.note_barrier(None)
        return out

    def allgather(self, tag: bytes, value: Any) -> List[Any]:
        """Every process contributes ``value``; all receive the full list (by rank)."""
        blob = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        got = self.exchange_parts(tag, {p: blob for p in self._conns})
        out: List[Any] = [None] * self.n
        out[self.me] = value
        for peer, payload in got.items():
            out[peer] = pickle.loads(payload)
        return out

    def close(self) -> None:
        """Idempotent teardown — safe to call again from the fence path when a
        rejoin aborts mid-handshake (never double-closes peer sockets, parked
        rejoin dial-ins, or the listener)."""
        self._stop.set()
        with self._cv:
            if self._closed:
                return
            self._closed = True
            pending = list(self._pending_rejoin.values())
            self._pending_rejoin = {}
            conns = list(self._conns.values())
            listener, self._listener = self._listener, None
            self._cv.notify_all()  # release parked readers and waiting recvs
        for conn, _epoch in pending:
            try:
                conn.close()
            except OSError:
                pass
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        if listener is not None:
            try:
                # shutdown BEFORE close: the rejoin acceptor blocks in
                # accept() on this fd, and a plain close would leave that
                # in-flight syscall holding the open file description — the
                # port would stay bound and wedge a relaunched rank on
                # EADDRINUSE. shutdown wakes the acceptor with an error first.
                listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                listener.close()
            except OSError:
                pass

    # -- delta routing ---------------------------------------------------------

    def exchange_delta(self, tag: bytes, delta: Any, route_keys: np.ndarray) -> Any:
        """Hash-route a commit's delta rows to their owner process and merge what
        this process owns (reference shard routing, ``shard.rs:15-20``): owner =
        key.lo % n. Returns the merged delta (own partition + received rows)."""
        from pathway_tpu.engine.columnar import Delta
        from pathway_tpu.internals.keys import shard_of

        owners = shard_of(route_keys, self.n)
        # the sender's trace context rides each frame (5th tuple slot,
        # length-tolerant on receive): receivers link the sender's span into
        # their own commit trace, making the routed delta a causal edge
        ctx = _trace_current()
        rider = _format_trace_header(ctx) if ctx is not None else None
        parts: Dict[int, bytes] = {}
        for peer in range(self.n):
            if peer == self.me:
                continue
            rows = np.nonzero(owners == peer)[0]
            if len(rows):
                sub = delta.select(rows)
                parts[peer] = pickle.dumps(
                    (sub.keys, sub.diffs, sub.columns, sub.neu, rider),
                    protocol=pickle.HIGHEST_PROTOCOL,
                )
            else:
                parts[peer] = b""
        received = self.exchange_parts(tag, parts)
        mine = delta.select(np.nonzero(owners == self.me)[0])
        merged = [mine]
        link_ctxs = []
        for peer in sorted(received):
            payload = received[peer]
            if payload:
                unpacked = pickle.loads(payload)
                keys, diffs, columns, neu = unpacked[:4]
                if len(unpacked) > 4 and unpacked[4]:
                    peer_ctx = _parse_trace_header(unpacked[4])
                    if peer_ctx is not None:
                        link_ctxs.append(peer_ctx)
                merged.append(Delta(keys, diffs, columns, neu=neu))
        tracer = _get_tracer()
        if link_ctxs and tracer.enabled and ctx is not None:
            span = tracer.start(
                "exchange",
                f"exchange {tag.decode('utf-8', 'replace')}",
                links=tuple(link_ctxs),
            )
            if span is not None:
                span.duration_s = 1e-9  # a causal edge, not a timed wait
                tracer.finish(span)
        if len(merged) == 1:
            return mine
        return Delta.concat(merged, list(delta.columns))

    @staticmethod
    def _pack(delta: Any) -> bytes:
        return pickle.dumps(
            (delta.keys, delta.diffs, delta.columns, delta.neu),
            protocol=pickle.HIGHEST_PROTOCOL,
        )

    def exchange_to_root(self, tag: bytes, delta: Any) -> Any:
        """Centralize: every process ships its whole delta to process 0 (the
        reference routes temporal-behavior input to one worker,
        ``time_column.rs:48-51``). Process 0 returns the rank-ordered merge;
        everyone else returns an empty delta. Barrier."""
        from pathway_tpu.engine.columnar import Delta

        columns = list(delta.columns)
        parts: Dict[int, bytes] = {p: b"" for p in self._conns}
        if self.me != 0 and len(delta):
            parts[0] = self._pack(delta)
        received = self.exchange_parts(tag, parts)
        if self.me != 0:
            return Delta.empty(columns)
        merged = [delta]
        for peer in sorted(received):
            payload = received[peer]
            if payload:
                keys, diffs, cols, neu = pickle.loads(payload)
                merged.append(Delta(keys, diffs, cols, neu=neu))
        if len(merged) == 1:
            return delta
        return Delta.concat(merged, columns)

    def broadcast_merge(self, tag: bytes, delta: Any) -> Any:
        """Replicate: every process contributes its delta; ALL processes return the
        same rank-ordered merge (replicated-state operators, e.g. the external
        index's data side — every process holds the full index, queries answer
        locally). Barrier."""
        from pathway_tpu.engine.columnar import Delta

        columns = list(delta.columns)
        blob = self._pack(delta) if len(delta) else b""
        received = self.exchange_parts(tag, {p: blob for p in self._conns})
        by_rank: List[Any] = [None] * self.n
        by_rank[self.me] = delta
        for peer, payload in received.items():
            if payload:
                keys, diffs, cols, neu = pickle.loads(payload)
                by_rank[peer] = Delta(keys, diffs, cols, neu=neu)
        merged = [d for d in by_rank if d is not None and len(d)]
        if not merged:
            return Delta.empty(columns)
        if len(merged) == 1:
            return merged[0]
        return Delta.concat(merged, columns)


class ThreadExchangeHub:
    """Shared mailbox for the in-process worker-thread exchange: the timely
    shared-memory allocator's slot, where ``spawn -n``'s TCP mesh is its
    process allocator (``external/timely-dataflow/communication/src/initialize.rs:25-31``
    distinguishes exactly these two)."""

    def __init__(self, n: int):
        self.n = n
        self.boxes: Dict[tuple, bytes] = {}  # (dst, src, tag) -> payload
        self.cv = threading.Condition()
        self.closed = False
        # transparent-threads mode (one shared graph): sources ingest on rank 0
        # and outputs centralize there; compute partitions across all ranks
        self.shared_inputs = False

    def close(self) -> None:
        with self.cv:
            self.closed = True
            self.cv.notify_all()


class PeerShutdownError(ConnectionError):
    """A peer worker shut down while this worker waited on it — a SECONDARY
    failure (the peer's own exception is the root cause)."""


class PeerTimeoutError(TimeoutError):
    """Timed out waiting on a peer worker — secondary, like
    :class:`PeerShutdownError` (typed so failure triage classifies by
    ``isinstance`` instead of matching message text)."""


class ClusterFenceError(PeerShutdownError):
    """A peer observed a rank death and broadcast the epoch fence: this rank
    must abort its in-flight barriers and quiesce for a surgical rejoin (or,
    with surgical mode off, fail fast exactly like any other peer loss — it IS
    a :class:`PeerShutdownError`)."""


def _freeze_delta(payload: Any) -> Any:
    """Mark a delta's arrays read-only before handing the LIVE object to peer
    threads: the zero-serialization lane shares one address space, and the
    engine-wide convention that deltas are never mutated in place is otherwise
    unenforced — a violation must fail fast in the mutating worker, not corrupt
    its peers nondeterministically."""
    if payload is None:
        return payload
    for arr in (payload.keys, payload.diffs, *payload.columns.values()):
        if isinstance(arr, np.ndarray):
            arr.setflags(write=False)
    return payload


class ThreadExchange(ClusterExchange):
    """``ClusterExchange``'s collectives and delta routing over an in-memory
    transport: worker THREADS in one process instead of spawned processes.
    All the lockstep/barrier semantics are inherited — only ``_send``/``_recv``
    change (a dict handoff under one condition variable; no sockets, no
    serializing between address spaces beyond the pickle the routing layer
    already does)."""

    #: thread peers cannot be relaunched into a live hub — no fence protocol
    supports_rejoin = False

    def __init__(self, hub: ThreadExchangeHub, me: int):
        # deliberately NOT calling super().__init__ — no sockets to wire
        self.n = hub.n
        self.me = me
        self._hub = hub
        self.epoch = 0
        self._conns = {p: None for p in range(hub.n) if p != me}  # peer ranks
        # same barrier-deadline knob as the TCP lane (no heartbeats here: a
        # thread peer cannot vanish silently, only wedge — which this catches)
        self.barrier_timeout_s = _env_float("PATHWAY_BARRIER_TIMEOUT_S", 300.0)
        # no rejoin protocol -> no serve log (inherited exchange_parts reads these)
        self._commit_log = OrderedDict()
        self._commit_log_open = None
        self.commit_log_depth = 0

    def _send(self, peer: int, tag: bytes, payload: Any) -> None:
        if payload is not None and hasattr(payload, "columns"):
            _freeze_delta(payload)  # object handoff: enforce the no-mutation contract
        with self._hub.cv:
            self._hub.boxes[(peer, self.me, tag)] = payload
            self._hub.cv.notify_all()

    def _recv(self, peer: int, tag: bytes, timeout: Optional[float] = None) -> bytes:
        if timeout is None:
            timeout = self.barrier_timeout_s
        deadline = time.monotonic() + timeout
        key = (self.me, peer, tag)
        with self._hub.cv:
            while key not in self._hub.boxes:
                if self._hub.closed:
                    raise PeerShutdownError(
                        f"worker thread {peer} shut down while waiting for {tag!r}"
                    )
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise PeerTimeoutError(
                        f"worker thread {self.me} timed out waiting for {tag!r} "
                        f"from worker {peer}"
                    )
                self._hub.cv.wait(timeout=min(remaining, 1.0))
            return self._hub.boxes.pop(key)

    def close(self) -> None:
        self._hub.close()

    def heartbeat_ages(self) -> Dict[int, float]:
        return {}  # one address space: a peer thread cannot vanish silently

    def dead_peers(self) -> Dict[int, str]:
        return {}

    @property
    def shared_inputs(self) -> bool:
        return self._hub.shared_inputs

    # -- zero-serialization delta collectives --------------------------------
    # Worker threads share one address space: deltas cross the exchange as
    # OBJECT handoffs (the partition slice the routing already makes), not
    # pickled bytes. This is the in-memory allocator's whole advantage — the
    # TCP lane pays serialization because it must, this lane must not.

    def exchange_delta(self, tag: bytes, delta: Any, route_keys: np.ndarray) -> Any:
        from pathway_tpu.engine.columnar import Delta
        from pathway_tpu.internals.keys import shard_of

        owners = shard_of(route_keys, self.n)
        for peer in self._conns:
            rows = np.nonzero(owners == peer)[0]
            self._send(peer, tag, delta.select(rows) if len(rows) else None)
        mine = delta.select(np.nonzero(owners == self.me)[0])
        merged = [mine]
        for peer in sorted(self._conns):
            part = self._recv(peer, tag)
            if part is not None and len(part):
                merged.append(part)
        if len(merged) == 1:
            return mine
        return Delta.concat(merged, list(delta.columns))

    def exchange_to_root(self, tag: bytes, delta: Any) -> Any:
        from pathway_tpu.engine.columnar import Delta

        columns = list(delta.columns)
        if self.me != 0:
            self._send(0, tag, delta if len(delta) else None)
            for peer in self._conns:
                if peer != 0:
                    self._send(peer, tag, None)
        else:
            for peer in self._conns:
                self._send(peer, tag, None)
        received = {peer: self._recv(peer, tag) for peer in self._conns}
        if self.me != 0:
            return Delta.empty(columns)
        merged = [delta]
        for peer in sorted(received):
            part = received[peer]
            if part is not None and len(part):
                merged.append(part)
        if len(merged) == 1:
            return delta
        return Delta.concat(merged, columns)

    def broadcast_merge(self, tag: bytes, delta: Any) -> Any:
        from pathway_tpu.engine.columnar import Delta

        columns = list(delta.columns)
        payload = delta if len(delta) else None
        for peer in self._conns:
            self._send(peer, tag, payload)
        by_rank: List[Any] = [None] * self.n
        by_rank[self.me] = delta if len(delta) else None
        for peer in self._conns:
            by_rank[peer] = self._recv(peer, tag)
        merged = [d for d in by_rank if d is not None and len(d)]
        if not merged:
            return Delta.empty(columns)
        if len(merged) == 1:
            return merged[0]
        return Delta.concat(merged, columns)


_thread_ctx = threading.local()


def in_thread_worker() -> bool:
    """True on a thread already bound to a worker exchange (prevents nested
    fan-out when a worker's own ``pw.run`` consults PATHWAY_THREADS)."""
    return getattr(_thread_ctx, "hub", None) is not None


def thread_worker_rank() -> int:
    """This thread's worker rank (0 when not a worker thread)."""
    return int(getattr(_thread_ctx, "me", 0) or 0)


def thread_worker_shared_inputs() -> bool:
    """True on a ``run_shared_graph`` worker (the ``pw.run`` PATHWAY_THREADS
    fan-out over ONE already-built graph, which the parent runner already
    linted); False on a ``run_threads`` worker, where each rank builds and
    runs its own graph with no parent run."""
    hub = getattr(_thread_ctx, "hub", None)
    return bool(getattr(hub, "shared_inputs", False))


def set_thread_exchange(hub: "ThreadExchangeHub | None", me: int = 0) -> None:
    """Bind this thread to a worker-thread exchange (``run_threads`` launcher);
    None unbinds."""
    _thread_ctx.hub = hub
    _thread_ctx.me = me
    _thread_ctx.exchange = None


_cluster: Optional[ClusterExchange] = None
_cluster_tried = False


def get_cluster() -> Optional[ClusterExchange]:
    """Process-wide exchange, created from the spawn env on first use; None when
    running single-process. Worker threads bound to a ThreadExchangeHub get
    their in-memory exchange instead."""
    global _cluster, _cluster_tried
    hub = getattr(_thread_ctx, "hub", None)
    if hub is not None:
        ex = getattr(_thread_ctx, "exchange", None)
        if ex is None:
            ex = ThreadExchange(hub, _thread_ctx.me)
            _thread_ctx.exchange = ex
        return ex
    if _cluster_tried:
        return _cluster
    from pathway_tpu.internals.config import get_pathway_config

    cfg = get_pathway_config()
    n = int(getattr(cfg, "processes", 1) or 1)
    if n <= 1:
        _cluster_tried = True
        return None
    # mark as tried only on SUCCESS: a failed wiring attempt must raise again on
    # retry, never silently degrade to single-process partial results
    cluster = ClusterExchange(
        n, int(getattr(cfg, "process_id", 0) or 0), int(getattr(cfg, "first_port", 10000) or 10000)
    )
    _cluster = cluster
    _cluster_tried = True
    return _cluster
