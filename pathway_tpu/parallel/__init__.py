"""Distributed execution over a TPU device mesh.

TPU-native replacement for the reference's distributed backend — timely-dataflow's
``communication`` crate (``external/timely-dataflow/communication/src/initialize.rs:25``,
worker threads + shared-memory/TCP exchange, ``src/engine/dataflow/config.rs:63-120``).
Here, workers ↔ mesh devices under SPMD; the hash-partitioned ``Exchange`` pact becomes
``jax.lax.all_to_all`` over ICI; broadcast/top-k merge becomes ``all_gather``; progress
tracking stays on the host control-plane (XLA replicas are bulk-synchronous).

Components:
- :mod:`mesh` — device-mesh construction (``data``/``model`` axes, multi-host aware).
- :mod:`sharding` — sharding rules (param trees, batches, keyed table state).
- :mod:`exchange` — key-hash exchange (shard routing, the ``shard.rs:15-20`` analog).
- :mod:`knn_sharded` — mesh-sharded KNN store with all-gather top-k merge.
"""

from pathway_tpu.parallel.mesh import make_mesh, mesh_shape_for
from pathway_tpu.parallel.sharding import (
    batch_sharding,
    encoder_param_sharding,
    replicated,
)
from pathway_tpu.parallel.exchange import shard_of_keys, exchange_by_key
from pathway_tpu.parallel.knn_sharded import ShardedIvfKnnStore, ShardedKNNStore

__all__ = [
    "make_mesh",
    "mesh_shape_for",
    "batch_sharding",
    "encoder_param_sharding",
    "replicated",
    "shard_of_keys",
    "exchange_by_key",
    "ShardedIvfKnnStore",
    "ShardedKNNStore",
]
