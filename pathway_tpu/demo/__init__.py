"""Synthetic demo streams (parity: reference ``demo/__init__.py`` — ``generate_custom_stream``
``:28``, ``noisy_linear_stream``, ``range_stream``)."""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Dict

from pathway_tpu.internals import schema as sch
from pathway_tpu.io.python import ConnectorSubject, read


def generate_custom_stream(
    value_generators: Dict[str, Callable[[int], Any]],
    *,
    schema: sch.SchemaMetaclass,
    nb_rows: int | None = None,
    input_rate: float = 1.0,
    autocommit_duration_ms: int = 100,
    name: str = "demo",
) -> Any:
    class _Subject(ConnectorSubject):
        def run(self) -> None:
            i = 0
            while nb_rows is None or i < nb_rows:
                row = {name_: gen(i) for name_, gen in value_generators.items()}
                self.next(**row)
                i += 1
                if input_rate and nb_rows is None or (nb_rows and nb_rows > 100):
                    time.sleep(1.0 / input_rate if input_rate else 0)

    return read(_Subject(), schema=schema, autocommit_duration_ms=autocommit_duration_ms, name=name)


def noisy_linear_stream(nb_rows: int = 10, input_rate: float = 1.0) -> Any:
    schema = sch.schema_from_types(x=float, y=float)
    rng = random.Random(0)
    return generate_custom_stream(
        {
            "x": lambda i: float(i),
            "y": lambda i: float(i) + (2 * rng.random() - 1) / 10,
        },
        schema=schema,
        nb_rows=nb_rows,
        input_rate=input_rate,
    )


def range_stream(
    nb_rows: int = 30, offset: int = 0, input_rate: float = 1.0, autocommit_duration_ms: int = 100
) -> Any:
    schema = sch.schema_from_types(value=int)
    return generate_custom_stream(
        {"value": lambda i: i + offset},
        schema=schema,
        nb_rows=nb_rows,
        input_rate=input_rate,
        autocommit_duration_ms=autocommit_duration_ms,
    )


def replay_csv(path: str, *, schema: Any, input_rate: float = 1.0) -> Any:
    import csv as _csv

    from pathway_tpu.internals import dtype as dt

    class _Subject(ConnectorSubject):
        def run(self) -> None:
            dtypes = schema.dtypes()
            with open(path, newline="") as f:
                for rec in _csv.DictReader(f):
                    row = {}
                    for k, v in rec.items():
                        if k not in dtypes:
                            continue
                        base = dtypes[k].strip_optional()
                        if base == dt.INT:
                            row[k] = int(v)
                        elif base == dt.FLOAT:
                            row[k] = float(v)
                        else:
                            row[k] = v
                    self.next(**row)
                    if input_rate:
                        time.sleep(1.0 / input_rate)

    return read(_Subject(), schema=schema)
