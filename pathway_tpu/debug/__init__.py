"""Debug & testing API.

Parity: reference ``python/pathway/debug/__init__.py`` — ``table_from_markdown`` (``:429``),
``table_from_pandas`` (``:343``), ``compute_and_print`` (``:207``),
``compute_and_print_update_stream`` (``:235``), ``table_to_pandas``, ``StreamGenerator``.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

import numpy as np

from pathway_tpu.engine.columnar import Delta
from pathway_tpu.engine.datasource import StaticDataSource, StreamingDataSource
from pathway_tpu.engine.runner import GraphRunner
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import Pointer, pointer_from, sequential_keys
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table

_SPECIAL_COLUMNS = {"__time__", "__diff__"}


def _parse_value(token: str) -> Any:
    token = token.strip()
    if token in ("", "None"):
        return None
    if token == "True":
        return True
    if token == "False":
        return False
    try:
        return int(token)
    except ValueError:
        pass
    try:
        return float(token)
    except ValueError:
        pass
    return token


def table_from_markdown(
    table_def: str,
    *,
    id_from: list[str] | None = None,
    schema: Any = None,
    unsafe_trusted_ids: bool = False,
    split_on_whitespace_only: bool = False,
) -> Table:
    """Build a static table from a markdown-ish definition (reference ``debug:429``).

    Supports an optional unnamed leading id column and ``__time__``/``__diff__`` columns for
    simulating update streams.
    """
    lines = [l for l in table_def.strip().splitlines() if l.strip() and not set(l.strip()) <= {"-", "|", " "}]
    if not lines:
        raise ValueError("empty table definition")
    if split_on_whitespace_only:
        header = re.split(r"\s+", lines[0].strip())
        rows_raw = [re.split(r"\s+", l.strip()) for l in lines[1:]]
    else:
        header = [h.strip() for h in lines[0].split("|")]
        rows_raw = [[c for c in l.split("|")] for l in lines[1:]]

    has_id_col = header[0] == ""
    if has_id_col:
        header = header[1:]
    names = [h for h in header]

    rows: List[dict] = []
    keys: List[Pointer] = []
    times: List[int] = []
    diffs: List[int] = []
    for cells in rows_raw:
        cells = [c.strip() for c in cells]
        if has_id_col:
            row_id, cells = cells[0], cells[1:]
            keys.append(pointer_from(row_id, "mkdtable"))
        if len(cells) != len(names):
            raise ValueError(f"row {cells!r} does not match header {names!r}")
        row = {}
        t, d = 0, 1
        for name, cell in zip(names, cells):
            value = _parse_value(cell)
            if name == "__time__":
                t = int(value)
            elif name == "__diff__":
                d = int(value)
            else:
                row[name] = value
        rows.append(row)
        times.append(t)
        diffs.append(d)

    data_names = [n for n in names if n not in _SPECIAL_COLUMNS]
    if schema is not None:
        schema_cls = schema
        for row in rows:
            for name, col in schema_cls.columns().items():
                if name in row and row[name] is not None:
                    row[name] = _coerce_to(row[name], col.dtype)
        pk = schema_cls.primary_key_columns()
        if pk:
            keys = [pointer_from(*(row[c] for c in pk)) for row in rows]
    else:
        schema_cls = _infer_schema(rows, data_names)
        if id_from:
            keys = [pointer_from(*(row[c] for c in id_from)) for row in rows]

    streaming = any(n in _SPECIAL_COLUMNS for n in names)
    if streaming:
        source: Any = _TimedSource(rows, keys if keys else None, times, diffs)
    else:
        key_arr = None
        if keys:
            from pathway_tpu.internals.keys import pointers_to_keys

            key_arr = pointers_to_keys(keys)
        source = StaticDataSource(rows, keys=key_arr)
    node = G.add_node(pg.InputNode(source=source, streaming=False))
    return Table(node, schema_cls, name="markdown")


# convenient aliases matching the reference API
table_from_markdown.__doc__ = (table_from_markdown.__doc__ or "") + "\n(reference debug/__init__.py:429)"


def _coerce_to(value: Any, dtype: dt.DType) -> Any:
    base = dtype.strip_optional()
    try:
        if base == dt.INT:
            return int(value)
        if base == dt.FLOAT:
            return float(value)
        if base == dt.STR:
            return str(value)
        if base == dt.BOOL:
            if isinstance(value, bool):
                return value
            return value == "True"
    except (TypeError, ValueError):
        pass
    return value


def _infer_schema(rows: List[dict], names: List[str]) -> sch.SchemaMetaclass:
    columns: Dict[str, sch.ColumnSchema] = {}
    for name in names:
        values = [row.get(name) for row in rows]
        non_null = [v for v in values if v is not None]
        if not non_null:
            dtype: dt.DType = dt.NONE
        elif all(isinstance(v, bool) for v in non_null):
            dtype = dt.BOOL
        elif all(isinstance(v, int) and not isinstance(v, bool) for v in non_null):
            dtype = dt.INT
        elif all(isinstance(v, (int, float)) and not isinstance(v, bool) for v in non_null):
            dtype = dt.FLOAT
        elif all(isinstance(v, str) for v in non_null):
            dtype = dt.STR
        else:
            dtype = dt.ANY
        if any(v is None for v in values) and dtype not in (dt.NONE, dt.ANY):
            dtype = dt.Optional_(dtype)
        columns[name] = sch.ColumnSchema(name, dtype)
    return sch.schema_from_columns(columns, "markdown")


class _TimedSource(StaticDataSource):
    """Rows released per __time__ value, with __diff__ signs — update-stream simulation."""

    def __init__(
        self,
        rows: List[dict],
        keys: List[Pointer] | None,
        times: List[int],
        diffs: List[int],
        columns: Dict[str, np.ndarray] | None = None,
    ):
        super().__init__(rows)
        self._times = times
        self._diffs = np.asarray(diffs, dtype=np.int64)
        self._prebuilt_columns = columns  # built at graph construction, off the run clock
        self._pointers = keys
        self._schedule = sorted(set(times))
        self._pos = 0
        self._col_arrays: Dict[str, np.ndarray] | None = None
        # All timed sources of one graph share a global clock: each commit releases the
        # rows of the earliest pending __time__ across the whole graph, so interleaved
        # streams (e.g. events vs a wall-clock table) arrive in deterministic order.
        from pathway_tpu.internals.parse_graph import G

        self._clock = G.timed_source_clock
        self._clock.register(self)

    def on_start(self) -> None:
        self._pos = 0
        self._done = False
        self._clock._polled = set()
        self._clock._round_min = None

    def _next_time(self) -> Any:
        if self._done or self._pos >= len(self._schedule):
            return None
        return self._schedule[self._pos]

    def _materialize(self, column_names: List[str]) -> None:
        """One-time columnar layout: whole-dataset column arrays, per-time row index
        slices, and (when keys are value-derived) one vectorized base-key hash."""
        from pathway_tpu.engine.expression_evaluator import _tidy
        from pathway_tpu.internals.keys import KEY_DTYPE, pointers_to_keys

        n = len(self._rows)
        prebuilt = getattr(self, "_prebuilt_columns", None)
        self._col_arrays = {}
        for name in column_names:
            if prebuilt is not None and name in prebuilt:
                self._col_arrays[name] = prebuilt[name]
                continue
            col = np.empty(n, dtype=object)
            for i, row in enumerate(self._rows):
                col[i] = row.get(name)
            self._col_arrays[name] = _tidy(col)
        times = np.asarray(self._times)
        self._time_rows = {}
        if n:
            order = np.argsort(times, kind="stable")
            sorted_t = times[order]
            bounds = np.nonzero(np.diff(sorted_t))[0] + 1
            for chunk in np.split(order, bounds):
                # chunk holds ORIGINAL row indices: look the time up in `times`,
                # not `sorted_t` (equal only when rows arrive pre-sorted by time)
                self._time_rows[times[chunk[0]].item()] = chunk
        if self._pointers:
            self._all_keys = pointers_to_keys(self._pointers)
        else:
            # value-derived row identity: one native hash over all value columns
            # (sorted names, as the old per-row token did), then GLOBAL occurrence
            # numbers so duplicate rows get distinct deterministic keys. Occurrence
            # counters follow release order (time, then input order) and pair a
            # __diff__=-1 row LIFO with its matching insert.
            from pathway_tpu.internals.keys import key_bytes, keys_from_values

            value_cols = [
                self._col_arrays[name] for name in sorted(self._col_arrays)
            ]
            base = (
                keys_from_values(value_cols)
                if value_cols
                else np.zeros(n, dtype=KEY_DTYPE)
            )
            release = np.concatenate(
                [self._time_rows[t] for t in sorted(self._time_rows)]
            ) if n else np.zeros(0, dtype=np.int64)
            diffs = np.asarray(self._diffs, dtype=np.int64)
            occ = np.zeros(n, dtype=np.int64)
            if (diffs >= 0).all():
                # pure-insert stream: occurrence = rank within duplicate group, in
                # release order — one vectorized pass over index slots
                from pathway_tpu.engine.index import KeyIndex

                slots, _ = KeyIndex(n).upsert(base[release])
                grouped = np.argsort(slots, kind="stable")
                sorted_slots = slots[grouped]
                starts = np.nonzero(
                    np.diff(sorted_slots, prepend=sorted_slots[:1] - 1)
                )[0]
                rank = np.arange(len(slots), dtype=np.int64)
                first_of_group = np.zeros(len(slots), dtype=np.int64)
                first_of_group[starts] = starts
                first_of_group = np.maximum.accumulate(first_of_group)
                occ_in_release = np.empty(len(slots), dtype=np.int64)
                occ_in_release[grouped] = rank - first_of_group
                occ[release] = occ_in_release
            else:
                occurrences: dict = {}
                kbs = key_bytes(base)
                for i in release.tolist():
                    bb = kbs[i]
                    if diffs[i] > 0:
                        o = occurrences.get(bb, 0)
                        occurrences[bb] = o + 1
                    else:
                        o = occurrences.get(bb, 1) - 1
                        occurrences[bb] = o
                    occ[i] = o
            salt = np.empty(n, dtype=object)
            salt[:] = "timedrow"
            self._all_keys = (
                keys_from_values([base, occ, salt]) if n else np.zeros(0, dtype=KEY_DTYPE)
            )

    def next_batch(self, column_names: List[str]) -> Delta:
        if getattr(self, "_col_arrays", None) is None:
            self._materialize(column_names)
        if self._pos >= len(self._schedule):
            self._done = True
            return Delta.empty(column_names)
        if not self._clock.may_release(self):
            # another source owns the globally-earliest timestamp; wait our turn
            return Delta.empty(column_names)
        t = self._schedule[self._pos]
        self._pos += 1
        if self._pos >= len(self._schedule):
            self._done = True
        idx = self._time_rows[t]
        if len(idx) > 1 and idx[0] + len(idx) - 1 == idx[-1] and (np.diff(idx) == 1).all():
            # time-contiguous rows (the common layout: streams are built in
            # commit order): basic slicing returns zero-copy VIEWS instead of
            # one fancy-gather copy per column — deltas are immutable once
            # emitted, so sharing the backing arrays is safe
            sl = slice(int(idx[0]), int(idx[-1]) + 1)
            columns = {name: self._col_arrays[name][sl] for name in column_names}
            return Delta(self._all_keys[sl], self._diffs[sl], columns)
        columns = {name: self._col_arrays[name][idx] for name in column_names}
        return Delta(self._all_keys[idx], self._diffs[idx], columns)

    def is_finished(self) -> bool:
        return self._done


def table_from_rows(
    schema: sch.SchemaMetaclass,
    rows: list[tuple],
    unsafe_trusted_ids: bool = False,
    is_stream: bool = False,
) -> Table:
    names = schema.column_names()
    dict_rows = []
    for row in rows:
        if is_stream:
            *values, t, d = row
            r = dict(zip(names, values))
            r["__time__"], r["__diff__"] = t, d
        else:
            r = dict(zip(names, row))
        dict_rows.append(r)
    pk = schema.primary_key_columns()
    keys = [pointer_from(*(r[c] for c in pk)) for r in dict_rows] if pk else None
    if is_stream:
        from pathway_tpu.engine.columnar import objarray
        from pathway_tpu.engine.expression_evaluator import _tidy

        # columnarize once at graph-build time (one zip pass per column), so the
        # run-time source only slices
        value_cols = list(zip(*(r[:-2] for r in rows))) if rows else [()] * len(names)
        columns = {
            name: _tidy(objarray(list(vals))) for name, vals in zip(names, value_cols)
        }
        source: Any = _TimedSource(
            [{k: v for k, v in r.items() if k not in _SPECIAL_COLUMNS} for r in dict_rows],
            keys,
            [r["__time__"] for r in dict_rows],
            [r["__diff__"] for r in dict_rows],
            columns=columns,
        )
        # columnar layout + key derivation happen at graph build, off the run clock
        source._materialize(names)
    else:
        key_arr = None
        if keys:
            from pathway_tpu.internals.keys import pointers_to_keys

            key_arr = pointers_to_keys(keys)
        from pathway_tpu.engine.columnar import objarray
        from pathway_tpu.engine.expression_evaluator import _tidy

        value_cols = list(zip(*rows)) if rows else [()] * len(names)
        columns = {
            name: _tidy(objarray(list(vals))) for name, vals in zip(names, value_cols)
        }
        source = StaticDataSource(dict_rows, keys=key_arr, columns=columns)
    node = G.add_node(pg.InputNode(source=source))
    return Table(node, schema, name="rows")


def table_from_pandas(
    df: Any, *, id_from: list[str] | None = None, unsafe_trusted_ids: bool = False, schema: Any = None
) -> Table:
    rows = []
    for _, prow in df.iterrows():
        row = {}
        for col in df.columns:
            v = prow[col]
            if isinstance(v, np.integer):
                v = int(v)
            elif isinstance(v, np.floating):
                v = float(v)
            elif isinstance(v, np.bool_):
                v = bool(v)
            row[str(col)] = v
        rows.append(row)
    schema_cls = schema if schema is not None else sch.schema_from_pandas(df, id_from=id_from)
    keys = None
    if id_from:
        from pathway_tpu.internals.keys import pointers_to_keys

        keys = pointers_to_keys([pointer_from(*(r[c] for c in id_from)) for r in rows])
    elif df.index is not None and not df.index.equals(type(df.index)(range(len(df)))):
        from pathway_tpu.internals.keys import pointers_to_keys

        keys = pointers_to_keys([pointer_from(i, "pandas") for i in df.index])
    source = StaticDataSource(rows, keys=keys)
    node = G.add_node(pg.InputNode(source=source))
    return Table(node, schema_cls, name="pandas")


def _capture_table(table: Table, *, terminate_on_error: bool = True) -> Dict[bytes, dict]:
    """Run the graph and return the table's final rows keyed by key bytes."""
    from pathway_tpu.internals.keys import pointers_to_keys

    captured: Dict[bytes, dict] = {}

    def on_change(key: Pointer, row: dict, time: int, is_addition: bool) -> None:
        kb = pointers_to_keys([key]).tobytes()
        if is_addition:
            captured[kb] = {"__key__": key, **row}
        else:
            captured.pop(kb, None)

    G.add_node(pg.OutputNode(inputs=[table], callback=on_change))
    runner = GraphRunner(G)
    # local inspection helper, not a production run: no lint gate (a debug
    # print must never be refused by PATHWAY_LINT=error) and no analyze-mode
    # capture interrupt (the analyzed program keeps executing past this call)
    runner.lint_exempt = True
    runner.run(terminate_on_error=terminate_on_error)
    return captured


def _capture_update_stream(table: Table, *, terminate_on_error: bool = True) -> List[dict]:
    updates: List[dict] = []

    def on_change(key: Pointer, row: dict, time: int, is_addition: bool) -> None:
        updates.append({"__key__": key, "__time__": time, "__diff__": 1 if is_addition else -1, **row})

    G.add_node(pg.OutputNode(inputs=[table], callback=on_change))
    runner = GraphRunner(G)
    runner.lint_exempt = True  # see _capture_table
    runner.run(terminate_on_error=terminate_on_error)
    return updates


def table_to_pandas(table: Table, *, include_id: bool = True) -> Any:
    import pandas as pd

    captured = _capture_table(table)
    names = table.column_names()
    data = {name: [row[name] for row in captured.values()] for name in names}
    index = [row["__key__"] for row in captured.values()]
    df = pd.DataFrame(data, index=index, columns=names)
    return df


def compute_and_print(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: int | None = None,
    squash_updates: bool = True,
    terminate_on_error: bool = True,
) -> None:
    captured = _capture_table(table, terminate_on_error=terminate_on_error)
    names = table.column_names()
    rows = sorted(captured.values(), key=lambda r: r["__key__"])
    if n_rows is not None:
        rows = rows[:n_rows]
    header = ([""] if include_id else []) + names
    print(" | ".join(header).strip())
    for row in rows:
        cells = []
        if include_id:
            key = row["__key__"]
            cells.append(f"^{key.as_int():X}"[:12] + "..." if short_pointers else repr(key))
        cells.extend(str(row[n]) for n in names)
        print(" | ".join(cells))


def compute_and_print_update_stream(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: int | None = None,
    terminate_on_error: bool = True,
) -> None:
    updates = _capture_update_stream(table, terminate_on_error=terminate_on_error)
    names = table.column_names() + ["__time__", "__diff__"]
    if n_rows is not None:
        updates = updates[:n_rows]
    header = ([""] if include_id else []) + names
    print(" | ".join(header).strip())
    for row in updates:
        cells = []
        if include_id:
            key = row["__key__"]
            cells.append(f"^{key.as_int():X}"[:12] + "..." if short_pointers else repr(key))
        cells.extend(str(row[n]) for n in names)
        print(" | ".join(cells))


class StreamGenerator:
    """Scripted multi-worker stream fixture (reference ``debug/__init__.py:496``)."""

    def __init__(self) -> None:
        self._events: List[tuple] = []

    def table_from_list_of_batches(self, batches: List[List[dict]], schema: sch.SchemaMetaclass) -> Table:
        rows = []
        for t, batch in enumerate(batches):
            for row in batch:
                r = dict(row)
                r["__time__"] = t
                r["__diff__"] = 1
                rows.append(r)
        names = schema.column_names()
        source = _TimedSource(
            [{k: v for k, v in r.items() if k not in _SPECIAL_COLUMNS} for r in rows],
            None,
            [r["__time__"] for r in rows],
            [r["__diff__"] for r in rows],
        )
        node = G.add_node(pg.InputNode(source=source))
        return Table(node, schema, name="stream_generator")

    def table_from_list_of_batches_by_workers(
        self, batches: Dict[int, List[List[dict]]], schema: sch.SchemaMetaclass
    ) -> Table:
        merged: List[List[dict]] = []
        for worker_batches in batches.values():
            for t, batch in enumerate(worker_batches):
                while len(merged) <= t:
                    merged.append([])
                merged[t].extend(batch)
        return self.table_from_list_of_batches(merged, schema)
