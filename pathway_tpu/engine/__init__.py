"""TPU-native incremental engine: columnar state, delta propagation, JAX kernels."""
