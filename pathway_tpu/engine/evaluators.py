"""Incremental operator evaluators — the differential-dataflow replacement.

Each parse-graph node kind gets an evaluator that consumes input ``Delta`` batches and emits an
output ``Delta`` per commit, maintaining whatever keyed state incrementality requires. This
mirrors the reference's DD operator implementations in ``src/engine/dataflow.rs`` (joins,
groupby, ix, concat, flatten, sort via prev/next) at batch granularity. Dense numeric work
inside a batch (expression trees, reducer sums, KNN search) is delegated to vectorized
numpy/JAX kernels.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from pathway_tpu.engine import expression_evaluator as ee
from pathway_tpu.engine.columnar import ERROR, Delta, Error, StateTable, empty_keys, objarray
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.keys import (
    KEY_DTYPE,
    Pointer,
    broadcast_key,
    key_bytes,
    combine_keys,
    hash_upsert,
    keys_from_values,
    keys_to_pointers,
    pointer_from,
    pointers_to_keys,
)
from pathway_tpu.internals.reducers import _IdMarker, _SeqMarker


class UnpicklableStateError(Exception):
    """Operator state can't be checkpointed; the journal must keep full history."""


def _collect_nondet_exprs(value: Any, found: List[Any], seen: set) -> None:
    """Deterministic walk over a node config collecting non-deterministic apply
    expressions (dicts by sorted key, sequences in order, expression trees by
    ``_deps`` order) — the walk order IS the expressions' stable identity across
    process restarts, so memoized replay state can live in operator snapshots."""
    if isinstance(value, expr.ColumnExpression):
        if id(value) in seen:
            return
        seen.add(id(value))
        if isinstance(value, expr.ApplyExpression) and not value._deterministic:
            found.append(value)
        for dep in value._deps():
            _collect_nondet_exprs(dep, found, seen)
    elif isinstance(value, dict):
        for k in sorted(value, key=repr):
            _collect_nondet_exprs(value[k], found, seen)
    elif isinstance(value, (list, tuple)):
        for v in value:
            _collect_nondet_exprs(v, found, seen)


def _to_host(v: Any) -> Any:
    if type(v).__module__.startswith("jax"):
        return np.asarray(v)
    return v


def filter_mask_to_bool(mask: np.ndarray) -> np.ndarray:
    """Filter predicate column → boolean row mask: poisoned (Error) cells drop
    the row. ONE home for the rule — FilterEvaluator and the fusion compiler's
    composed filters (``engine/fusion.py``) must stay in bitwise lockstep."""
    if mask.dtype == object:
        mask = np.frompyfunc(
            lambda v: bool(v) if not isinstance(v, Error) else False, 1, 1
        )(mask).astype(bool)
    return mask.astype(bool)


def id_pointer_column(keys: np.ndarray) -> np.ndarray:
    """The materialized ``id`` pseudo-column: row-key Pointers boxed in an
    object array — shared by ``Evaluator._resolver_for`` and the fusion
    chain resolver so both paths box identically."""
    out = np.empty(len(keys), dtype=object)
    out[:] = keys_to_pointers(keys)
    return out


class Evaluator:
    def __init__(self, node: pg.Node, runner: Any):
        self.node = node
        self.runner = runner
        self.output_columns: List[str] = (
            node.output.column_names() if node.output is not None else []
        )
        found: List[Any] = []
        _collect_nondet_exprs(node.config, found, set())
        # id(expr) -> stable token; the token keys _udf_memo so replay state
        # survives a checkpoint/restore round-trip (id() does not)
        self._memo_tokens: Dict[int, str] = {
            id(e): f"nd{i}" for i, e in enumerate(found)
        }

    def process(self, input_deltas: List[Delta]) -> Delta:
        raise NotImplementedError

    # -- multi-process placement (parallel/cluster.py) ------------------------
    #
    # Per-input routing policy applied by the runner before ``process`` when a
    # spawn cluster is active (reference: timely Exchange pacts per operator,
    # ``shard.rs`` routing; centralization ``time_column.rs:48-51``):
    #   None        — rows stay where they were produced (stateless / row-local)
    #   "rowkey"    — hash-exchange by row key: same-key rows of every such
    #                 input meet on the key's owner process
    #   "custom"    — hash-exchange by ``cluster_route_keys(idx, delta)``
    #   "root"      — centralize the input on process 0 (global-order state)
    #   "broadcast" — replicate the input on every process (replicated state)
    # An evaluator with ANY non-None policy participates in the all-to-all
    # barrier every commit, even with no local rows.

    CLUSTER_POLICIES: Dict[int, str] = {}
    _cluster_policies: tuple = ()  # resolved per-instance by GraphRunner.setup
    _cluster_barrier: bool = False
    # incremental rewind (GraphRunner._capture_undo_state): True means a
    # pre-commit state_dict()/load_state_dict() round-trip exactly restores
    # this operator, so a fenced survivor may undo an interrupted commit in
    # place. Set False on operators whose per-commit state snapshot is
    # unreasonable (huge or externally mutated in place) — the graph then
    # skips the rewind rung and fences use checkpoint + tail replay.
    # The PWA002 graph-lint pass (pathway_tpu/analysis) reports every
    # REWIND_SAFE=False operator at build time; any evaluator that flushes on
    # ``runner.draining`` (a live-only signal replay cannot reproduce) MUST set
    # this False — tests/test_analysis.py audits that invariant by source scan.
    REWIND_SAFE = True
    # False when this operator's state sits outside the snapshot protocol
    # (device-resident / externally mutated): state_dict() would abort the
    # checkpoint or restore an empty shell. The PWA005 lint pass reports such
    # operators in persistence-enabled graphs at build time.
    SNAPSHOT_CAPTURE = True

    def cluster_input_policy(self, idx: int) -> str | None:
        return self.CLUSTER_POLICIES.get(idx)

    def cluster_route_keys(self, idx: int, delta: Delta) -> np.ndarray:
        raise NotImplementedError  # required for "custom" policies only

    # -- operator snapshots (reference ``operator_snapshot.rs``) -------------

    _NON_STATE_ATTRS = (
        "node", "runner", "output_columns", "_memo_tokens",
        "_cluster_policies", "_cluster_barrier",
    )

    def state_dict(self) -> Dict[str, bytes]:
        """Picklable per-attribute snapshot of this operator's incremental state.
        Graph-config attributes (expressions, callbacks) are excluded by name via
        ``_NON_STATE_ATTRS`` — they are rebuilt identically from the (sig-checked) graph
        on restore. A *state* attribute that fails to pickle aborts the checkpoint
        (``UnpicklableStateError``): silently dropping it would compact away journal
        history the restore then cannot reconstruct."""
        import pickle

        out: Dict[str, bytes] = {}
        for name, value in self.__dict__.items():
            if name in self._NON_STATE_ATTRS:
                continue
            if name == "_udf_memo":
                # replay values may be device arrays (the serving path keeps
                # query embeddings on the TPU) — snapshot their host mirror so
                # post-restore retractions still replay the exact value
                value = {
                    tok: {kb: _to_host(v) for kb, v in store.items()}
                    for tok, store in value.items()
                }
            try:
                out[name] = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
            except Exception as exc:
                raise UnpicklableStateError(
                    f"{type(self).__name__}.{name} is not picklable ({exc}); "
                    "operator checkpointing is unavailable for this pipeline"
                ) from exc
        return out

    def load_state_dict(self, state: Dict[str, bytes]) -> None:
        import pickle

        for name, blob in state.items():
            self.__dict__[name] = pickle.loads(blob)

    # -- elastic membership handoff (parallel/membership.py) -----------------
    #
    # A membership change re-partitions key-owned state across the new
    # topology. The base protocol covers the common cases generically:
    # stateless evaluators export nothing, and the non-deterministic-apply
    # replay memo (``_udf_memo``: token -> {row-key bytes -> value}) is keyed
    # by row key, so it partitions exactly. Evaluators holding other keyed
    # state implement their own export/import (GroupbyEvaluator, the
    # key-presence family); evaluators whose state is NOT key-partitionable
    # return a reason from ``reshard_check`` and the whole transition is
    # refused loudly before anything mutates.

    #: state-shaped instance attrs that are really graph config, rebuilt
    #: identically from the (sig-checked) graph on every rank
    RESHARD_CONFIG_ATTRS: tuple = ()

    @staticmethod
    def _reshard_empty(value: Any) -> bool:
        if value is None or value is False:
            return True
        if isinstance(value, np.ndarray):
            return value.size == 0
        if isinstance(value, (dict, list, tuple, set, frozenset, str, bytes)):
            return len(value) == 0
        if isinstance(value, (int, float)) and value == 0:
            return True
        return False

    def _reshard_state_attrs(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, value in self.__dict__.items():
            if name in self._NON_STATE_ATTRS or name in self.RESHARD_CONFIG_ATTRS:
                continue
            if self._reshard_empty(value):
                continue
            out[name] = value
        return out

    def reshard_check(self) -> "str | None":
        """None when this evaluator's live state can ride the membership
        handoff; else a human-readable refusal reason."""
        extra = [n for n in self._reshard_state_attrs() if n != "_udf_memo"]
        if extra:
            return (
                f"{type(self).__name__} holds state ({', '.join(sorted(extra))}) "
                "this build cannot re-partition by key"
            )
        return None

    def reshard_export(self, owner_of: Any, new_n: int) -> Dict[int, Any]:
        """Partition this evaluator's keyed state by new owner rank. The
        export is COMPLETE (includes the keys this rank keeps): fragments
        double as the new topology's checkpoint, so the importer always
        starts from a fresh instance."""
        reason = self.reshard_check()
        if reason is not None:
            # defense in depth: the preflight vote refuses such graphs before
            # anything mutates — reaching here means the plan and the export
            # disagree, which must fail loudly, never silently drop state
            from pathway_tpu.parallel.membership import MembershipUnsupportedError

            raise MembershipUnsupportedError(reason)
        memo = self.__dict__.get("_udf_memo") or {}
        if not memo:
            return {}
        from pathway_tpu.internals.keys import KEY_DTYPE

        out: Dict[int, Any] = {}
        for tok, store in memo.items():
            for kb, val in store.items():
                keys = np.frombuffer(kb, dtype=KEY_DTYPE)
                dest = int(np.asarray(owner_of(keys))[0])
                out.setdefault(dest, {}).setdefault("_udf_memo", {}).setdefault(
                    tok, {}
                )[kb] = _to_host(val)
        return out

    def reshard_import(self, payload: Any) -> None:
        memo = self.__dict__.setdefault("_udf_memo", {})
        for tok, store in (payload or {}).get("_udf_memo", {}).items():
            memo.setdefault(tok, {}).update(store)

    # -- helpers ------------------------------------------------------------

    def _resolver_for(self, table: Any, delta: Delta) -> Callable[[expr.ColumnReference], np.ndarray]:
        """Resolve column refs against a delta of ``table``; cross-table refs hit state.

        Retraction rows resolve cross-table refs against the *retracted* values: when the
        referenced table replaced a key this commit (a -1/+1 pair on the same key), the
        materialized state already holds the new value, but a retraction must carry what
        was originally emitted (DD value-matched semantics — ``dataflow.rs`` joins match
        on values, not on current state)."""

        def resolver(ref: expr.ColumnReference) -> np.ndarray:
            if ref.table is table:
                if ref.name == "id":
                    return id_pointer_column(delta.keys)
                return delta.columns[ref.name]
            # cross-table reference: same-universe lookup by key in materialized state
            state = self.runner.state_of(ref.table._node)
            if ref.name == "id":
                return id_pointer_column(delta.keys)
            slots = state.lookup(delta.keys)
            hit = slots >= 0
            if hit.all() and len(state):
                out = state.gather(ref.name, slots)  # fancy indexing already copied
            else:
                # a same-universe reference must hit: a miss means the tables' key sets
                # genuinely differ (e.g. select over a reindexed table referencing the
                # pre-reindex table) — poison instead of silently yielding None
                out = np.empty(len(delta), dtype=object)
                out[:] = ERROR
                if hit.any():
                    out[hit] = state.gather(ref.name, slots[hit])
            if np.any(delta.diffs < 0):
                # retraction rows resolve against the *retracted* upstream values when
                # the referenced table replaced the key this commit (see docstring)
                ref_delta = self.runner.current_delta_of(ref.table._node)
                if ref_delta is not None and len(ref_delta):
                    neg = np.nonzero(ref_delta.diffs < 0)[0]
                    ref_col = ref_delta.columns.get(ref.name)
                    if len(neg) and ref_col is not None:
                        from pathway_tpu.engine.index import KeyIndex

                        ret_idx = KeyIndex(len(neg))
                        ret_slots, _ = ret_idx.upsert(ref_delta.keys[neg])
                        slot_values = np.empty(ret_idx.slot_bound(), dtype=ref_col.dtype)
                        slot_values[ret_slots] = ref_col[neg]
                        mine = np.nonzero(delta.diffs < 0)[0]
                        found = ret_idx.lookup(delta.keys[mine])
                        use = found >= 0
                        if use.any():
                            if out.dtype != object and out.dtype != slot_values.dtype:
                                out = out.astype(object)
                            out[mine[use]] = slot_values[found[use]]
            return ee._tidy(out) if out.dtype == object else out

        return resolver

    def _eval_expr(
        self, e: expr.ColumnExpression, delta: Delta, resolver: Callable
    ) -> np.ndarray:
        """Evaluate with non-deterministic-apply replay wired in: retraction rows
        reuse the value computed at insert time (see EvalContext docstring)."""
        return ee.evaluate(
            e,
            len(delta),
            resolver,
            keys=delta.keys,
            diffs=delta.diffs,
            memo=self.__dict__.setdefault("_udf_memo", {}),
            memo_tokens=self._memo_tokens,
        )

    def _eval_exprs(
        self, exprs: Dict[str, expr.ColumnExpression], table: Any, delta: Delta
    ) -> Dict[str, np.ndarray]:
        resolver = self._resolver_for(table, delta)
        return {name: self._eval_expr(e, delta, resolver) for name, e in exprs.items()}


class InputEvaluator(Evaluator):
    """Source node: pulls batches from its DataSource each commit."""

    def process(self, input_deltas: List[Delta]) -> Delta:
        source = self.node.config["source"]
        delta = source.next_batch(self.output_columns)
        if len(delta) == 0:
            return delta
        # a keyed upsert stream (e.g. Debezium CDC) can retract and re-add the same key
        # within one commit; net the multiplicities so state application is order-free
        # (reference UpsertSession semantics, adaptors.rs:67)
        return delta.consolidated()


class RowwiseEvaluator(Evaluator):
    """select/with_columns. Cross-table column references are LIVE dependencies
    (reference: a read of another same-universe table is a dataflow edge — DD
    re-derives downstream rows when the referenced arrangement changes): when a
    referenced table emits a delta this commit, the affected rows of THIS table
    re-evaluate and re-emit even though the primary input saw no delta."""

    # cross-ref node list is derived from the graph config, not run state
    RESHARD_CONFIG_ATTRS = ("_cross_nodes",)

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        own = node.inputs[0]
        cross: Dict[int, Any] = {}
        for e in node.config["exprs"].values():
            for ref in e._column_refs:
                if ref.table is not own:
                    cross[ref.table._node.id] = ref.table._node
        self._cross_nodes = list(cross.values())

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        table = self.node.inputs[0]
        parts: List[Delta] = []
        if len(delta):
            columns = self._eval_exprs(self.node.config["exprs"], table, delta)
            parts.append(Delta(delta.keys, delta.diffs, columns))
        if self._cross_nodes:
            refreshed = self._cross_refresh(delta)
            if refreshed is not None:
                parts.append(refreshed)
        if not parts:
            return Delta.empty(self.output_columns)
        if len(parts) == 1:
            return parts[0]
        return Delta.concat(parts, self.output_columns)

    def _cross_refresh(self, own_delta: Delta) -> Delta | None:
        """Retract+reinsert rows whose cross-referenced values changed this
        commit (keys from the referenced tables' deltas, restricted to this
        table's universe, minus rows the primary delta already covers)."""
        runner = self.runner
        key_parts = []
        for ref_node in self._cross_nodes:
            d = runner.current_delta_of(ref_node)
            if d is not None and len(d):
                key_parts.append(d.keys)
        if not key_parts:
            return None
        seen: set = set()
        own_keys = set(key_bytes(own_delta.keys)) if len(own_delta) else set()
        kept: List[np.void] = []
        for arr in key_parts:
            for i, kb in enumerate(key_bytes(arr)):
                if kb in seen or kb in own_keys:
                    continue
                seen.add(kb)
                kept.append(arr[i])
        if not kept:
            return None
        keys = np.array(kept, dtype=KEY_DTYPE)
        in_state = runner.state_of(self.node.inputs[0]._node)
        slots = in_state.lookup(keys)
        present = slots >= 0
        if not present.any():
            return None
        keys = keys[present]
        slots = slots[present]
        in_cols = self.node.inputs[0].column_names()
        synth = Delta(
            keys,
            np.ones(len(keys), dtype=np.int64),
            {c: in_state.gather(c, slots) for c in in_cols},
        )
        new_cols = self._eval_exprs(self.node.config["exprs"], self.node.inputs[0], synth)
        out_state = runner.state_of(self.node)
        oslots = out_state.lookup(keys)
        had = oslots >= 0
        # suppress no-op rows: only emit where some output value actually moved
        changed = ~had  # rows never emitted always emit
        if had.any():
            idx = np.nonzero(had)[0]
            neq = np.zeros(len(idx), dtype=bool)
            for name in self.output_columns:
                old = out_state.gather(name, oslots[idx])
                neq |= _col_neq(old, new_cols[name][idx])
            changed[idx] |= neq
        if not changed.any():
            return None
        ch = np.nonzero(changed)[0]
        # batch-gather old values once per column, then assemble rows
        ret_idx = ch[had[ch]]
        old_cols = {
            c: out_state.gather(c, oslots[ret_idx]) for c in self.output_columns
        }
        old_pos = {int(i): p for p, i in enumerate(ret_idx.tolist())}
        out_keys: List[np.void] = []
        out_diffs: List[int] = []
        rows: List[dict] = []
        for i in ch.tolist():
            if had[i]:
                p = old_pos[i]
                rows.append({c: old_cols[c][p] for c in self.output_columns})
                out_keys.append(keys[i])
                out_diffs.append(-1)
            rows.append({c: new_cols[c][i] for c in self.output_columns})
            out_keys.append(keys[i])
            out_diffs.append(1)
        return _delta_from_rows(out_keys, out_diffs, rows, self.output_columns)


class FilterEvaluator(Evaluator):
    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        table = self.node.inputs[0]
        resolver = self._resolver_for(table, delta)
        mask = ee.evaluate(self.node.config["expression"], len(delta), resolver)
        return delta.select(filter_mask_to_bool(mask))


class _DerivedKeyMixin(Evaluator):
    """Provenance machinery for key-DERIVING evaluators (reindex, flatten,
    concat-reindex).

    These nodes change the row key without an exchange, so an output row
    resides wherever its INPUT row lived — the membership planner composes
    their owner function as ``upstream_owner(prov[out_key])``. The provenance
    map is tracked only under a cluster and is monotonic: derivation is
    deterministic, so a retracted-then-re-added row maps identically, and
    keeping retired entries lets late retractions route to the rank that
    still holds the matching downstream state. Growth is bounded by the
    number of DISTINCT derived keys ever produced on this rank.
    """

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self._reshard_prov: Dict[bytes, bytes] = {}

    def _track_prov(self, out_keys: Any, in_keys: Any) -> None:
        if getattr(self.runner, "_cluster", None) is None:
            return
        prov = self._reshard_prov
        for j in range(len(out_keys)):
            kb = out_keys[j].tobytes()
            if kb not in prov:
                prov[kb] = in_keys[j].tobytes()

    # -- elastic membership handoff: the provenance map itself partitions by
    # the DERIVED key's (composed) owner so the new topology can re-plan later

    def reshard_check(self) -> "str | None":
        return None

    def reshard_export(self, owner_of: Any, new_n: int) -> Dict[int, Any]:
        out: Dict[int, Any] = {}
        for kb, src in self._reshard_prov.items():
            keys = np.frombuffer(kb, dtype=KEY_DTYPE)
            dest = int(np.asarray(owner_of(keys))[0])
            out.setdefault(dest, {"prov": {}})["prov"][kb] = src
        memo = Evaluator.reshard_export(self, owner_of, new_n)
        for dest, payload in memo.items():
            out.setdefault(dest, {"prov": {}})["_udf_memo"] = payload["_udf_memo"]
        return out

    def reshard_export_parts(
        self, owner_of: Any, new_n: int, budget_rows: int
    ) -> "Iterable[tuple]":
        # streamed: never materialize the full per-dest export — buffer at
        # most ``budget_rows`` provenance entries per open destination, so the
        # donor's peak is O(budget x dests), not O(prov map)
        step = max(1, int(budget_rows))
        memo = Evaluator.reshard_export(self, owner_of, new_n)
        extras: Dict[int, dict] = {
            dest: {"_udf_memo": payload["_udf_memo"]}
            for dest, payload in memo.items()
        }
        open_parts: Dict[int, dict] = {}
        for kb, src in self._reshard_prov.items():
            keys = np.frombuffer(kb, dtype=KEY_DTYPE)
            dest = int(np.asarray(owner_of(keys))[0])
            part = open_parts.get(dest)
            if part is None:
                part = open_parts[dest] = {"prov": {}}
                part.update(extras.pop(dest, {}))
            part["prov"][kb] = src
            if len(part["prov"]) >= step:
                yield dest, open_parts.pop(dest)
        for dest in sorted(open_parts):
            yield dest, open_parts[dest]
        for dest in sorted(extras):
            # a dest owed memo state but no provenance rows
            yield dest, {"prov": {}, **extras[dest]}

    def reshard_import(self, payload: Any) -> None:
        self._reshard_prov.update((payload or {}).get("prov", {}))
        Evaluator.reshard_import(self, payload)


class ReindexEvaluator(_DerivedKeyMixin):
    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        table = self.node.inputs[0]
        resolver = self._resolver_for(table, delta)
        new_ids = ee.evaluate(self.node.config["expression"], len(delta), resolver)
        keys = pointers_to_keys(
            [p if isinstance(p, Pointer) else pointer_from(p) for p in new_ids]
        )
        self._track_prov(keys, delta.keys)
        return Delta(keys, delta.diffs, dict(delta.columns))


class ConcatEvaluator(_DerivedKeyMixin):
    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        # net live multiplicity per key: concat is a DISJOINT union, so a key
        # reaching multiplicity 2 is a collision and fails the run (reference
        # raises on duplicate keys; reindex mode cannot collide)
        self.live: Dict[bytes, int] = {}

    def process(self, input_deltas: List[Delta]) -> Delta:
        reindex = self.node.config.get("reindex", False)
        parts = []
        net: Dict[bytes, tuple] = {}  # kb -> (net diff this commit, sample key)
        for i, delta in enumerate(input_deltas):
            if len(delta) == 0:
                continue
            if reindex:
                new_keys = np.empty(len(delta), dtype=KEY_DTYPE)
                for j in range(len(delta)):
                    p = pointer_from(Pointer(int(delta.keys[j]["hi"]), int(delta.keys[j]["lo"])), i)
                    new_keys[j]["hi"], new_keys[j]["lo"] = p.hi, p.lo
                self._track_prov(new_keys, delta.keys)
                delta = Delta(new_keys, delta.diffs, delta.columns)
            else:
                for j in range(len(delta)):
                    kb = delta.keys[j].tobytes()
                    prev = net.get(kb)
                    net[kb] = (
                        (prev[0] if prev else 0) + int(delta.diffs[j]),
                        delta.keys[j],
                    )
            parts.append(delta)
        # collision check on the NET per-commit count: a same-commit key handoff
        # between inputs (one retracts, another inserts, any row order) is legal
        for kb, (d, key) in net.items():
            cnt = self.live.get(kb, 0) + d
            if cnt > 1:
                from pathway_tpu.internals.keys import keys_to_pointers

                raise ValueError(
                    "concat: duplicate key "
                    f"{keys_to_pointers(np.array([key], dtype=KEY_DTYPE))[0]!r} — "
                    "input universes must be disjoint (use concat_reindex for "
                    "overlapping tables)"
                )
            if cnt:
                self.live[kb] = cnt
            else:
                self.live.pop(kb, None)
        return Delta.concat(parts, self.output_columns)

    # -- elastic membership handoff: the collision tracker is keyed by the
    # OUTPUT key in both modes (pass-through or derived), so it partitions
    # under the same (possibly composed) owner function as the provenance map

    def reshard_export(self, owner_of: Any, new_n: int) -> Dict[int, Any]:
        out = _DerivedKeyMixin.reshard_export(self, owner_of, new_n)
        for kb, cnt in self.live.items():
            keys = np.frombuffer(kb, dtype=KEY_DTYPE)
            dest = int(np.asarray(owner_of(keys))[0])
            out.setdefault(dest, {"prov": {}}).setdefault("live", {})[kb] = cnt
        return out

    def reshard_import(self, payload: Any) -> None:
        for kb, cnt in (payload or {}).get("live", {}).items():
            self.live[kb] = self.live.get(kb, 0) + cnt
        _DerivedKeyMixin.reshard_import(self, payload)


def _col_neq(old: np.ndarray, new: np.ndarray) -> np.ndarray:
    """Elementwise inequality tolerant of object cells (ndarray values, exceptions).

    NaN compares unequal to itself, matching the previous per-row tuple compare —
    a group whose aggregate stays NaN re-emits, which is harmless."""
    try:
        res = np.asarray(old != new)
        if res.dtype == np.bool_ and res.shape == old.shape:
            return res
        # object != produced non-scalar cells (ndarray values): per-cell fallback
    except (TypeError, ValueError):
        pass

    def cell_neq(a: Any, b: Any) -> bool:
        if a is b:
            return False
        if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
            return not (
                isinstance(a, np.ndarray)
                and isinstance(b, np.ndarray)
                and np.array_equal(a, b)
            )
        try:
            return not (a == b)
        except Exception:
            return True

    return np.frompyfunc(cell_neq, 2, 1)(old, new).astype(bool)


def _group_stable(e: expr.ColumnExpression) -> bool:
    """True when the expression is a deterministic function of grouping values
    only — no reducer leaves, no non-deterministic applies anywhere in the tree."""
    if isinstance(e, expr.ReducerExpression):
        return False
    if isinstance(e, expr.ApplyExpression) and not e._deterministic:
        return False
    return all(_group_stable(d) for d in e._deps())


class GroupbyEvaluator(Evaluator):
    """Incremental groupby-reduce (reference ``reduce.rs`` + DD reduce), fully columnar.

    Group state is struct-of-arrays indexed by dense slots from the native ``KeyIndex``
    (group key -> slot): signed row counts, grouping values, one ``ColumnarState`` per
    reducer leaf (``internals/reducers.py``), and the last-emitted output row per group
    for change detection. A commit is a handful of vectorized passes — hash, upsert,
    segment-reduce, gather — with per-group Python only inside non-semigroup reducer
    fallbacks (the reference's recompute-style reducers)."""

    # reducer_leaves is graph config: checkpoints must not replace it — identity (id())
    # keys the leaf-value mapping
    _NON_STATE_ATTRS = Evaluator._NON_STATE_ATTRS + ("reducer_leaves",)

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        from pathway_tpu.engine.index import KeyIndex

        self.gindex = KeyIndex()
        self._capacity = 0
        self.gkeys = np.zeros(0, dtype=KEY_DTYPE)
        self.counts = np.zeros(0, dtype=np.int64)
        self.last_valid = np.zeros(0, dtype=bool)
        self.gvals: Dict[str, np.ndarray] = {
            name: np.empty(0, dtype=object) for name in node.config["grouping_names"]
        }
        self.last_cols: Dict[str, np.ndarray] = {
            name: np.empty(0, dtype=object) for name in self.output_columns
        }
        self.reducer_leaves: List[expr.ReducerExpression] = []
        self._collect_reducers(node.config["out_exprs"])
        self.leaf_states = [leaf._reducer.make_state() for leaf in self.reducer_leaves]
        self.seq = 0
        # output columns that are pure functions of the grouping values (no
        # reducer, no non-deterministic apply) CANNOT change while a group is
        # alive — change detection skips comparing them (group keys fingerprint
        # the grouping values, so equal key implies equal value)
        self._stable_cols = {
            name
            for name, e in node.config["out_exprs"].items()
            if _group_stable(e)
        }

    def load_state_dict(self, state: Dict[str, bytes]) -> None:
        super().load_state_dict(state)
        if "groups" in self.__dict__:
            # dict-of-groups checkpoints predate the columnar state; restoring them
            # silently-empty would corrupt aggregates — fail loudly instead
            raise RuntimeError(
                "checkpoint was written by an incompatible (pre-columnar) build; "
                "clear the persistence directory and re-run"
            )

    # -- elastic membership handoff ------------------------------------------
    #
    # Group state is columnar keyed by the group key (= output row key), so
    # the reshard is exactly an array redistribution: gather the moved
    # groups' slots (counts, grouping values, last-emitted rows, every
    # reducer leaf's accumulator columns), ship per new owner, scatter into
    # freshly upserted slots on the importer.

    def reshard_check(self) -> "str | None":
        # columnar group state partitions exactly by group key — but the
        # non-deterministic-UDF replay memo is keyed by INPUT row key while
        # future retractions route by GROUP key, so a populated memo cannot
        # be re-partitioned (the row->group mapping is not recoverable from
        # the memo): refuse loudly rather than silently drop replay values
        if self.__dict__.get("_udf_memo"):
            return (
                "GroupbyEvaluator holds non-deterministic-UDF replay state "
                "(_udf_memo) that cannot be re-partitioned by group key"
            )
        return None

    def reshard_export(self, owner_of: Any, new_n: int) -> Dict[int, Any]:
        reason = self.reshard_check()
        if reason is not None:
            from pathway_tpu.parallel.membership import MembershipUnsupportedError

            raise MembershipUnsupportedError(reason)
        gkeys, slots = self.gindex.items()
        if len(gkeys) == 0:
            return {}
        owners = np.asarray(owner_of(gkeys))
        out: Dict[int, Any] = {}
        for dest in np.unique(owners):
            sel = owners == dest
            dslots = slots[sel]
            out[int(dest)] = {
                "gkeys": gkeys[sel].copy(),
                "counts": self.counts[dslots].copy(),
                "last_valid": self.last_valid[dslots].copy(),
                "gvals": {n: a[dslots].copy() for n, a in self.gvals.items()},
                "last_cols": {
                    n: a[dslots].copy() for n, a in self.last_cols.items()
                },
                "leaves": [st.reshard_take(dslots) for st in self.leaf_states],
                "seq": int(self.seq),
            }
        return out

    def reshard_import(self, payload: Any) -> None:
        from pathway_tpu.engine.columnar import set_cells

        gkeys = payload["gkeys"]
        if len(gkeys) == 0:
            return
        slots, is_new = self.gindex.upsert(gkeys)
        if not is_new.all():
            raise RuntimeError(
                "membership handoff fragment re-imported a group key that is "
                "already present — fragments must be disjoint; the store is "
                "inconsistent"
            )
        self._ensure_capacity()
        self.gkeys[slots] = gkeys
        self.counts[slots] = payload["counts"]
        self.last_valid[slots] = payload["last_valid"]
        for name in self.gvals:
            self.gvals[name] = set_cells(
                self.gvals[name], slots, payload["gvals"][name]
            )
        for name in self.last_cols:
            self.last_cols[name] = set_cells(
                self.last_cols[name], slots, payload["last_cols"][name]
            )
        for st, blob in zip(self.leaf_states, payload["leaves"]):
            st.reshard_put(slots, blob)
        # seq continues past every donor's counter: the sequence reducer's
        # per-rank monotonicity survives the move
        self.seq = max(self.seq, int(payload.get("seq", 0)))

    def _collect_reducers(self, out_exprs: Dict[str, expr.ColumnExpression]) -> None:
        seen: set[int] = set()

        def walk(e: expr.ColumnExpression) -> None:
            if isinstance(e, expr.ReducerExpression):
                if id(e) not in seen:
                    seen.add(id(e))
                    self.reducer_leaves.append(e)
                return
            for d in e._deps():
                walk(d)

        for e in out_exprs.values():
            walk(e)

    def _ensure_capacity(self) -> None:
        bound = self.gindex.slot_bound()
        if bound <= self._capacity:
            return
        cap = max(16, 2 * self._capacity, bound)
        gkeys = np.zeros(cap, dtype=KEY_DTYPE)
        gkeys[: self._capacity] = self.gkeys
        self.gkeys = gkeys
        self.counts = np.concatenate(
            [self.counts, np.zeros(cap - len(self.counts), dtype=np.int64)]
        )
        valid = np.zeros(cap, dtype=bool)
        valid[: self._capacity] = self.last_valid
        self.last_valid = valid
        from pathway_tpu.engine.columnar import grow_column

        for name in self.gvals:
            self.gvals[name] = grow_column(self.gvals[name], cap)
        for name in self.last_cols:
            self.last_cols[name] = grow_column(self.last_cols[name], cap)
        for st in self.leaf_states:
            st.ensure(cap)
        self._capacity = cap

    def _eval_out(self, slots: np.ndarray) -> Dict[str, np.ndarray]:
        """Output expressions over the given group slots, vectorized, with reducer
        leaves bound to their columnar aggregates."""
        leaf_value_arrays = {
            id(leaf): st.values(slots)
            for leaf, st in zip(self.reducer_leaves, self.leaf_states)
        }
        gval_arrays = {name: self.gvals[name][slots] for name in self.gvals}

        class _GroupEval(ee.ExpressionEvaluator):
            def _eval_ReducerExpression(self, re: expr.ReducerExpression) -> np.ndarray:
                return leaf_value_arrays[id(re)]

            def _eval_ColumnReference(self, ref: expr.ColumnReference) -> np.ndarray:
                return gval_arrays[ref.name]

        evaluator = _GroupEval(ee.EvalContext(len(slots), lambda ref: None))
        out_exprs = self.node.config["out_exprs"]
        return {name: evaluator.eval(out_exprs[name]) for name in self.output_columns}

    def _group_keys(self, grouping_vals: List[np.ndarray], n: int, set_id: bool) -> np.ndarray:
        if not grouping_vals:
            # global reduce: every row lands in the single salt-only group
            return broadcast_key(pointer_from(), n)
        if not set_id:
            return keys_from_values(grouping_vals)
        col = grouping_vals[0]
        out = np.empty(n, dtype=KEY_DTYPE)
        for i in range(n):
            p = col[i]
            if not isinstance(p, Pointer):
                p = pointer_from(*(g[i] for g in grouping_vals))
            out[i]["hi"], out[i]["lo"] = p.hi, p.lo
        return out

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        set_id = self.node.config.get("set_id", False)
        cluster = getattr(self.runner, "_cluster", None)
        if cluster is not None:
            # hash-route rows to their group key's owner process (all-to-all
            # barrier; participates even with no local rows — peers block on our
            # partitions). Reference: DD reduce's exchange over the Cluster
            # allocator, shard.rs routing.
            n0 = len(delta)
            resolver0 = self._resolver_for(self.node.inputs[0], delta)
            gvals0 = [ee.evaluate(g, n0, resolver0) for g in self.node.config["grouping"]]
            gkeys0 = self._group_keys(gvals0, n0, set_id)
            tag = f"{self.runner.current_time}:{self.node.id}:g".encode()
            delta = cluster.exchange_delta(tag, delta, gkeys0)
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        table = self.node.inputs[0]
        resolver = self._resolver_for(table, delta)
        n = len(delta)
        diffs = delta.diffs

        grouping_vals = [
            ee.evaluate(g, n, resolver) for g in self.node.config["grouping"]
        ]

        # reducer argument values per leaf (vectorized)
        leaf_args: List[List[np.ndarray]] = []
        for leaf in self.reducer_leaves:
            arrays = []
            for a in leaf._args:
                if isinstance(a, _IdMarker):
                    ids = np.empty(n, dtype=object)
                    ids[:] = keys_to_pointers(delta.keys)
                    arrays.append(ids)
                elif isinstance(a, _SeqMarker):
                    seqs = np.arange(self.seq, self.seq + n, dtype=np.int64)
                    arrays.append(seqs.astype(object))
                else:
                    arrays.append(self._eval_expr(a, delta, resolver))
            leaf_args.append(arrays)
        self.seq += n

        if grouping_vals and not set_id:
            # fused native fingerprint + upsert: one crossing for the hot pair
            gkeys, slots, is_new = hash_upsert(self.gindex, grouping_vals)
        else:
            gkeys = self._group_keys(grouping_vals, n, set_id)
            slots, is_new = self.gindex.upsert(gkeys)
        self._ensure_capacity()
        new_slots = slots[is_new]
        if len(new_slots):
            # recycled slots start pristine
            self.counts[new_slots] = 0
            self.last_valid[new_slots] = False
            self.gkeys[new_slots] = gkeys[is_new]
            for st in self.leaf_states:
                st.reset(new_slots)
            from pathway_tpu.engine.columnar import set_cells

            for gi, name in enumerate(self.gvals):
                self.gvals[name] = set_cells(
                    self.gvals[name], new_slots, np.asarray(grouping_vals[gi])[is_new]
                )

        from pathway_tpu.ops.segment import segment_count

        # dense batch segmentation: an O(n + slot_bound) bitmap pass when the batch
        # is comparable to the live slot space; an O(n log n) sort when a small
        # commit touches a huge accumulated group space (bitmap would scan it all)
        bound = self.gindex.slot_bound()
        if bound <= 4 * n + 1024:
            seen = np.zeros(bound, dtype=bool)
            seen[slots] = True
            uniq_slots = np.nonzero(seen)[0]
            pos_of_slot = np.empty(bound, dtype=np.int64)
            pos_of_slot[uniq_slots] = np.arange(len(uniq_slots), dtype=np.int64)
            inverse = pos_of_slot[slots]
        else:
            uniq_slots, inverse = np.unique(slots, return_inverse=True)
        m = len(uniq_slots)
        cnt_delta = segment_count(inverse, m, weights=diffs)
        counts_after = self.counts[uniq_slots] + cnt_delta

        for st, arrays in zip(self.leaf_states, leaf_args):
            st.update(
                slots, uniq_slots, inverse, arrays, diffs, cnt_delta, counts_after,
                key_lo=gkeys["lo"],
            )
        self.counts[uniq_slots] = counts_after

        # -- emission: retract old rows, insert new rows, per changed group ----
        alive_mask = counts_after > 0
        alive_slots = uniq_slots[alive_mask]
        dead_slots = uniq_slots[~alive_mask]

        new_cols = self._eval_out(alive_slots) if len(alive_slots) else {}
        had_row_alive = self.last_valid[alive_slots]
        changed = ~had_row_alive  # groups without a cached row always emit
        if had_row_alive.any():
            idx = np.nonzero(had_row_alive)[0]
            neq = np.zeros(len(idx), dtype=bool)
            for name in self.output_columns:
                if name in self._stable_cols:
                    continue  # pure grouping function: equal by construction
                old = self.last_cols[name][alive_slots[idx]]
                neq |= _col_neq(old, new_cols[name][idx])
            changed[idx] |= neq

        # retracts: dead groups with a cached row + changed alive groups with one
        r_uniq = np.zeros(m, dtype=bool)
        r_uniq[~alive_mask] = self.last_valid[dead_slots]
        alive_pos = np.nonzero(alive_mask)[0]
        r_uniq[alive_pos] = had_row_alive & changed
        i_uniq = np.zeros(m, dtype=bool)
        i_uniq[alive_pos] = changed

        if not r_uniq.any() and not i_uniq.any():
            if len(dead_slots):
                self._bury(dead_slots)
            return Delta.empty(self.output_columns)

        # interleave so each group's retract immediately precedes its insert
        r_idx = np.nonzero(r_uniq)[0]
        i_idx = np.nonzero(i_uniq)[0]
        seqd = np.sort(np.concatenate([r_idx * 2, i_idx * 2 + 1]))
        is_ins = (seqd % 2) == 1
        group_pos = seqd // 2
        ev_slots = uniq_slots[group_pos]
        out_keys = self.gkeys[ev_slots]
        out_diffs = np.where(is_ins, 1, -1).astype(np.int64)

        # map uniq position -> position in alive_slots (for gathering new values)
        alive_rel = np.full(m, -1, dtype=np.int64)
        alive_rel[alive_pos] = np.arange(len(alive_slots))
        ins_rel = alive_rel[group_pos[is_ins]]

        from pathway_tpu.engine.columnar import set_cells

        columns: Dict[str, np.ndarray] = {}
        for name in self.output_columns:
            old_part = self.last_cols[name][ev_slots[~is_ins]]
            new_part = new_cols[name][ins_rel] if len(ins_rel) else np.empty(0, dtype=object)
            if not is_ins.any():
                columns[name] = old_part
            elif not (~is_ins).any():
                columns[name] = new_part
            else:
                out = None
                if old_part.dtype == new_part.dtype and old_part.dtype != object:
                    out = np.empty(len(is_ins), dtype=old_part.dtype)
                else:
                    out = np.empty(len(is_ins), dtype=object)
                try:
                    out[~is_ins] = old_part
                    out[is_ins] = new_part
                except (TypeError, ValueError):
                    out = np.empty(len(is_ins), dtype=object)
                    out[~is_ins] = old_part
                    out[is_ins] = new_part
                columns[name] = out

        # update the last-emitted cache
        changed_slots = alive_slots[changed]
        if len(changed_slots):
            for name in self.output_columns:
                self.last_cols[name] = set_cells(
                    self.last_cols[name], changed_slots, new_cols[name][changed]
                )
            self.last_valid[changed_slots] = True
        if len(dead_slots):
            self._bury(dead_slots)

        return Delta(out_keys, out_diffs, columns)

    def _bury(self, dead_slots: np.ndarray) -> None:
        """A group's multiset emptied: drop it from the index (slot recycles) and
        release cached object references."""
        self.last_valid[dead_slots] = False
        self.gindex.remove(self.gkeys[dead_slots])
        for name in self.last_cols:
            col = self.last_cols[name]
            if col.dtype == object:
                col[dead_slots] = None
        for name in self.gvals:
            col = self.gvals[name]
            if col.dtype == object:
                col[dead_slots] = None


class DeduplicateEvaluator(Evaluator):
    # state is per INSTANCE: route rows to their instance's owner process
    # (within-commit arrival order across processes is rank-merged, the same
    # nondeterminism timely's exchange has). The route key IS the instance's
    # OUTPUT row key (``pointer_from(inst, "dedup")``), so the rank owning an
    # instance's state also owns its emitted rows — the reshard planner
    # treats this "custom" exchange as plain ``bykey`` (RESHARD_ROUTE_BYKEY).
    CLUSTER_POLICIES = {0: "custom"}
    RESHARD_ROUTE_BYKEY = True

    @staticmethod
    def _instance_out_key(inst: Any) -> Pointer:
        return pointer_from(
            inst if not isinstance(inst, np.void) else int(inst["lo"]), "dedup"
        )

    def cluster_route_keys(self, idx: int, delta: Delta) -> np.ndarray:
        instance_e = self.node.config.get("instance")
        if instance_e is None:
            # global dedup: a single logical instance — one owner (the
            # process owning the output key of instance 0)
            return broadcast_key(self._instance_out_key(0), len(delta))
        resolver = self._resolver_for(self.node.inputs[0], delta)
        instances = ee.evaluate(instance_e, len(delta), resolver)
        return pointers_to_keys(
            [self._instance_out_key(inst) for inst in instances]
        )

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.current: Dict[bytes, Tuple[np.void, dict, Any]] = {}  # instance -> (key,row,value)
        # instance -> output row-key bytes: the reshard partition key for
        # ``current`` (an instance repr is not invertible)
        self._okeys: Dict[bytes, bytes] = {}

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        table = self.node.inputs[0]
        resolver = self._resolver_for(table, delta)
        n = len(delta)
        value_e = self.node.config.get("value")
        instance_e = self.node.config.get("instance")
        acceptor = self.node.config.get("acceptor")
        values = ee.evaluate(value_e, n, resolver) if value_e is not None else delta.keys
        instances = (
            ee.evaluate(instance_e, n, resolver)
            if instance_e is not None
            else np.zeros(n, dtype=object)
        )
        # emission is consolidated PER INSTANCE per call: several accepted rows
        # for one instance in a single delta must not emit chained retract/add
        # pairs for the same output key — StateTable.apply replays retractions
        # before insertions, so an intra-delta chain would retract a row whose
        # add rides the very same delta. One retraction of the pre-call row
        # (if any) plus one add of the final winner keeps every retraction
        # pointing at an already-applied row.
        pre: Dict[bytes, Any] = {}  # ib -> (ikey, pre-call entry | None)
        for i in range(n):
            if delta.diffs[i] < 0:
                continue  # append-only semantics (reference deduplicate is streaming-only)
            inst = instances[i]
            ib = repr(inst).encode()
            row = {c: delta.columns[c][i] for c in delta.column_names}
            val = values[i]
            cur = self.current.get(ib)
            if cur is None:
                accept = True
            else:
                accept = bool(acceptor(val, cur[2])) if acceptor is not None else True
            if not accept:
                continue
            ikey = self._instance_out_key(inst)
            if ib not in pre:
                pre[ib] = (ikey, cur)
            self.current[ib] = (delta.keys[i], row, val)
            if ib not in self._okeys:
                self._okeys[ib] = pointers_to_keys([ikey])[0].tobytes()
        if not pre:
            return Delta.empty(self.output_columns)
        out_keys, out_diffs, out_rows = [], [], []
        for ib, (ikey, cur) in pre.items():
            if cur is not None:
                out_keys.append(ikey)
                out_diffs.append(-1)
                out_rows.append(cur[1])
            out_keys.append(ikey)
            out_diffs.append(1)
            out_rows.append(self.current[ib][1])
        columns = {
            name: ee._tidy(objarray([r[name] for r in out_rows]))
            for name in self.output_columns
        }
        return Delta(pointers_to_keys(out_keys), np.array(out_diffs, dtype=np.int64), columns)

    # -- elastic membership handoff: instances partition by OUTPUT key -------

    def reshard_check(self) -> "str | None":
        if self.__dict__.get("_udf_memo"):
            return (
                "DeduplicateEvaluator holds a non-deterministic replay memo "
                "keyed by pre-exchange row keys — re-partitioning by instance "
                "output key cannot place it"
            )
        if len(self._okeys) < len(self.current):
            # a pre-upgrade checkpoint restored `current` without the output
            # key sidecar: those instances cannot be placed — refuse loudly
            return (
                "DeduplicateEvaluator state predates output-key tracking "
                f"({len(self.current) - len(self._okeys)} instance(s) without "
                "a recorded output key) — cannot re-partition this checkpoint"
            )
        return None

    def reshard_export(self, owner_of: Any, new_n: int) -> Dict[int, Any]:
        reason = self.reshard_check()
        if reason is not None:
            from pathway_tpu.parallel.membership import MembershipUnsupportedError

            raise MembershipUnsupportedError(reason)
        if not self.current:
            return {}
        from pathway_tpu.internals.keys import KEY_DTYPE

        out: Dict[int, Any] = {}
        for ib, entry in self.current.items():
            kb = self._okeys[ib]
            dest = int(np.asarray(owner_of(np.frombuffer(kb, dtype=KEY_DTYPE)))[0])
            bucket = out.setdefault(dest, {"current": {}, "okeys": {}})
            bucket["current"][ib] = entry
            bucket["okeys"][ib] = kb
        return out

    def reshard_export_parts(
        self, owner_of: Any, new_n: int, budget_rows: int
    ) -> "Iterable[tuple]":
        # streamed: never materialize the full per-dest export — buffer at
        # most ``budget_rows`` instances per open destination, so the donor's
        # peak is O(budget x dests), not O(instances)
        reason = self.reshard_check()
        if reason is not None:
            from pathway_tpu.parallel.membership import MembershipUnsupportedError

            raise MembershipUnsupportedError(reason)
        from pathway_tpu.internals.keys import KEY_DTYPE

        step = max(1, int(budget_rows))
        open_parts: Dict[int, dict] = {}
        for ib, entry in self.current.items():
            kb = self._okeys[ib]
            dest = int(np.asarray(owner_of(np.frombuffer(kb, dtype=KEY_DTYPE)))[0])
            part = open_parts.setdefault(dest, {"current": {}, "okeys": {}})
            part["current"][ib] = entry
            part["okeys"][ib] = kb
            if len(part["current"]) >= step:
                yield dest, open_parts.pop(dest)
        for dest in sorted(open_parts):
            yield dest, open_parts[dest]

    def reshard_import(self, payload: Any) -> None:
        cur = (payload or {}).get("current", {})
        overlap = self.current.keys() & cur.keys()
        if overlap:
            raise RuntimeError(
                "dedup reshard import found an instance already present — "
                "handoff fragments overlap"
            )
        self.current.update(cur)
        self._okeys.update((payload or {}).get("okeys", {}))


class _JoinSide:
    """Columnar arrangement for one join side on native structures: a ``KeyIndex``
    (row key -> slot), a ``MultiMap`` (join key -> row slots), and slot-indexed value
    arrays. The DD-arrangement stand-in for the join's build state (reference
    ``dataflow.rs`` join over arranged collections) — inserts, removals, and probes
    are O(batch) native calls."""

    def __init__(self, names: Iterable[str]):
        from pathway_tpu.engine.index import KeyIndex, MultiMap

        self.names = list(names)
        self.row_index = KeyIndex()
        self.jkmap = MultiMap()
        self._capacity = 0
        self.keys = np.zeros(0, dtype=KEY_DTYPE)
        self.jk = np.zeros(0, dtype=KEY_DTYPE)
        self.cols: Dict[str, np.ndarray] = {c: np.empty(0, dtype=object) for c in self.names}

    def _ensure_capacity(self, bound: int | None = None) -> None:
        if bound is None:
            bound = self.row_index.slot_bound()
        if bound <= self._capacity:
            return
        from pathway_tpu.engine.columnar import grow_column

        cap = max(16, 2 * self._capacity, bound)
        keys = np.empty(cap, dtype=KEY_DTYPE)
        keys[: self._capacity] = self.keys
        self.keys = keys
        jk = np.empty(cap, dtype=KEY_DTYPE)
        jk[: self._capacity] = self.jk
        self.jk = jk
        for c in self.names:
            self.cols[c] = grow_column(self.cols[c], cap)
        self._capacity = cap

    def insert_batch(
        self, row_keys: np.ndarray, jkeys: np.ndarray, values: Dict[str, np.ndarray]
    ) -> np.ndarray:
        from pathway_tpu.engine.columnar import set_cells
        from pathway_tpu.engine.index import _NativeKeyIndex, _NativeMultiMap

        n = len(row_keys)
        if self._capacity == 0:
            # first allocation: value-column dtypes come from the first batch
            # through (StateTable does the same) — downstream gathers then stay
            # typed int64/float64 instead of object, which keeps the groupby
            # reducers fed by this join on their vectorized segment kernels
            # (an object `net` column was a per-row Python sum, ~40x slower);
            # set_cells/adopt_dtype still demote to object on any conflict
            for c in self.names:
                self.cols[c] = np.empty(0, dtype=np.asarray(values[c]).dtype)
        if isinstance(self.row_index, _NativeKeyIndex) and isinstance(
            self.jkmap, _NativeMultiMap
        ):
            # fused native pass: upsert + duplicate-replace + slot writes + jk-map
            import ctypes

            u64p = ctypes.POINTER(ctypes.c_uint64)
            i64p = ctypes.POINTER(ctypes.c_int64)
            self._ensure_capacity(self.row_index.slot_bound() + n)
            rk = np.ascontiguousarray(row_keys)
            jkc = np.ascontiguousarray(jkeys)
            slots = np.empty(n, dtype=np.int64)
            self.row_index._lib.pwtpu_side_insert(
                self.row_index._h, self.jkmap._h,
                rk.ctypes.data_as(u64p), jkc.ctypes.data_as(u64p), n,
                self.keys.ctypes.data_as(u64p), self.jk.ctypes.data_as(u64p),
                slots.ctypes.data_as(i64p),
            )
        else:
            # pure-Python fallback: sequential, mirroring the fused native pass
            # exactly (within-batch duplicate row keys replace the earlier row,
            # including its join-key bucket entry)
            self._ensure_capacity(self.row_index.slot_bound() + n)
            slots = np.empty(n, dtype=np.int64)
            one = np.empty(1, dtype=np.int64)
            for i in range(n):
                s_arr, new_arr = self.row_index.upsert(row_keys[i : i + 1])
                s = int(s_arr[0])
                if not new_arr[0]:
                    one[0] = s
                    self.jkmap.remove(self.jk[s : s + 1], one)
                self.keys[s] = row_keys[i]
                self.jk[s] = jkeys[i]
                one[0] = s
                self.jkmap.insert(jkeys[i : i + 1], one)
                slots[i] = s
        for c in self.names:
            self.cols[c] = set_cells(self.cols[c], slots, values[c])
        return slots

    def reshard_export(self, owner_of: Any) -> Dict[int, dict]:
        """Partition the live arrangement by the JOIN key's new owner:
        per-dest parallel arrays (row keys, join keys, value columns) a fresh
        side rebuilds from via :meth:`reshard_import`. Complete — includes
        the rows this rank keeps."""
        keys, slots = self.row_index.items()
        if not len(keys):
            return {}
        jk = self.jk[slots]
        owners = np.asarray(owner_of(jk))
        out: Dict[int, dict] = {}
        for dest in np.unique(owners):
            sel = slots[owners == dest]
            out[int(dest)] = {
                "keys": self.keys[sel].copy(),
                "jk": self.jk[sel].copy(),
                "cols": {c: self.cols[c][sel].copy() for c in self.names},
            }
        return out

    def reshard_export_chunks(
        self, owner_of: Any, budget_rows: int
    ) -> "Iterable[tuple]":
        """Bounded variant of :meth:`reshard_export`: yields ``(dest, piece)``
        with ≤``budget_rows`` rows per piece, copying only one piece at a
        time (the O(rows) owner metadata is ints, never row payload)."""
        keys, slots = self.row_index.items()
        if not len(keys):
            return
        owners = np.asarray(owner_of(self.jk[slots]))
        step = max(1, int(budget_rows))
        for dest in np.unique(owners):
            sel = slots[owners == dest]
            for s in range(0, len(sel), step):
                sl = sel[s : s + step]
                yield int(dest), {
                    "keys": self.keys[sl].copy(),
                    "jk": self.jk[sl].copy(),
                    "cols": {c: self.cols[c][sl].copy() for c in self.names},
                }

    def reshard_import(self, payload: dict) -> None:
        keys = payload.get("keys")
        if keys is None or not len(keys):
            return
        present = self.row_index.lookup(keys)
        if (present >= 0).any():
            # two old ranks both claimed a row key: the partitions were not
            # disjoint — corrupt handoff, never merge silently
            raise RuntimeError(
                "join-side reshard import found a row key already present — "
                "handoff fragments overlap"
            )
        self.insert_batch(keys, payload["jk"], payload["cols"])

    def remove_batch(self, row_keys: np.ndarray) -> np.ndarray:
        """Slots removed per key (-1 when the key was absent)."""
        from pathway_tpu.engine.index import _NativeKeyIndex, _NativeMultiMap

        n = len(row_keys)
        if isinstance(self.row_index, _NativeKeyIndex) and isinstance(
            self.jkmap, _NativeMultiMap
        ):
            import ctypes

            u64p = ctypes.POINTER(ctypes.c_uint64)
            i64p = ctypes.POINTER(ctypes.c_int64)
            rk = np.ascontiguousarray(row_keys)
            slots = np.empty(n, dtype=np.int64)
            self.row_index._lib.pwtpu_side_remove(
                self.row_index._h, self.jkmap._h,
                rk.ctypes.data_as(u64p), n,
                self.jk.ctypes.data_as(u64p), slots.ctypes.data_as(i64p),
            )
        else:
            slots = self.row_index.remove(row_keys)
            present = np.nonzero(slots >= 0)[0]
            if len(present):
                self.jkmap.remove(self.jk[slots[present]], slots[present])
        present = np.nonzero(slots >= 0)[0]
        if len(present):
            live = slots[present]
            for c in self.names:
                col = self.cols[c]
                if col.dtype == object:
                    col[live] = None
        return slots


class JoinEvaluator(Evaluator):
    """Symmetric incremental hash join (reference DD join replacement).

    Hot path is fully columnar: per commit, each side's join keys hash in one
    vectorized pass, the other side's matches come back as one CSR probe from the
    native multimap, and emission gathers own-side values straight from the delta
    (retraction rows carry their retracted values) and other-side values from slot
    arrays. Outer-join null-row bookkeeping runs per distinct join key, not per row.
    """

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        from pathway_tpu.internals.joins import JoinKind

        self.kind = node.config["kind"]
        self.JoinKind = JoinKind
        self.left = _JoinSide(node.inputs[0].column_names())
        self.right = _JoinSide(node.inputs[1].column_names())

    def load_state_dict(self, state: Dict[str, bytes]) -> None:
        super().load_state_dict(state)
        if "left_map" in self.__dict__ or "right_map" in self.__dict__:
            raise RuntimeError(
                "checkpoint was written by an incompatible (pre-columnar) build; "
                "clear the persistence directory and re-run"
            )

    # -- elastic membership handoff: arrangements partition by JOIN key ------
    #
    # In cluster mode both input sides exchange by join key, so this rank's
    # arrangements hold exactly the rows whose join key it owns — they
    # re-partition under shard_of(join_key, new_n). The join's OUTPUT is
    # exchanged by output row key (see process), so the planner treats the
    # node as "bykey": the owner function it hands reshard_export is the
    # plain new-topology hash, which this export applies to JOIN keys.

    def reshard_check(self) -> "str | None":
        if self.__dict__.get("_udf_memo"):
            return (
                "JoinEvaluator holds a non-deterministic replay memo keyed by "
                "pre-exchange row keys — re-partitioning by join key cannot "
                "place it"
            )
        return None

    def reshard_export(self, owner_of: Any, new_n: int) -> Dict[int, Any]:
        reason = self.reshard_check()
        if reason is not None:
            from pathway_tpu.parallel.membership import MembershipUnsupportedError

            raise MembershipUnsupportedError(reason)
        out: Dict[int, Any] = {}
        for side_name, side in (("left", self.left), ("right", self.right)):
            for dest, payload in side.reshard_export(owner_of).items():
                out.setdefault(dest, {})[side_name] = payload
        return out

    def reshard_export_parts(
        self, owner_of: Any, new_n: int, budget_rows: int
    ) -> "Iterable[tuple]":
        """Bounded-transport export: the same partitions as
        :meth:`reshard_export`, sliced into ≤``budget_rows``-row pieces so the
        chunked fragment stream never materializes a whole side at once.
        Pieces merge on import (insert_batch is incremental)."""
        reason = self.reshard_check()
        if reason is not None:
            from pathway_tpu.parallel.membership import MembershipUnsupportedError

            raise MembershipUnsupportedError(reason)
        for side_name, side in (("left", self.left), ("right", self.right)):
            for dest, piece in side.reshard_export_chunks(owner_of, budget_rows):
                yield dest, {side_name: piece}

    def reshard_import(self, payload: Any) -> None:
        for side_name, side in (("left", self.left), ("right", self.right)):
            p = (payload or {}).get(side_name)
            if p:
                side.reshard_import(p)

    def _join_keys(self, side: str, delta: Delta) -> np.ndarray:
        table = self.node.inputs[0 if side == "left" else 1]
        exprs = self.node.config["left_on" if side == "left" else "right_on"]
        if not exprs:
            # no on-condition: every row shares the salt-only bucket (cross join)
            return broadcast_key(pointer_from(), len(delta))
        resolver = self._resolver_for(table, delta)
        arrays = [self._eval_expr(e, delta, resolver) for e in exprs]
        return keys_from_values(arrays)

    def process(self, input_deltas: List[Delta]) -> Delta:
        left_delta, right_delta = input_deltas
        cluster = getattr(self.runner, "_cluster", None)
        parts: List[Delta] = []
        JK = self.JoinKind
        for delta, side_name in ((left_delta, "left"), (right_delta, "right")):
            if len(delta) == 0 and cluster is None:
                continue
            # Frontier optimization: own-side rows are arranged only so FUTURE
            # other-side deltas can probe them (and, for outer kinds, so null-row
            # bookkeeping can see past own-side counts). When the other side's
            # subtree is closed — no delta this commit and none ever again — and
            # the other side never emits null rows, arranging this side buys
            # nothing: skip it. This is the static-build-side join fast path.
            is_left = side_name == "left"
            other_delta = right_delta if is_left else left_delta
            other_null = self.kind in ((JK.RIGHT, JK.OUTER) if is_left else (JK.LEFT, JK.OUTER))
            other_table = self.node.inputs[1 if is_left else 0]
            skip_arrange = (
                not other_null
                and len(other_delta) == 0
                and self.runner.subtree_closed(other_table._node)
            )
            part = self._run_side(delta, side_name, skip_arrange=skip_arrange)
            if part is not None and len(part):
                parts.append(part)
        if not parts:
            out = Delta.empty(self.output_columns)
        else:
            out = Delta.concat(parts, self.output_columns).consolidated()
        if cluster is not None and self.runner._persistence is not None:
            # replies re-route by OUTPUT row key: post-join rows land on their
            # output key's owner, so this node's materialized output and every
            # downstream key-preserving chain is plain "bykey" state for the
            # reshard planner — the join's arrangements (keyed by join key)
            # are the only state that partitions by shard_of(join_key)
            # (all-to-all barrier; runs even when empty). Only reshard-capable
            # runs (persistence on — membership handoffs write through it)
            # need the invariant; ephemeral runs keep rows where the join-key
            # exchange computed them and skip the extra hop.
            tag = f"{self.runner.current_time}:{self.node.id}:out".encode()
            out = cluster.exchange_delta(tag, out, out.keys)
        return out

    def _run_side(
        self, delta: Delta, side_name: str, *, skip_arrange: bool = False
    ) -> Delta | None:
        JK = self.JoinKind
        is_left = side_name == "left"
        own = self.left if is_left else self.right
        other = self.right if is_left else self.left
        own_null = self.kind in ((JK.LEFT, JK.OUTER) if is_left else (JK.RIGHT, JK.OUTER))
        other_null = self.kind in ((JK.RIGHT, JK.OUTER) if is_left else (JK.LEFT, JK.OUTER))

        cluster = getattr(self.runner, "_cluster", None)
        if cluster is not None:
            # both sides hash-route by JOIN key, so every join key's rows meet on
            # one owner process (all-to-all barrier; runs even with no local rows)
            jkeys0 = self._join_keys(side_name, delta)
            tag = f"{self.runner.current_time}:{self.node.id}:{side_name}".encode()
            delta = cluster.exchange_delta(tag, delta, jkeys0)
        if len(delta) == 0:
            return None

        n = len(delta)
        diffs = delta.diffs
        jkeys = self._join_keys(side_name, delta)

        # one CSR probe against the other side (static during this side's pass)
        offsets, match_slots = other.jkmap.probe(jkeys)
        counts = np.diff(offsets)

        # matched events: row i of the delta x each matching other-side slot.
        # Unique-key build sides (the common case) probe to exactly one match
        # per row — the repeats collapse to identity/copy, skip them.
        own_identity = False
        if len(match_slots) == n and counts[-1] == 1 and (counts == 1).all():
            ev_row = np.arange(n, dtype=np.int64)
            ev_d = diffs
            own_identity = True
        else:
            ev_row = np.repeat(np.arange(n, dtype=np.int64), counts)
            ev_d = np.repeat(diffs, counts)
        ev_other = match_slots

        null_rows = np.zeros(0, dtype=np.int64)
        null_d = np.zeros(0, dtype=np.int64)
        flip_slots = np.zeros(0, dtype=np.int64)
        flip_d = np.zeros(0, dtype=np.int64)
        if own_null:
            # unmatched rows of a LEFT/OUTER side emit with the other side null
            unmatched = np.nonzero(counts == 0)[0]
            null_rows = unmatched
            null_d = diffs[unmatched]
        if other_null and len(match_slots):
            # other-side rows flip between "null row" and "matched": when this side's
            # distinct join key goes 0 -> >0 rows, retract the other side's null rows;
            # on >0 -> 0, re-emit them. Tracked per distinct join key.
            from pathway_tpu.engine.index import KeyIndex

            uidx = KeyIndex(n)
            uslot, first = uidx.upsert(jkeys)
            n_keys = uidx.slot_bound()
            base = np.zeros(n_keys, dtype=np.int64)
            own_counts, _ = own.jkmap.counts(jkeys[first])
            base[uslot[first]] = own_counts
            net = np.zeros(n_keys, dtype=np.int64)
            np.add.at(net, uslot, diffs)
            flips: List[tuple] = []
            went_up = np.nonzero((base == 0) & (net > 0))[0]
            went_down = np.nonzero((base > 0) & (base + net == 0))[0]
            if len(went_up) or len(went_down):
                first_rows = np.nonzero(first)[0]
                row_of_uslot = np.zeros(n_keys, dtype=np.int64)
                row_of_uslot[uslot[first_rows]] = first_rows
                for uj, d in [(j, -1) for j in went_up] + [(j, 1) for j in went_down]:
                    r = int(row_of_uslot[uj])
                    s, e = offsets[r], offsets[r + 1]
                    flips.append((match_slots[s:e], d))
            if flips:
                flip_slots = np.concatenate([f[0] for f in flips])
                flip_d = np.concatenate(
                    [np.full(len(f[0]), f[1], dtype=np.int64) for f in flips]
                )

        # mutate own-side state AFTER all probes/gathers that read it.
        # Retractions ALWAYS apply (rows arranged before the other side closed
        # must still evict, or they leak for the run's lifetime); only new
        # inserts are skipped under the frontier fast path.
        ret_rows = np.nonzero(diffs < 0)[0]
        if len(ret_rows):
            own.remove_batch(delta.keys[ret_rows])
        if not skip_arrange:
            ins_rows = np.nonzero(diffs > 0)[0]
            if len(ins_rows):
                own.insert_batch(
                    delta.keys[ins_rows],
                    jkeys[ins_rows],
                    {c: delta.columns[c][ins_rows] for c in own.names},
                )

        total = len(ev_row) + len(null_rows) + len(flip_slots)
        if total == 0:
            return None
        return self._emit_side(
            delta, side_name, other,
            ev_d, ev_row, ev_other,
            null_d, null_rows,
            flip_d, flip_slots,
            own_identity=own_identity
            and len(null_rows) == 0
            and len(flip_slots) == 0,
        )

    def _emit_side(
        self,
        delta: Delta,
        side_name: str,
        other: _JoinSide,
        ev_d: np.ndarray,
        ev_row: np.ndarray,
        ev_other: np.ndarray,
        null_d: np.ndarray,
        null_rows: np.ndarray,
        flip_d: np.ndarray,
        flip_slots: np.ndarray,
        own_identity: bool = False,
    ) -> Delta:
        """Assemble one side-pass's output: matched events, own-null rows, and
        other-side null-row flips, in that order. ``own_identity`` marks the
        unique-match inner pass where ``ev_row`` is the identity permutation:
        own-side gathers collapse to the delta's own arrays (no copy — delta
        columns are immutable once emitted, like every evaluator treats them)."""
        is_left = side_name == "left"
        left_table, right_table = self.node.inputs
        n_ev = len(ev_d) + len(null_d) + len(flip_d)
        n_m, n_nu = len(ev_d), len(null_d)

        # per-event row index into the delta (own side) / slot into other side; -1 null
        if n_nu == 0 and len(flip_d) == 0:
            # inner-match-only pass (the common case): no null segments to
            # splice — reuse the event arrays and a shared all-true mask
            own_rows = ev_row
            other_slots = ev_other
            out_d = ev_d
            own_mask = other_mask = np.ones(n_ev, dtype=bool)
        else:
            own_rows = np.concatenate(
                [ev_row, null_rows, np.full(len(flip_d), -1, dtype=np.int64)]
            )
            other_slots = np.concatenate(
                [ev_other, np.full(len(null_d), -1, dtype=np.int64), flip_slots]
            )
            out_d = np.concatenate([ev_d, null_d, flip_d])
            own_mask = own_rows >= 0
            other_mask = other_slots >= 0

        cache: Dict[str, np.ndarray] = {}

        def own_col(name: str) -> np.ndarray:
            key = "own:" + name
            if key not in cache:
                src = delta.columns[name]
                if own_identity:
                    out = src  # identity permutation: the delta's array as-is
                elif own_mask.all():
                    out = src[own_rows]
                else:
                    out = np.empty(n_ev, dtype=object)
                    out[own_mask] = src[own_rows[own_mask]]
                    out[~own_mask] = None
                cache[key] = out
            return cache[key]

        def other_col(name: str) -> np.ndarray:
            key = "other:" + name
            if key not in cache:
                src = other.cols[name]
                if other_mask.all():
                    out = src[other_slots]
                else:
                    out = np.empty(n_ev, dtype=object)
                    out[other_mask] = src[other_slots[other_mask]]
                    out[~other_mask] = None
                cache[key] = out
            return cache[key]

        def own_ids() -> np.ndarray:
            key = "own:id"
            if key not in cache:
                out = np.empty(n_ev, dtype=object)
                rows = np.nonzero(own_mask)[0]
                ptrs = keys_to_pointers(delta.keys[own_rows[rows]])
                for a, p in zip(rows, ptrs):
                    out[a] = p
                out[~own_mask] = None
                cache[key] = out
            return cache[key]

        def other_ids() -> np.ndarray:
            key = "other:id"
            if key not in cache:
                out = np.empty(n_ev, dtype=object)
                rows = np.nonzero(other_mask)[0]
                ptrs = keys_to_pointers(other.keys[other_slots[rows]])
                for a, p in zip(rows, ptrs):
                    out[a] = p
                out[~other_mask] = None
                cache[key] = out
            return cache[key]

        def resolver(ref: expr.ColumnReference) -> np.ndarray:
            own_side = (ref.table is left_table) == is_left
            if ref.table is not left_table and ref.table is not right_table:
                raise ValueError(f"join select references foreign table: {ref!r}")
            if ref.name == "id":
                return own_ids() if own_side else other_ids()
            return own_col(ref.name) if own_side else other_col(ref.name)

        exprs = self.node.config["exprs"]
        columns = {name: ee.evaluate(e, n_ev, resolver) for name, e in exprs.items()}

        # output keys: hash (left_key, right_key, "join"); id_expr overrides where
        # the left side is present
        if own_identity:
            own_keys = delta.keys
        else:
            own_keys = np.zeros(n_ev, dtype=KEY_DTYPE)
            own_keys[own_mask] = delta.keys[own_rows[own_mask]]
        oth_keys = np.zeros(n_ev, dtype=KEY_DTYPE)
        oth_keys[other_mask] = other.keys[other_slots[other_mask]]
        lkeys, lmask = (own_keys, own_mask) if is_left else (oth_keys, other_mask)
        rkeys, rmask = (oth_keys, other_mask) if is_left else (own_keys, own_mask)
        keys = combine_keys(lkeys, rkeys, lmask, rmask)
        id_expr = self.node.config.get("id_expr")
        if id_expr is not None and lmask.any():
            id_vals = ee.evaluate(id_expr, n_ev, resolver)
            for i in np.nonzero(lmask)[0]:
                p = id_vals[i]
                if isinstance(p, Pointer):
                    keys[i]["hi"], keys[i]["lo"] = p.hi, p.lo
        return Delta(keys, out_d, columns)


class UpdateRowsEvaluator(Evaluator):
    # base and patch relate rows BY ROW KEY: exchanging both means every key's
    # base/patch pair meets on its owner process (exact under spawn -n N)
    CLUSTER_POLICIES = {0: "rowkey", 1: "rowkey"}

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.base = StateTable(self.output_columns)
        self.patch = StateTable(self.output_columns)

    def process(self, input_deltas: List[Delta]) -> Delta:
        base_delta, patch_delta = input_deltas
        out_keys, out_diffs, out_rows = [], [], []

        for i in range(len(base_delta)):
            kb = base_delta.keys[i].tobytes()
            d = int(base_delta.diffs[i])
            row = {c: base_delta.columns[c][i] for c in self.output_columns}
            if self.patch.get_row(kb) is None:
                out_keys.append(base_delta.keys[i])
                out_diffs.append(d)
                out_rows.append(row)
        self.base.apply(base_delta)

        for i in range(len(patch_delta)):
            kb = patch_delta.keys[i].tobytes()
            d = int(patch_delta.diffs[i])
            row = {c: patch_delta.columns[c][i] for c in self.output_columns}
            base_row = self.base.get_row(kb)
            if d > 0:
                if base_row is not None and self.patch.get_row(kb) is None:
                    out_keys.append(patch_delta.keys[i])
                    out_diffs.append(-1)
                    out_rows.append(base_row)
                out_keys.append(patch_delta.keys[i])
                out_diffs.append(1)
                out_rows.append(row)
            else:
                out_keys.append(patch_delta.keys[i])
                out_diffs.append(-1)
                out_rows.append(row)
                if base_row is not None:
                    out_keys.append(patch_delta.keys[i])
                    out_diffs.append(1)
                    out_rows.append(base_row)
        self.patch.apply(patch_delta)

        return _delta_from_rows(
            out_keys, out_diffs, out_rows, self.output_columns
        ).consolidated()


class UpdateCellsEvaluator(Evaluator):
    CLUSTER_POLICIES = {0: "rowkey", 1: "rowkey"}

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        patch_cols = [
            c for c in node.inputs[1].column_names() if c in node.inputs[0].column_names()
        ]
        self.patch_cols = patch_cols
        self.base = StateTable(self.output_columns)
        self.patch = StateTable(patch_cols)

    def _merged(self, kb: bytes, base_row: dict) -> dict:
        patch_row = self.patch.get_row(kb)
        if patch_row is None:
            return base_row
        merged = dict(base_row)
        merged.update(patch_row)
        return merged

    def process(self, input_deltas: List[Delta]) -> Delta:
        base_delta, patch_delta = input_deltas
        out_keys, out_diffs, out_rows = [], [], []

        # patch first so base rows arriving same commit see it
        self.patch.apply(
            Delta(
                patch_delta.keys,
                patch_delta.diffs,
                {c: patch_delta.columns[c] for c in self.patch_cols},
            )
        )
        for i in range(len(base_delta)):
            kb = base_delta.keys[i].tobytes()
            row = {c: base_delta.columns[c][i] for c in self.output_columns}
            out_keys.append(base_delta.keys[i])
            out_diffs.append(int(base_delta.diffs[i]))
            out_rows.append(self._merged(kb, row))
        self.base.apply(base_delta)

        # patch changes for keys NOT in this commit's base delta
        seen = {base_delta.keys[i].tobytes() for i in range(len(base_delta))}
        handled: set[bytes] = set()
        for i in range(len(patch_delta)):
            kb = patch_delta.keys[i].tobytes()
            if kb in seen or kb in handled:
                continue
            handled.add(kb)
            base_row = self.base.get_row(kb)
            if base_row is None:
                continue
            # old merged (reconstruct patch state before this commit's patch delta)
            old_patch: dict | None = None
            for j in range(len(patch_delta)):
                if patch_delta.keys[j].tobytes() == kb and patch_delta.diffs[j] < 0:
                    old_patch = {c: patch_delta.columns[c][j] for c in self.patch_cols}
            old_row = dict(base_row)
            if old_patch is not None:
                old_row.update(old_patch)
            new_row = self._merged(kb, base_row)
            if old_row != new_row:
                out_keys.append(patch_delta.keys[i])
                out_diffs.append(-1)
                out_rows.append(old_row)
                out_keys.append(patch_delta.keys[i])
                out_diffs.append(1)
                out_rows.append(new_row)
        return _delta_from_rows(out_keys, out_diffs, out_rows, self.output_columns).consolidated()


class _KeyPresenceMixin(Evaluator):
    """Shared machinery for intersect/difference/restrict/having."""

    def cluster_input_policy(self, idx: int) -> str | None:
        # presence is tested key-by-key: co-partition every input by row key
        return "rowkey"

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.base = StateTable(self.output_columns)
        self.presence: List[set[bytes]] = [set() for _ in node.inputs[1:]]

    # -- elastic membership handoff: base rows and presence sets are both
    # keyed by the row key (every input is rowkey-exchanged), so they
    # partition exactly

    def reshard_check(self) -> "str | None":
        return None

    def reshard_export(self, owner_of: Any, new_n: int) -> Dict[int, Any]:
        out: Dict[int, Any] = {}

        def bucket(dest: int) -> dict:
            return out.setdefault(
                dest,
                {"base": None, "presence": [set() for _ in self.presence]},
            )

        for dest, part in self.base.reshard_partition(owner_of).items():
            bucket(dest)["base"] = part
        for idx, pres in enumerate(self.presence):
            for kb in pres:
                keys = np.frombuffer(kb, dtype=KEY_DTYPE)
                dest = int(np.asarray(owner_of(keys))[0])
                bucket(dest)["presence"][idx].add(kb)
        memo = Evaluator.reshard_export(self, owner_of, new_n)
        for dest, payload in memo.items():
            bucket(dest)["_udf_memo"] = payload["_udf_memo"]
        return out

    def reshard_import(self, payload: Any) -> None:
        part = payload.get("base")
        if part is not None:
            keys, diffs, columns = part
            self.base.apply(Delta(keys, diffs, columns))
        for idx, pres in enumerate(payload.get("presence", ())):
            if idx < len(self.presence):
                self.presence[idx] |= set(pres)
        Evaluator.reshard_import(self, payload)

    def _emit_row(self, kb: bytes, key: np.void, diff: int, row: dict, out: list) -> None:
        out.append((key, diff, row))

    def _condition(self, kb: bytes) -> bool:
        raise NotImplementedError

    def process(self, input_deltas: List[Delta]) -> Delta:
        base_delta = input_deltas[0]
        out: List[tuple] = []

        # update presence sets, recording transitions
        transitions: Dict[bytes, np.void] = {}
        for idx, delta in enumerate(input_deltas[1:]):
            for i in range(len(delta)):
                kb = delta.keys[i].tobytes()
                before = self._condition(kb)
                if delta.diffs[i] > 0:
                    self.presence[idx].add(kb)
                else:
                    self.presence[idx].discard(kb)
                after = self._condition(kb)
                if before != after:
                    transitions[kb] = delta.keys[i]

        for i in range(len(base_delta)):
            kb = base_delta.keys[i].tobytes()
            transitions.pop(kb, None)
        # base rows: emit if condition currently holds
        for i in range(len(base_delta)):
            kb = base_delta.keys[i].tobytes()
            if self._condition(kb):
                row = {c: base_delta.columns[c][i] for c in self.output_columns}
                out.append((base_delta.keys[i], int(base_delta.diffs[i]), row))
        self.base.apply(base_delta)

        for kb, key in transitions.items():
            row = self.base.get_row(kb)
            if row is None:
                continue
            diff = 1 if self._condition(kb) else -1
            out.append((key, diff, row))

        keys = [o[0] for o in out]
        diffs = [o[1] for o in out]
        rows = [o[2] for o in out]
        return _delta_from_rows(keys, diffs, rows, self.output_columns)


class IntersectEvaluator(_KeyPresenceMixin):
    def _condition(self, kb: bytes) -> bool:
        return all(kb in p for p in self.presence)


class DifferenceEvaluator(_KeyPresenceMixin):
    def _condition(self, kb: bytes) -> bool:
        return kb not in self.presence[0]


class RestrictEvaluator(_KeyPresenceMixin):
    def _condition(self, kb: bytes) -> bool:
        return kb in self.presence[0]


class HavingEvaluator(Evaluator):
    """Keep base rows whose key appears among the indexer pointer column's values."""

    _NON_STATE_ATTRS = Evaluator._NON_STATE_ATTRS + ("indexers",)

    # custom routes carry the base ROW KEY itself (the pointer value each
    # indexer row asserts), so state partitions exactly under shard_of(row key)
    RESHARD_ROUTE_BYKEY = True

    def cluster_input_policy(self, idx: int) -> str | None:
        # indexer rows route by the POINTER VALUE they carry (the key whose
        # presence they assert), meeting the base row they reference
        return "rowkey" if idx == 0 else "custom"

    def cluster_route_keys(self, idx: int, delta: Delta) -> np.ndarray:
        vals = delta.columns[self.indexers[idx - 1].name]
        out = delta.keys.copy()  # non-pointer cells: route arbitrarily (ignored)
        for i in range(len(delta)):
            if isinstance(vals[i], Pointer):
                out[i] = pointers_to_keys([vals[i]])[0]
        return out

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.base = StateTable(self.output_columns)
        self.indexers: List[expr.ColumnReference] = node.config["indexers"]
        self.counts: List[Dict[bytes, int]] = [defaultdict(int) for _ in self.indexers]

    def _condition(self, kb: bytes) -> bool:
        return all(c.get(kb, 0) > 0 for c in self.counts)

    def process(self, input_deltas: List[Delta]) -> Delta:
        base_delta = input_deltas[0]
        out: List[tuple] = []
        transitions: Dict[bytes, np.void] = {}
        for idx, delta in enumerate(input_deltas[1:]):
            ref = self.indexers[idx]
            if len(delta) == 0:
                continue
            vals = delta.columns[ref.name]
            for i in range(len(delta)):
                p = vals[i]
                if not isinstance(p, Pointer):
                    continue
                kb = pointers_to_keys([p]).tobytes()
                before = self._condition(kb)
                self.counts[idx][kb] += int(delta.diffs[i])
                after = self._condition(kb)
                if before != after:
                    transitions[kb] = pointers_to_keys([p])[0]

        for i in range(len(base_delta)):
            kb = base_delta.keys[i].tobytes()
            transitions.pop(kb, None)
            if self._condition(kb):
                row = {c: base_delta.columns[c][i] for c in self.output_columns}
                out.append((base_delta.keys[i], int(base_delta.diffs[i]), row))
        self.base.apply(base_delta)

        for kb, key in transitions.items():
            row = self.base.get_row(kb)
            if row is None:
                continue
            diff = 1 if self._condition(kb) else -1
            out.append((key, diff, row))
        return _delta_from_rows(
            [o[0] for o in out], [o[1] for o in out], [o[2] for o in out], self.output_columns
        )

    # -- elastic membership handoff: base rows and indexer reference counts
    # are both keyed by the base row key, so they partition exactly

    def reshard_check(self) -> "str | None":
        return None

    def reshard_export(self, owner_of: Any, new_n: int) -> Dict[int, Any]:
        out: Dict[int, Any] = {}

        def bucket(dest: int) -> dict:
            return out.setdefault(
                dest,
                {"base": None, "counts": [dict() for _ in self.indexers]},
            )

        for dest, part in self.base.reshard_partition(owner_of).items():
            bucket(dest)["base"] = part
        for idx, cnt in enumerate(self.counts):
            for kb, c in cnt.items():
                if not c:
                    continue
                keys = np.frombuffer(kb, dtype=KEY_DTYPE)
                dest = int(np.asarray(owner_of(keys))[0])
                bucket(dest)["counts"][idx][kb] = c
        memo = Evaluator.reshard_export(self, owner_of, new_n)
        for dest, payload in memo.items():
            bucket(dest)["_udf_memo"] = payload["_udf_memo"]
        return out

    def reshard_import(self, payload: Any) -> None:
        part = payload.get("base")
        if part is not None:
            keys, diffs, columns = part
            self.base.apply(Delta(keys, diffs, columns))
        for idx, cnt in enumerate(payload.get("counts", ())):
            if idx < len(self.counts):
                for kb, c in cnt.items():
                    self.counts[idx][kb] += c
        Evaluator.reshard_import(self, payload)


class WithUniverseOfEvaluator(Evaluator):
    """Runtime enforcement of the promised universe equality (the reference's
    engine rekeys onto the other universe and fails on mismatch; here both key
    sets are tracked and verified once the stream is final)."""

    CLUSTER_POLICIES = {0: "rowkey", 1: "rowkey"}

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        from pathway_tpu.engine.index import KeyIndex

        self.self_keys = KeyIndex()
        self.other_keys = KeyIndex()

    def process(self, input_deltas: List[Delta]) -> Delta:
        self_delta, other_delta = input_deltas
        for delta, idx in ((self_delta, self.self_keys), (other_delta, self.other_keys)):
            if not len(delta):
                continue
            # removals first: an in-place update (-1 old, +1 new on one key in one
            # delta) must leave the key PRESENT regardless of row order
            ins = delta.diffs > 0
            if (~ins).any():
                idx.remove(delta.keys[~ins])
            if ins.any():
                idx.upsert(delta.keys[ins])
        return self_delta

    def verify_universes(self) -> None:
        """Called at stream end: the promised key-set equality must actually hold."""
        from pathway_tpu.internals.keys import keys_to_pointers

        a_keys, _ = self.self_keys.items()
        b_keys, _ = self.other_keys.items()
        only_a = self.other_keys.lookup(a_keys) < 0 if len(a_keys) else np.zeros(0, bool)
        only_b = self.self_keys.lookup(b_keys) < 0 if len(b_keys) else np.zeros(0, bool)
        if only_a.any() or only_b.any():
            sample_a = keys_to_pointers(a_keys[only_a][:3]) if only_a.any() else []
            sample_b = keys_to_pointers(b_keys[only_b][:3]) if only_b.any() else []
            raise RuntimeError(
                "with_universe_of: promised universe equality violated at runtime — "
                f"{int(only_a.sum())} key(s) only in the table (e.g. {sample_a}), "
                f"{int(only_b.sum())} only in the other (e.g. {sample_b})"
            )


class FlattenEvaluator(_DerivedKeyMixin):
    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        flat_name = self.node.config["flat_name"]
        origin_id = self.node.config.get("origin_id")
        out_keys, out_diffs, out_rows, in_idx = [], [], [], []
        ptrs = keys_to_pointers(delta.keys)
        for i in range(len(delta)):
            value = delta.columns[flat_name][i]
            items = _iter_flatten(value)
            for j, item in enumerate(items):
                row = {c: delta.columns[c][i] for c in delta.column_names}
                row[flat_name] = item
                if origin_id:
                    row[origin_id] = ptrs[i]
                out_keys.append(pointer_from(ptrs[i], j, "flatten"))
                out_diffs.append(int(delta.diffs[i]))
                out_rows.append(row)
                in_idx.append(i)
        keys = pointers_to_keys(out_keys) if out_keys else []
        if len(keys):
            self._track_prov(keys, delta.keys[np.asarray(in_idx, dtype=np.int64)])
        return _delta_from_rows(keys, out_diffs, out_rows, self.output_columns)


def _iter_flatten(value: Any) -> list:
    from pathway_tpu.internals.json import Json

    if isinstance(value, Json):
        return [Json(v) if isinstance(v, (dict, list)) else v for v in value.value]
    if isinstance(value, (list, tuple)):
        return list(value)
    if isinstance(value, np.ndarray):
        return list(value)
    if isinstance(value, str):
        return list(value)
    raise TypeError(f"cannot flatten value of type {type(value).__name__}")


class IxEvaluator(Evaluator):
    """source-keyed lookup into target (reference ``ix``/``ix_ref``).

    Multi-process: the TARGET side replicates (broadcast) into a private state
    replica, so a lookup of any pointer answers locally wherever the source row
    lives — the same replicated-state pattern as the external index. Source
    rows (and therefore output rows) stay where they were produced."""

    CLUSTER_POLICIES = {1: "broadcast"}

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.src_keys: Dict[bytes, bytes] = {}  # source key -> target key
        self.reverse: Dict[bytes, set[bytes]] = defaultdict(set)
        self.src_rows: Dict[bytes, np.void] = {}
        self.emitted: Dict[bytes, dict] = {}  # source key -> last emitted output row
        self._replica: Any = (
            StateTable(node.inputs[1].column_names())
            if getattr(runner, "_cluster", None) is not None
            else None
        )

    def process(self, input_deltas: List[Delta]) -> Delta:
        source_delta, target_delta = input_deltas
        source_table, target_table = self.node.inputs
        optional = self.node.config.get("optional", False)
        if self._replica is not None:
            # broadcast target deltas feed the replica BEFORE lookups, matching
            # the single-process ordering (target materializes before ix runs)
            self._replica.apply(target_delta)
            target_state = self._replica
        else:
            target_state = self.runner.state_of(target_table._node)
        out_keys, out_diffs, out_rows = [], [], []

        handled_sources: set[bytes] = set()
        if len(source_delta):
            resolver = self._resolver_for(source_table, source_delta)
            ixptrs = ee.evaluate(
                self.node.config["key_expression"], len(source_delta), resolver
            )
            for i in range(len(source_delta)):
                skb = source_delta.keys[i].tobytes()
                handled_sources.add(skb)
                d = int(source_delta.diffs[i])
                p = ixptrs[i]
                tkb = pointers_to_keys([p]).tobytes() if isinstance(p, Pointer) else None
                if d > 0:
                    self.src_keys[skb] = tkb
                    self.src_rows[skb] = source_delta.keys[i]
                    if tkb is not None:
                        self.reverse[tkb].add(skb)
                    row = None if tkb is None else target_state.get_row(tkb)
                    if row is None:
                        if not optional and tkb is not None:
                            raise KeyError(f"ix: missing key {p!r} in target table")
                        row = {c: None for c in self.output_columns}
                    self.emitted[skb] = row
                else:
                    self.src_keys.pop(skb, None)
                    self.src_rows.pop(skb, None)
                    if tkb is not None:
                        self.reverse[tkb].discard(skb)
                    # retraction replays what was last emitted, regardless of target state
                    row = self.emitted.pop(skb, {c: None for c in self.output_columns})
                out_keys.append(source_delta.keys[i])
                out_diffs.append(d)
                out_rows.append(row)

        # target-side changes re-emit affected source rows, preserving row-per-key:
        # optional sources flip between the real row and an all-None row
        none_row = {c: None for c in self.output_columns}
        for i in range(len(target_delta)):
            tkb = target_delta.keys[i].tobytes()
            d = int(target_delta.diffs[i])
            row = {c: target_delta.columns[c][i] for c in self.output_columns}
            for skb in self.reverse.get(tkb, set()):
                if skb in handled_sources:
                    continue
                prev = self.emitted.get(skb)
                if d > 0:
                    if prev is not None:
                        out_keys.append(self.src_rows[skb])
                        out_diffs.append(-1)
                        out_rows.append(prev)
                    out_keys.append(self.src_rows[skb])
                    out_diffs.append(1)
                    out_rows.append(row)
                    self.emitted[skb] = row
                else:
                    out_keys.append(self.src_rows[skb])
                    out_diffs.append(-1)
                    out_rows.append(prev if prev is not None else row)
                    if optional:
                        out_keys.append(self.src_rows[skb])
                        out_diffs.append(1)
                        out_rows.append(none_row)
                        self.emitted[skb] = none_row
                    else:
                        self.emitted.pop(skb, None)
        return _delta_from_rows(
            out_keys, out_diffs, out_rows, self.output_columns
        ).consolidated()


class SortEvaluator(Evaluator):
    """prev/next pointers per instance (reference ``prev_next.rs:770``)."""

    # global per-instance ordering: centralize on process 0 (the reference routes
    # such operators to one worker, ``time_column.rs:48-51``)
    CLUSTER_POLICIES = {0: "root"}

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.rows: Dict[bytes, tuple] = {}  # key -> (sort_val, instance, Pointer)
        self.emitted: Dict[bytes, tuple] = {}  # key -> (prev, next)

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        table = self.node.inputs[0]
        resolver = self._resolver_for(table, delta)
        n = len(delta)
        keys_vals = ee.evaluate(self.node.config["key"], n, resolver)
        instance_e = self.node.config.get("instance")
        instances = (
            ee.evaluate(instance_e, n, resolver) if instance_e is not None else np.zeros(n, dtype=object)
        )
        ptrs = keys_to_pointers(delta.keys)
        touched_instances = set()
        for i in range(n):
            kb = delta.keys[i].tobytes()
            if delta.diffs[i] > 0:
                self.rows[kb] = (keys_vals[i], instances[i], ptrs[i], delta.keys[i])
            else:
                self.rows.pop(kb, None)
            touched_instances.add(_hashable_scalar(instances[i]))

        # recompute orders for touched instances
        out_keys, out_diffs, out_rows = [], [], []
        by_instance: Dict[Any, list] = defaultdict(list)
        for kb, (sv, inst, ptr, key) in self.rows.items():
            hi = _hashable_scalar(inst)
            if hi in touched_instances:
                by_instance[hi].append((sv, ptr, kb, key))
        new_links: Dict[bytes, tuple] = {}
        for inst, rows in by_instance.items():
            rows.sort(key=lambda r: (r[0], r[1]))
            for idx, (sv, ptr, kb, key) in enumerate(rows):
                prev_ptr = rows[idx - 1][1] if idx > 0 else None
                next_ptr = rows[idx + 1][1] if idx < len(rows) - 1 else None
                new_links[kb] = (prev_ptr, next_ptr, key)
        # diff against emitted
        for kb, (pv, nv) in list(self.emitted.items()):
            if kb not in self.rows:
                # row gone: retract
                out_keys.append(self._key_of(kb))
                out_diffs.append(-1)
                out_rows.append({"prev": pv, "next": nv})
                del self.emitted[kb]
        for kb, (pv, nv, key) in new_links.items():
            old = self.emitted.get(kb)
            if old == (pv, nv):
                continue
            if old is not None:
                out_keys.append(key)
                out_diffs.append(-1)
                out_rows.append({"prev": old[0], "next": old[1]})
            out_keys.append(key)
            out_diffs.append(1)
            out_rows.append({"prev": pv, "next": nv})
            self.emitted[kb] = (pv, nv)
        return _delta_from_rows(out_keys, out_diffs, out_rows, self.output_columns)

    def _key_of(self, kb: bytes) -> np.void:
        arr = np.frombuffer(kb, dtype=KEY_DTYPE)
        return arr[0]


def _hashable_scalar(v: Any) -> Any:
    if isinstance(v, np.ndarray):
        return (v.tobytes(), v.shape)
    return v


class SortedIndexEvaluator(Evaluator):
    """Sorted binary tree per instance (reference ``stdlib/indexing/sorting.py:92``).

    The reference grows a treap through ``pw.iterate`` rounds of ix/groupby; here
    the engine holds each instance's rows sorted and rebuilds the tree for touched
    instances per commit as a CARTESIAN TREE (one O(n) stack pass): in-order =
    key order, heap order = per-row priority. Priorities are the rows' xxh3 key
    fingerprints — deterministic, uniform, independent of arrival order, matching
    the reference's hash-as-priority treap shape."""

    CLUSTER_POLICIES = {0: "root"}  # global per-instance ordering, like sort

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.rows: Dict[bytes, tuple] = {}  # kb -> (sort_val, instance, ptr, key)
        self.emitted: Dict[bytes, tuple] = {}  # kb -> row tuple
        # per-instance membership so a commit touches only its instances'
        # rows, not the whole table (incrementality)
        self.members: Dict[Any, Dict[bytes, tuple]] = defaultdict(dict)

    @staticmethod
    def _tree_links(ordered: List[tuple]) -> List[tuple]:
        """(left, right, parent) per position for the cartesian tree of
        ``ordered`` = [(priority, ptr), ...] in key order; min-priority root."""
        n = len(ordered)
        left = [None] * n
        right = [None] * n
        parent = [None] * n
        stack: List[int] = []
        for i in range(n):
            dethroned = None
            while stack and ordered[stack[-1]][0] > ordered[i][0]:
                dethroned = stack.pop()
            if dethroned is not None:
                left[i] = ordered[dethroned][1]
                parent[dethroned] = ordered[i][1]
            if stack:
                right[stack[-1]] = ordered[i][1]
                parent[i] = ordered[stack[-1]][1]
            stack.append(i)
        return list(zip(left, right, parent))

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        table = self.node.inputs[0]
        resolver = self._resolver_for(table, delta)
        n = len(delta)
        keys_vals = ee.evaluate(self.node.config["key"], n, resolver)
        instance_e = self.node.config.get("instance")
        instances = (
            ee.evaluate(instance_e, n, resolver)
            if instance_e is not None
            else np.zeros(n, dtype=object)
        )
        ptrs = keys_to_pointers(delta.keys)
        touched = set()
        for i in range(n):
            kb = delta.keys[i].tobytes()
            old = self.rows.get(kb)
            if old is not None:
                self.members[_hashable_scalar(old[1])].pop(kb, None)
                touched.add(_hashable_scalar(old[1]))
            if delta.diffs[i] > 0:
                entry = (keys_vals[i], instances[i], ptrs[i], delta.keys[i])
                self.rows[kb] = entry
                self.members[_hashable_scalar(instances[i])][kb] = entry
            else:
                self.rows.pop(kb, None)
            touched.add(_hashable_scalar(instances[i]))

        fresh: Dict[bytes, tuple] = {}
        for hi in touched:
            members = [
                (sv, ptr, kb, key, inst)
                for kb, (sv, inst, ptr, key) in self.members.get(hi, {}).items()
            ]
            members.sort(key=lambda r: (r[0], r[1]))
            # priority = xxh3 fingerprint already inside the row key (lo word)
            links = self._tree_links(
                [(np.frombuffer(kb, dtype=KEY_DTYPE)[0]["lo"].item(), ptr) for _sv, ptr, kb, _k, _i in members]
            )
            for (sv, ptr, kb, key, inst), (lf, rt, par) in zip(members, links):
                fresh[kb] = (key, {"key": sv, "left": lf, "right": rt, "parent": par, "instance": inst})

        out_keys, out_diffs, out_rows = [], [], []
        # removals come from the delta's negative rows, not a full emitted scan
        for i in range(n):
            if delta.diffs[i] >= 0:
                continue
            kb = delta.keys[i].tobytes()
            if kb in self.rows:
                continue  # replaced within this commit, not removed
            old_row = self.emitted.pop(kb, None)
            if old_row is not None:
                out_keys.append(delta.keys[i])
                out_diffs.append(-1)
                out_rows.append(old_row)
        for kb, (key, row) in fresh.items():
            old = self.emitted.get(kb)
            if old == row:
                continue
            if old is not None:
                out_keys.append(key)
                out_diffs.append(-1)
                out_rows.append(old)
            out_keys.append(key)
            out_diffs.append(1)
            out_rows.append(row)
            self.emitted[kb] = row
        return _delta_from_rows(out_keys, out_diffs, out_rows, self.output_columns)


class RemoveErrorsEvaluator(Evaluator):
    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return delta
        mask = np.ones(len(delta), dtype=bool)
        for col in delta.columns.values():
            if col.dtype == object:
                mask &= ~np.frompyfunc(lambda v: isinstance(v, Error), 1, 1)(col).astype(bool)
        return delta.select(mask)


class AsofNowEvaluator(Evaluator):
    """``_forget_immediately`` / ``_filter_out_results_of_forgetting``.

    Forget mode passes each commit's rows through unchanged and schedules a retraction of
    every insert; the runner drains those in the same commit's *neu* phase (the
    reference's odd-time forgetting, ``dataflow.rs:3447``): downstream state shrinks, but
    the forgetting filter drops neu deltas so delivered results stay. An upstream
    retraction of a still-scheduled key cancels the schedule (no double retraction).
    """

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.pending: Dict[bytes, tuple] = {}  # kb -> (key, row)

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        mode = self.node.config["mode"]
        if mode == "filter_forgotten":
            if delta.neu:
                return Delta.empty(self.output_columns)
            return delta
        # forget mode
        for i in range(len(delta)):
            kb = delta.keys[i].tobytes()
            if delta.diffs[i] > 0:
                self.pending[kb] = (
                    delta.keys[i],
                    {c: delta.columns[c][i] for c in delta.column_names},
                )
            else:
                # genuine upstream retraction passes through; cancel the scheduled one
                self.pending.pop(kb, None)
        return delta

    def neu_pending(self) -> bool:
        return self.node.config["mode"] == "forget" and bool(self.pending)

    def drain_neu(self, input_deltas: List[Delta]) -> Delta:
        parts = []
        if self.pending:
            keys = [p[0] for p in self.pending.values()]
            rows = [p[1] for p in self.pending.values()]
            self.pending = {}
            parts.append(
                _delta_from_rows(keys, [-1] * len(keys), rows, self.output_columns)
            )
        if any(len(d) for d in input_deltas):
            parts.append(self.process(input_deltas))
        return Delta.concat(parts, self.output_columns)

    def has_pending(self) -> bool:
        return bool(self.pending)


class _TimeThresholdEvaluator(Evaluator):
    """Shared machinery for buffer/forget/freeze (reference ``time_column.rs``).

    Tracks ``now`` = the max value of the time column observed so far; a row is *ripe*
    once its threshold column value is ≤ ``now`` (the commit-granularity stand-in for
    the reference's frontier comparison). Ripeness scans use a min-heap on threshold so
    each commit pops only the ripe prefix (no full rescan of buffered state).
    """

    # ``now`` is a GLOBAL watermark (max time over the whole stream): centralize
    # on process 0, as the reference does for time-column operators
    # (``time_column.rs:48-51`` — "we need to process all data in one worker")
    CLUSTER_POLICIES = {0: "root"}

    # drain-sensitive: these operators flush on ``runner.draining``, a
    # live-only signal that a rejoining rank's journal replay does not
    # reproduce (``_ready`` is forced False during replay) — a rung-1 survivor
    # keeping post-flush state while the replacement replays without the flush
    # would diverge per rank, so graphs holding one skip the rewind rung
    REWIND_SAFE = False

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.now: Any = None
        self._heap: List[tuple] = []  # (threshold, seq, kb)
        self._heap_seq = 0

    def _thresholds_times(self, delta: Delta) -> Tuple[np.ndarray, np.ndarray]:
        table = self.node.inputs[0]
        resolver = self._resolver_for(table, delta)
        n = len(delta)
        thr = ee.evaluate(self.node.config["threshold"], n, resolver)
        tim = ee.evaluate(self.node.config["time"], n, resolver)
        return thr, tim

    def _advance_now(self, tim: np.ndarray, diffs: np.ndarray) -> None:
        for i in range(len(tim)):
            if diffs[i] > 0 and tim[i] is not None:
                if self.now is None or tim[i] > self.now:
                    self.now = tim[i]

    def _ripe(self, threshold: Any) -> bool:
        return self.now is not None and threshold <= self.now

    def _heap_push(self, threshold: Any, kb: bytes) -> None:
        import heapq

        heapq.heappush(self._heap, (threshold, self._heap_seq, kb))
        self._heap_seq += 1

    def _heap_pop_ripe(self, *, all_: bool = False):
        """Yield (threshold, kb) for entries whose threshold passed ``now`` (or all,
        when draining). Entries are lazily validated by the caller."""
        import heapq

        while self._heap and (all_ or self._ripe(self._heap[0][0])):
            threshold, _, kb = heapq.heappop(self._heap)
            yield threshold, kb


class BufferEvaluator(_TimeThresholdEvaluator):
    """Postpone emission until the stream's time passes each row's threshold
    (reference ``TimeColumnBuffer`` / ``postpone_core``, ``time_column.rs:255,380``).
    At stream close every buffered row flushes, as when the frontier empties."""

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        # kb -> [key, row, threshold, accumulated diff]
        self.pending: Dict[bytes, list] = {}
        self.emitted: set[bytes] = set()

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        out_keys: List[Any] = []
        out_diffs: List[int] = []
        out_rows: List[dict] = []
        if len(delta):
            thr, tim = self._thresholds_times(delta)
            self._advance_now(tim, delta.diffs)
            for i in range(len(delta)):
                kb = delta.keys[i].tobytes()
                d = int(delta.diffs[i])
                row = {c: delta.columns[c][i] for c in delta.column_names}
                if d < 0 and kb in self.emitted:
                    # retraction of an already-emitted row passes straight through
                    out_keys.append(delta.keys[i])
                    out_diffs.append(-1)
                    out_rows.append(row)
                    self.emitted.discard(kb)
                    continue
                cur = self.pending.get(kb)
                if cur is None:
                    self.pending[kb] = [delta.keys[i], row, thr[i], d]
                    self._heap_push(thr[i], kb)
                else:
                    cur[3] += d
                    if d > 0:
                        cur[1] = row
                        if cur[2] != thr[i]:
                            cur[2] = thr[i]
                            self._heap_push(thr[i], kb)
                    if cur[3] == 0:
                        del self.pending[kb]
        draining = getattr(self.runner, "draining", False)
        for threshold, kb in self._heap_pop_ripe(all_=draining):
            cur = self.pending.get(kb)
            if cur is None or cur[2] != threshold:
                continue  # stale heap entry (row cancelled or rescheduled)
            del self.pending[kb]
            key, row, _, acc = cur
            if acc == 0:
                continue
            out_keys.append(key)
            out_diffs.append(acc)
            out_rows.append(row)
            if acc > 0:
                self.emitted.add(kb)
        return _delta_from_rows(
            out_keys, out_diffs, out_rows, self.output_columns
        ).consolidated()

    def has_pending(self) -> bool:
        return bool(self.pending)


class FreezeEvaluator(_TimeThresholdEvaluator):
    """Drop late rows — updates arriving after the stream's time passed their threshold
    (reference ``TimeColumnFreeze`` / ``ignore_late``, ``time_column.rs:631,677``)."""

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        thr, tim = self._thresholds_times(delta)
        mask = np.ones(len(delta), dtype=bool)
        for i in range(len(delta)):
            if self._ripe(thr[i]):
                mask[i] = False
        self._advance_now(tim, delta.diffs)
        return delta.select(mask)


class ForgetEvaluator(_TimeThresholdEvaluator):
    """Retract rows once the stream's time passes their threshold (reference
    ``TimeColumnForget``, ``time_column.rs:556``). The retractions drain in the same
    commit's *neu* phase; with keep_results=True a downstream forgetting filter drops
    them so state is bounded but delivered results stay, and with keep_results=False
    there is no filter, so results are genuinely removed."""

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.live: Dict[bytes, tuple] = {}  # kb -> (key, row, threshold)
        self.pending_forget: Dict[bytes, tuple] = {}  # kb -> (key, row)

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if len(delta) == 0:
            return Delta.empty(self.output_columns)
        thr, tim = self._thresholds_times(delta)
        self._advance_now(tim, delta.diffs)
        for i in range(len(delta)):
            kb = delta.keys[i].tobytes()
            if delta.diffs[i] > 0:
                row = {c: delta.columns[c][i] for c in delta.column_names}
                self.live[kb] = (delta.keys[i], row, thr[i])
                self._heap_push(thr[i], kb)
            else:
                # genuine upstream retraction: cancel any scheduled forgetting
                self.live.pop(kb, None)
                self.pending_forget.pop(kb, None)
        for threshold, kb in self._heap_pop_ripe():
            cur = self.live.get(kb)
            if cur is None or cur[2] != threshold:
                continue  # stale heap entry
            del self.live[kb]
            self.pending_forget[kb] = (cur[0], cur[1])
        return delta

    def neu_pending(self) -> bool:
        return bool(self.pending_forget)

    def drain_neu(self, input_deltas: List[Delta]) -> Delta:
        parts = []
        if self.pending_forget:
            keys = [p[0] for p in self.pending_forget.values()]
            rows = [p[1] for p in self.pending_forget.values()]
            self.pending_forget = {}
            parts.append(
                _delta_from_rows(keys, [-1] * len(keys), rows, self.output_columns)
            )
        if any(len(d) for d in input_deltas):
            parts.append(self.process(input_deltas))
        return Delta.concat(parts, self.output_columns)

    def has_pending(self) -> bool:
        return bool(self.pending_forget)


class ExternalIndexEvaluator(Evaluator):
    """External index operator (reference ``external_index.rs:38``).

    In as-of-now mode (the default, reference ``use_external_index_as_of_now``) a query is
    answered once against the index state at arrival and never revisited. With
    ``asof_now=False`` live queries are *re-answered* whenever the index changes: the old
    reply is retracted and the fresh one emitted (reference full differential semantics of
    ``DataIndex.query``)."""

    # the data side replicates to every process (each holds the FULL index);
    # queries stay local and answer exactly against the replicated state —
    # the replicated-index pattern (queries never cross processes)
    CLUSTER_POLICIES = {0: "broadcast"}
    # the index mutates in place (possibly device-resident pages); pickling it
    # every commit for an undo record would dwarf the tail replay it avoids
    REWIND_SAFE = False
    # checkpoints cannot capture the device-resident index either: a restore
    # rebuilds it only through journal replay (PWA005 flags this under
    # persistence so the weaker recovery contract is visible at build time)
    SNAPSHOT_CAPTURE = False

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.index = node.config["index_factory"].make_instance()
        self.replies = StateTable(["_pw_index_reply"])
        self.asof_now: bool = bool(self.node.config.get("asof_now", True))
        # kb -> (key, qvec, limit, filter) for re-answering mode
        self.live_queries: Dict[bytes, tuple] = {}

    # -- elastic membership: the rebuildable-descriptor contract -------------
    #
    # The index's data side is broadcast (every rank holds the FULL index),
    # so its content is identical everywhere and a membership transition can
    # replicate it to the new topology from ONE export instead of refusing —
    # when the index can export one. The query side (replies, live_queries)
    # is keyed by query row key and partitions like any keyed state.

    def rebuild_supported(self) -> bool:
        """True when the backing index exports a rebuildable descriptor
        (keys + host vectors + filter data) — the membership preflight's
        alternative to the blanket device-resident refusal."""
        index = self.index
        return (
            getattr(index, "rebuild_descriptor", None) is not None
            and getattr(getattr(index, "store", None), "export_rows", None)
            is not None
        )

    def rebuild_descriptor(self) -> "Any | None":
        if not self.rebuild_supported():
            return None
        return self.index.rebuild_descriptor()

    def install_rebuild_descriptor(self, desc: Any) -> None:
        if desc is not None:
            self.index.install_rebuild_descriptor(desc)

    def reshard_check(self) -> "str | None":
        if self.rebuild_supported():
            return None
        return (
            "external index state lives outside the snapshot protocol "
            "(device-resident) and this index type exports no rebuildable "
            "descriptor"
        )

    def reshard_export(self, owner_of: Any, new_n: int) -> Dict[int, Any]:
        """Partition the QUERY-side state by row key (the index content
        itself rides the replicated descriptor, not the keyed export)."""
        from pathway_tpu.internals.keys import KEY_DTYPE

        out: Dict[int, Any] = {}
        for dest, part in self.replies.reshard_partition(owner_of).items():
            out.setdefault(dest, {})["replies"] = part
        for kb, (key, qvec, limit, flt) in self.live_queries.items():
            keys = np.frombuffer(kb, dtype=KEY_DTYPE)
            dest = int(np.asarray(owner_of(keys))[0])
            out.setdefault(dest, {}).setdefault("live_queries", {})[kb] = (
                key, _to_host(qvec), limit, flt,
            )
        return out

    def reshard_import(self, payload: Any) -> None:
        payload = payload or {}
        part = payload.get("replies")
        if part is not None:
            keys, diffs, columns = part
            if len(keys):
                self.replies.apply(Delta(keys, diffs, columns))
        self.live_queries.update(payload.get("live_queries", {}))

    def _search_batch(
        self, vecs: List[Any], limits: List[int], filters: List[Any]
    ) -> List[List[tuple]]:
        if not vecs:
            return []
        if hasattr(self.index, "search_many"):
            return self.index.search_many(vecs, limits, filters)
        return [
            self.index.search(v, l, f) for v, l, f in zip(vecs, limits, filters)
        ]

    def process(self, input_deltas: List[Delta]) -> Delta:
        index_delta, query_delta = input_deltas
        index_table, query_table = self.node.inputs
        index_changed = len(index_delta) > 0

        if len(index_delta):
            resolver = self._resolver_for(index_table, index_delta)
            vec_ref = self.node.config["index_column"]
            vectors = self._eval_expr(vec_ref, index_delta, resolver)
            filter_col = self.node.config.get("index_filter_data_column")
            filters = (
                self._eval_expr(filter_col, index_delta, resolver)
                if filter_col is not None
                else None
            )
            ptrs = keys_to_pointers(index_delta.keys)
            add_mask = index_delta.diffs > 0
            bulk_add = getattr(self.index, "add_many", None)
            if bulk_add is not None and add_mask.all():
                # pure-insert commit: one staged batch + one capacity jump
                bulk_add(
                    ptrs,
                    list(vectors),
                    list(filters) if filters is not None else None,
                )
            else:
                for i in range(len(index_delta)):
                    if add_mask[i]:
                        self.index.add(
                            ptrs[i], vectors[i], filters[i] if filters is not None else None
                        )
                    else:
                        self.index.remove(ptrs[i])

        out_keys, out_diffs, out_rows = [], [], []
        if len(query_delta):
            resolver = self._resolver_for(query_table, query_delta)
            qvecs = self._eval_expr(
                self.node.config["query_column"], query_delta, resolver
            )
            limit_col = self.node.config.get("query_responses_limit_column")
            limits = (
                self._eval_expr(limit_col, query_delta, resolver)
                if limit_col is not None
                else None
            )
            qfilter_col = self.node.config.get("query_filter_column")
            qfilters = (
                self._eval_expr(qfilter_col, query_delta, resolver)
                if qfilter_col is not None
                else None
            )
            q_kbs = key_bytes(query_delta.keys)
            ins = [i for i in range(len(query_delta)) if query_delta.diffs[i] > 0]
            ins_replies = self._search_batch(
                [qvecs[i] for i in ins],
                [int(limits[i]) if limits is not None else 1 for i in ins],
                [qfilters[i] if qfilters is not None else None for i in ins],
            )
            reply_of = dict(zip(ins, ins_replies))
            for i in range(len(query_delta)):
                kb = q_kbs[i]
                if query_delta.diffs[i] > 0:
                    limit = int(limits[i]) if limits is not None else 1
                    flt = qfilters[i] if qfilters is not None else None
                    reply = tuple(reply_of[i])
                    out_keys.append(query_delta.keys[i])
                    out_diffs.append(1)
                    out_rows.append({"_pw_index_reply": reply})
                    if not self.asof_now:
                        self.live_queries[kb] = (
                            query_delta.keys[i],
                            qvecs[i],
                            limit,
                            flt,
                        )
                else:
                    self.live_queries.pop(kb, None)
                    stored = self.replies.get_row(kb)
                    if stored is not None:
                        out_keys.append(query_delta.keys[i])
                        out_diffs.append(-1)
                        out_rows.append(stored)

        if not self.asof_now and index_changed and self.live_queries:
            answered = set(key_bytes(query_delta.keys))
            live = [
                (kb, entry)
                for kb, entry in self.live_queries.items()
                if kb not in answered
            ]
            live_replies = self._search_batch(
                [entry[1] for _, entry in live],
                [entry[2] for _, entry in live],
                [entry[3] for _, entry in live],
            )
            for (kb, (key, qvec, limit, flt)), matches in zip(live, live_replies):
                reply = tuple(matches)
                stored = self.replies.get_row(kb)
                if stored is not None and stored["_pw_index_reply"] == reply:
                    continue
                if stored is not None:
                    out_keys.append(key)
                    out_diffs.append(-1)
                    out_rows.append(stored)
                out_keys.append(key)
                out_diffs.append(1)
                out_rows.append({"_pw_index_reply": reply})
        delta = _delta_from_rows(out_keys, out_diffs, out_rows, ["_pw_index_reply"])
        self.replies.apply(delta)
        return delta


class GradualBroadcastEvaluator(Evaluator):
    """Broadcast a (lower, value, upper) threshold to every row with per-key
    staggering and hysteresis (reference ``gradual_broadcast.rs``): each row's
    ``apx_value`` sits at its own point of the band — apx(k) = lower +
    (upper - lower) * frac(key) — and only re-emits when a threshold update moves
    the band past the row's stored value, so a drifting threshold updates rows
    progressively instead of retracting the whole table each tick."""

    # rows are row-local (apx derives from the row's own key), but the
    # threshold band typically comes from a GLOBAL reduce living on one owner
    # process — replicate it so every process applies the same band
    CLUSTER_POLICIES = {1: "broadcast"}

    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.rows = StateTable(node.inputs[0].column_names())
        self.apx: Dict[bytes, Any] = {}
        self.threshold: tuple | None = None

    @staticmethod
    def _frac(keys: np.ndarray) -> np.ndarray:
        return keys["lo"].astype(np.float64) / float(2**64)

    def _candidate(self, keys: np.ndarray) -> np.ndarray:
        lower, _value, upper = self.threshold
        return lower + (upper - lower) * self._frac(keys)

    def process(self, input_deltas: List[Delta]) -> Delta:
        from pathway_tpu.internals.keys import key_bytes

        rows_delta, thr_delta = input_deltas
        out_parts: List[tuple] = []  # (keys, diffs, cols dict incl apx)

        new_threshold = self.threshold
        if len(thr_delta):
            ins = np.nonzero(thr_delta.diffs > 0)[0]
            if len(ins):
                i = int(ins[-1])
                cfg = self.node.config
                new_threshold = (
                    thr_delta.columns[cfg["lower"]][i],
                    thr_delta.columns[cfg["value"]][i],
                    thr_delta.columns[cfg["upper"]][i],
                )

        def emit(delta: Delta, apx_vals: np.ndarray, sign: int) -> None:
            cols = {c: delta.columns[c] for c in self.rows.column_names}
            cols["apx_value"] = apx_vals
            out_parts.append(
                Delta(delta.keys, np.full(len(delta), sign, dtype=np.int64), cols)
            )

        if len(rows_delta):
            ret = rows_delta.select(rows_delta.diffs < 0)
            if len(ret):
                kbs = key_bytes(ret.keys)
                olds = np.array([self.apx.pop(kb, None) for kb in kbs], dtype=object)
                emit(ret, olds, -1)
            self.rows.apply(rows_delta)
            ins = rows_delta.select(rows_delta.diffs > 0)
            if len(ins):
                if self.threshold is None and new_threshold is None:
                    apx = np.zeros(len(ins), dtype=np.float64)
                else:
                    save, self.threshold = self.threshold, (
                        new_threshold or self.threshold
                    )
                    apx = self._candidate(ins.keys)
                    self.threshold = save
                for kb, a in zip(key_bytes(ins.keys), apx):
                    self.apx[kb] = a
                emit(ins, np.asarray(apx, dtype=np.float64), 1)

        if new_threshold is not None and new_threshold != self.threshold:
            self.threshold = new_threshold
            lower, _value, upper = new_threshold
            snap = self.rows.snapshot()
            if len(snap):
                kbs = key_bytes(snap.keys)
                stored = np.array([self.apx.get(kb) for kb in kbs], dtype=np.float64)
                cand = self._candidate(snap.keys)
                # hysteresis: rows whose stored value still sits inside the new
                # band keep it; only rows the band moved past re-emit
                move = (stored < lower) | (stored > upper)
                move &= stored != cand
                idx = np.nonzero(move)[0]
                if len(idx):
                    moving = snap.select(idx)
                    emit(moving, stored[idx], -1)
                    emit(moving, cand[idx], 1)
                    for i in idx.tolist():
                        self.apx[kbs[i]] = cand[i]

        if not out_parts:
            return Delta.empty(self.output_columns)
        return Delta.concat(out_parts, self.output_columns)


class OutputEvaluator(Evaluator):
    def __init__(self, node: pg.Node, runner: Any):
        super().__init__(node, runner)
        self.callback = node.config.get("callback")
        self.batch_callback = node.config.get("batch_callback")
        self.on_end = node.config.get("on_end")
        self.on_time_end = node.config.get("on_time_end")
        self.on_error = node.config.get("on_error")
        self.input_columns = node.inputs[0].column_names()

    def notify_failure(self, exc: BaseException) -> None:
        """The run is failing: sinks distinguishing failure from a clean end
        (ExportedTable) hear about it before finish() fires their on_end."""
        if self.on_error is not None and not getattr(self, "_on_error_fired", False):
            self._on_error_fired = True
            self.on_error(exc)

    def process(self, input_deltas: List[Delta]) -> Delta:
        (delta,) = input_deltas
        if (
            getattr(self.runner, "_inject", None) is not None
            and not getattr(self.runner, "replay_outputs", True)
        ):
            return Delta.empty([])  # journal replay with silent sinks
        if self.batch_callback is not None and len(delta):
            # vectorized delivery: one call per commit, raw columnar arrays
            self.batch_callback(
                delta.keys,
                delta.diffs,
                {c: delta.columns[c] for c in self.input_columns},
                self.runner.current_time,
            )
        if self.callback is not None and len(delta):
            ptrs = keys_to_pointers(delta.keys)
            time = self.runner.current_time
            names = self.input_columns
            from pathway_tpu.io._utils import columns_to_pylists

            col_map = columns_to_pylists(delta.columns, names)
            cols = [col_map[c] for c in names]
            additions = (delta.diffs > 0).tolist()
            callback = self.callback
            for ptr, is_add, *vals in zip(ptrs, additions, *cols):
                callback(
                    key=ptr, row=dict(zip(names, vals)), time=time, is_addition=is_add
                )
        if self.on_time_end is not None and len(delta):
            # the commit's batch is fully delivered: its time is closed (reference
            # on_time_end markers — AsyncTransformer flushes at time boundaries)
            self.on_time_end(self.runner.current_time)
        return Delta.empty([])

    def notify_stream_end(self) -> None:
        if self.on_end is not None and not getattr(self, "_on_end_fired", False):
            self._on_end_fired = True
            self.on_end()

    def finish(self) -> None:
        self.notify_stream_end()


def _delta_from_rows(
    keys: Any, diffs: List[int], rows: List[dict], column_names: List[str]
) -> Delta:
    if len(rows) == 0:
        return Delta.empty(column_names)
    if isinstance(keys, list):
        if keys and isinstance(keys[0], Pointer):
            keys = pointers_to_keys(keys)
        else:
            arr = np.empty(len(keys), dtype=KEY_DTYPE)
            for i, k in enumerate(keys):
                arr[i] = k
            keys = arr
    columns = {
        name: ee._tidy(objarray([r[name] for r in rows]))
        for name in column_names
    }
    return Delta(keys, np.array(diffs, dtype=np.int64), columns)


def wire_cluster_defaults(cls: type, policy: "str | None" = None) -> None:
    """Install the Evaluator cluster protocol on an evaluator class defined
    outside this module (iterate, row transformers): plumbing defaults plus an
    optional constant input policy for every input (e.g. ``"root"`` to
    centralize). One place to extend when the protocol grows."""
    cls._cluster_policies = ()
    cls._cluster_barrier = False
    cls.CLUSTER_POLICIES = {}
    if policy is None:
        cls.cluster_input_policy = Evaluator.cluster_input_policy
    else:
        cls.cluster_input_policy = lambda self, idx, _p=policy: _p


EVALUATORS: Dict[type, type] = {
    pg.InputNode: InputEvaluator,
    pg.RowwiseNode: RowwiseEvaluator,
    pg.FilterNode: FilterEvaluator,
    pg.ReindexNode: ReindexEvaluator,
    pg.ConcatNode: ConcatEvaluator,
    pg.GroupbyNode: GroupbyEvaluator,
    pg.DeduplicateNode: DeduplicateEvaluator,
    pg.JoinNode: JoinEvaluator,
    pg.UpdateRowsNode: UpdateRowsEvaluator,
    pg.UpdateCellsNode: UpdateCellsEvaluator,
    pg.IntersectNode: IntersectEvaluator,
    pg.DifferenceNode: DifferenceEvaluator,
    pg.RestrictNode: RestrictEvaluator,
    pg.HavingNode: HavingEvaluator,
    pg.WithUniverseOfNode: WithUniverseOfEvaluator,
    pg.FlattenNode: FlattenEvaluator,
    pg.IxNode: IxEvaluator,
    pg.SortNode: SortEvaluator,
    pg.SortedIndexNode: SortedIndexEvaluator,
    pg.RemoveErrorsNode: RemoveErrorsEvaluator,
    pg.AsofNowUpdateNode: AsofNowEvaluator,
    pg.BufferNode: BufferEvaluator,
    pg.ForgetNode: ForgetEvaluator,
    pg.FreezeNode: FreezeEvaluator,
    pg.ExternalIndexNode: ExternalIndexEvaluator,
    pg.GradualBroadcastNode: GradualBroadcastEvaluator,
    pg.OutputNode: OutputEvaluator,
}


def _register_iterate() -> None:
    from pathway_tpu.internals.iterate import IterateEvaluator, IterateResultEvaluator

    EVALUATORS[pg.IterateNode] = IterateEvaluator
    EVALUATORS[pg.IterateResultNode] = IterateResultEvaluator


_register_iterate()
